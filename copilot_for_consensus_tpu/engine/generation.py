"""Continuous-batching LLM generation engine.

The first-party replacement for the blocking single-request HTTP call the
reference makes per summary (``local_llm_summarizer.py:106-115`` — "THE
DOMINANT LATENCY" in SURVEY.md §3.2). Design:

* **Slot batch.** The decode state is a fixed batch of ``num_slots``
  sequences with a shared KV cache ``[L, slots, Hkv, max_len, Dh]``.
  Every decode step advances *all* active slots in one fused program —
  requests join and leave the batch without recompilation (continuous
  batching, the vLLM/Orca scheduling model, built TPU-style with static
  shapes).
* **Prefill/decode disaggregation.** Prompts are prefetched through a
  bucketed prefill (padded to the next bucket so XLA sees a handful of
  shapes), then their kv block is inserted into a free slot; decode is a
  single [slots]-wide matvec-bound step.
* **Sharding.** Params shard over the mesh per ``models.decoder
  .logical_axes`` (tp over heads/ffn/vocab); the cache shards its slot
  axis over dp and kv-head axis over tp. Collectives are emitted by XLA.
* **Prefix KV-cache reuse** (``prefix_cache_blocks`` > 0): a radix trie
  over token-block hashes maps each prompt's longest cached prefix to
  device-resident KV blocks; admission seeds the slot cache from the
  pool and prefills only the suffix, and completions publish their
  prompt-prefix blocks back. Design: ``docs/ENGINE_PREFIX_CACHE.md``.
* **Speculative decoding** (``spec_decode=True``): decode is pinned at
  the HBM weight-read wall (docs/PERF.md r3), so the only way past it
  is more tokens per weight pass. A host-side prompt-lookup n-gram
  index per stream (``tokenizer.NgramDraftIndex``) drafts copied
  spans from the stream's own context for free, and one ``_verify``
  dispatch — a short seeded prefill over the decode slots — scores
  k+1 positions per stream in a single weight pass, accepting exactly
  (greedy bit-identical; sampled via the rejection rule in
  ``sampling.verify_draft``). Design: ``docs/SPEC_DECODE.md``.

The engine is synchronous and single-owner: services drive it through
``submit()`` + ``step()`` (or ``generate()`` for batch use) from their
consumer thread, mirroring how the reference's summarization service owns
its single LLM connection.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    HloSpec,
    checkable,
)
from copilot_for_consensus_tpu.engine.faults import (
    InjectedFault,
    resolve_faults,
)
from copilot_for_consensus_tpu.engine.sampling import (
    SamplingConfig,
    sample,
    verify_draft,
)
from copilot_for_consensus_tpu.engine.scheduler import (
    jain_index,
    resolve_scheduler,
)
from copilot_for_consensus_tpu.engine.journal import resolve_journal
from copilot_for_consensus_tpu.engine.telemetry import resolve_telemetry
from copilot_for_consensus_tpu.engine.tokenizer import (
    NgramDraftIndex,
    Tokenizer,
)
from copilot_for_consensus_tpu.obs.profile import step_annotation
from copilot_for_consensus_tpu.models import decoder, quant
from copilot_for_consensus_tpu.models.configs import DecoderConfig
from copilot_for_consensus_tpu.parallel.sharding import (
    DEFAULT_RULES,
    serving_param_rules,
    shard_pytree,
)

try:  # jax.sharding only needed when a mesh is provided
    from jax.sharding import Mesh
except Exception:  # pragma: no cover
    Mesh = Any  # type: ignore


@dataclass
class Request:
    request_id: int
    prompt: list[int]
    max_new_tokens: int
    submitted_at: float = field(default_factory=time.monotonic)
    decode_started_at: float = 0.0
    #: prefix-cache publish cap: how many LEADING prompt tokens may be
    #: published to the shared block pool on completion (None = whole
    #: prompt, 0 = never publish this request). Lookup/reuse is always
    #: unrestricted — this only bounds what the request contributes.
    cache_eligible_tokens: int | None = None
    #: memoized block digests (PrefixCache.prompt_digests) — the
    #: admission router re-checks every queued request every step, and
    #: hashing is the only per-token host cost on that path
    block_digests: list | None = None
    #: pipeline correlation id, carried end-to-end through the
    #: request's telemetry span and into flight-recorder dumps / error
    #: reports (engine/telemetry.py)
    correlation_id: str = ""
    #: multi-tenant scheduling (engine/scheduler.py): the fairness key
    #: ("" = the anonymous/default tenant) and the priority lane
    #: (interactive > batch; batch sheds first under SLO pressure)
    tenant: str = ""
    priority: str = "interactive"
    #: absolute monotonic deadline (engine/supervisor.py policy):
    #: expired work is DROPPED (finish_reason="deadline"), never
    #: computed — queued requests at step start, active slots at
    #: harvest. inf = no deadline.
    deadline_at: float = float("inf")


@dataclass
class Completion:
    request_id: int
    prompt_len: int
    tokens: list[int]
    finish_reason: str            # "eos" | "length" | "deadline"
    prefill_s: float = 0.0
    decode_s: float = 0.0


@dataclass
class PrefilledHandoff:
    """One finished prefill exported for the disaggregated KV handoff
    (prefill-role → decode-role). ``kv_k``/``kv_v`` are the slot's
    pool blocks gathered dense ``[L, 1, Hkv, NBpad*block, Dh]`` —
    device arrays, moved device-to-device by the importing engine's
    ``jax.device_put`` onto its own mesh; only the first
    ``prompt_len`` columns are live. The refcount story: the source
    slot's pins/blocks were released at export (after the shard trie
    adopted the prompt prefix), and the importing engine allocates
    fresh blocks whose sole owner is the new slot — ownership moves,
    never aliases."""

    request: Request
    first_token: int
    prompt_len: int
    kv_k: Any
    kv_v: Any
    blocks: int                   # live (un-padded) block count
    ready_at: float               # monotonic: when the prefill parked
    prefill_s: float = 0.0


def _next_bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


_KV_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "float32": jnp.float32,
    "float8_e4m3fn": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
}


def resolve_kv_dtype(kv_dtype, default):
    """One place to accept/validate the kv cache dtype (config strings
    included) — a typo'd config key must fail here with the valid set,
    not as an opaque AttributeError deep in init_cache."""
    if not kv_dtype:          # None or "" (the schema default) = unset
        return default
    if isinstance(kv_dtype, str):
        try:
            return _KV_DTYPES[kv_dtype]
        except KeyError:
            raise ValueError(
                f"unknown kv_dtype {kv_dtype!r}; one of "
                f"{sorted(_KV_DTYPES)}") from None
    return kv_dtype


def _host_fetch(x) -> "np.ndarray":
    """Device→host for a program output that may be sharded across
    PROCESSES (multi-controller serving: dp shards the slot axis over
    ranks). ``device_get`` only works on fully-addressable arrays; a
    cross-process shard is all-gathered through the distributed
    runtime so every rank harvests the same full token block — which
    the SPMD lockstep requires anyway (each rank must observe the same
    retirements/admissions)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(jax.device_get(x))


class GenerationEngine:
    """Continuous-batching decoder serving. One instance per process/slice."""

    def __init__(
        self,
        cfg: DecoderConfig,
        params: Any | None = None,
        *,
        mesh: "Mesh | None" = None,
        num_slots: int = 8,
        max_len: int = 1024,
        prefill_buckets: tuple[int, ...] = (64, 128, 256, 512, 1024),
        sampling: SamplingConfig = SamplingConfig(),
        eos_id: int = 2,
        seed: int = 0,
        dtype=jnp.bfloat16,
        kv_dtype=None,
        attn_impl: str = "auto",
        quantize: bool | str = False,
        decode_window: int = 8,
        windows_per_dispatch: int = 1,
        admission_token_budget: int = 16384,
        admit_min_rows: int = 1,
        admit_max_wait_s: float = 0.5,
        prefill_chunk: int = 64,
        prefill_rows: int = 4,
        piggyback_min_prompt: int = 10**9,
        admit_hold_strict: bool = False,
        prefix_cache_blocks: int = 0,
        kv_pool_blocks: int = 0,
        kv_kernel: str = "auto",
        role: str = "both",
        handoff_high: int = 0,
        spec_decode: bool = False,
        spec_draft_lens: tuple[int, ...] = (0, 4, 8),
        spec_ngram: int = 3,
        spec_min_ngram: int = 2,
        profile_dir: str | None = None,
        int4_pallas_max_extent: int | None = 1536,
        telemetry: Any = True,
        scheduler: Any = None,
        faults: Any = None,
        journal: Any = None,
    ):
        self.profile_dir = profile_dir
        # Resilience plane (engine/faults.py + engine/supervisor.py;
        # docs/RESILIENCE.md): ``faults`` installs a deterministic
        # seeded fault injector at every host dispatch boundary
        # (``_dispatch_boundary`` — never inside jitted code); a
        # supervisor (attached by EngineSupervisor/AsyncEngineRunner)
        # gets watchdog begin/end + success/failure callbacks from the
        # same boundary, and may lower ``_slot_cap`` (resource breaker)
        # or veto the verify dispatch (spec breaker).
        self.faults = resolve_faults(faults)
        # Durable request journal (engine/journal.py;
        # docs/RESILIENCE.md#process-lifecycle): submits journal before
        # admission, accepted tokens checkpoint incrementally, retire
        # deletes — and a non-empty journal at construction warm-
        # restarts: unfinished requests resubmit as prompt+generated
        # continuations (the PR-7 replay identity: greedy bit-identical
        # at f32), so a serving-process SIGKILL costs latency, not work.
        self.journal = resolve_journal(journal)
        #: journal rows resubmitted at warm restart this process
        self.journal_replayed = 0
        #: journal rows that could NOT be resumed (continuation past
        #: prompt_limit) — honest loss accounting, never silent
        self.journal_abandoned = 0
        #: (new rid, correlation_id) pairs recovered at construction —
        #: callers that want to harvest/publish recovered completions
        #: read this to re-attach identities (the journal storm driver)
        self.journal_recovered: list[tuple[int, str]] = []
        #: rid → (original prompt_len, resumed token prefix): stitched
        #: back onto the continuation's completion at harvest
        self._journal_stitch: dict[int, tuple[int, list[int]]] = {}
        #: rid → generated-token count at last checkpoint (lag gauge)
        self._journal_ckpt: dict[int, int] = {}
        self._journal_steps = 0
        self._journal_recovering = False
        #: True while a resubmission's row is provided by an ATOMIC
        #: journal.supersede re-key instead of record_submit — the
        #: journal must never hold two live rows for one request
        self._journal_suppress = False
        self.supervisor: Any = None
        self._last_failed_kind = ""
        self._slot_cap = num_slots
        #: requests dropped un-computed because deadline_at passed
        self.deadline_expired = 0
        #: set the first time a deadline_s submit arrives — the
        #: per-step expiry sweep walks every queue, so engines that
        #: never see a deadline skip it entirely (hot-path economy)
        self._deadlines_in_use = False
        #: contained prefix-publish failures (completion still
        #: delivered; only the cache contribution was lost)
        self.prefix_publish_failures = 0
        # Flight recorder + request-lifecycle spans + Prometheus export
        # (engine/telemetry.py). Default ON: pure host-side bookkeeping
        # around dispatches the engine already syncs on (<1% measured —
        # docs/OBSERVABILITY.md). False disables; an EngineTelemetry or
        # MetricsCollector shares a collector across engines/services.
        self.telemetry = resolve_telemetry(telemetry, engine="generation",
                                           num_slots=num_slots)
        self.cfg = cfg
        self.mesh = mesh
        self.num_slots = num_slots
        self.max_len = min(max_len, cfg.max_seq_len)
        self.buckets = tuple(
            b for b in sorted(set(min(b, self.max_len)
                                  for b in prefill_buckets)))
        self.sampling = sampling
        # eos_id may be a list (Llama-3.1-style multi-EOS checkpoints).
        eos_list = list(eos_id) if isinstance(eos_id, (list, tuple)) \
            else [int(eos_id)]
        self.eos_id = int(eos_list[0])
        self._eos_set = frozenset(int(e) for e in eos_list)
        self.attn_impl = attn_impl
        self.decode_window = max(1, decode_window)
        # How many windows one dispatch chains in-program. >1 amortizes
        # the host↔device sync (expensive over the tunnel) at the cost
        # of coarser retirement/admission granularity — right for batch
        # workloads, 1 for latency-sensitive serving.
        self.windows_per_dispatch = max(1, windows_per_dispatch)
        # Prompt tokens one admission wave may prefill: the wave's f32
        # swiglu transient is budget×d_ff×8 bytes (~0.9 GB at 16k), so
        # long-context engines (big caches) trade admission batching
        # for HBM headroom by lowering this.
        self.admission_token_budget = admission_token_budget
        # Wave hysteresis for continuous arrivals: a prefill wave costs
        # a full weight pass + pow-2 row padding regardless of size, so
        # trickling arrivals amortize badly as 1-2-row waves. With
        # admit_min_rows > 1 the engine keeps decoding until that many
        # requests accumulate (or the oldest has waited admit_max_wait_s,
        # or the batch is fully drained) and admits them as one wave.
        self.admit_min_rows = max(1, admit_min_rows)
        self.admit_max_wait_s = admit_max_wait_s
        #: strict hold: apply the admit_min_rows hysteresis even when
        #: many slots are free. Bigger waves amortize the weight pass
        #: better (measured 9.9k vs 7k prompt tok/s at 64- vs 33-row
        #: waves); under heavy continuous load the idle-slot bypass
        #: defeats the batching, so load-oriented deployments set this.
        self.admit_hold_strict = admit_hold_strict
        # Chunked-prefill piggybacking: prompts in
        # [piggyback_min_prompt, decode_window*prefill_chunk] skip the
        # monolithic admission wave and ride the decode dispatches,
        # prefill_chunk tokens per decode step across prefill_rows
        # packed lanes — prefill FLOPs overlapping the bandwidth-bound
        # decode stream. OPT-IN (default off): on this toolchain the
        # piggyback program's structural costs (static P*C row padding
        # in every matmul, ~65 µs per pallas call, scan-carry buffer
        # rematerialization, no donation aliasing) measured above the
        # overlap gain in every serving shape tried — an EMPTY chunk
        # grid added +1.0 s to a 0.78 s dispatch — so the wave path
        # stays the default. The machinery is kept correct (oracle
        # tests vs the wave path) for backends where dispatch is
        # cheaper; full measurements in docs/PERF.md (r4 study).
        # Requires single-window dispatches and a dense model with no
        # sliding window narrower than the cache.
        self.prefill_chunk = max(1, prefill_chunk)
        self.prefill_rows = max(1, prefill_rows)
        self.piggyback_min_prompt = piggyback_min_prompt
        self._piggyback_ok = (
            self.windows_per_dispatch == 1 and not cfg.is_moe
            and (cfg.sliding_window == 0
                 or cfg.sliding_window >= self.max_len))
        self._prefilling: list[tuple[Request, float]] = []  # packer feed
        self._dispatch_steps = self.decode_window * self.windows_per_dispatch
        if self.max_len - self._dispatch_steps < 1:
            raise ValueError(
                f"decode_window {self.decode_window} x "
                f"{self.windows_per_dispatch} windows/dispatch leaves no "
                f"prompt room in max_len {self.max_len}")
        self._key = jax.random.PRNGKey(seed)

        # quantize: False | True ("int8") | "int8" | "int4". int4 packs
        # two nibbles per byte with group-wise scales — half the weight
        # HBM (and decode weight traffic) of int8 again.
        qmode = ("int8" if quantize is True else quantize) or None
        if qmode not in (None, "int8", "int4"):
            raise ValueError(f"unknown quantize mode {qmode!r}")
        self.quant_mode = qmode
        axes = decoder.logical_axes(cfg)
        if params is None:
            if qmode:
                params = quant.init_random_quantized(
                    jax.random.PRNGKey(seed), cfg, dtype=dtype, mode=qmode)
            else:
                params = decoder.init_params(jax.random.PRNGKey(seed), cfg,
                                             dtype=dtype)
        if qmode and mesh is not None:
            # The fused Pallas quant kernels are not GSPMD-partitionable
            # yet; sharded engines fall back to the XLA dequant
            # expression, which partitions naturally over tp.
            quant.set_pallas_qmatmul(False)
        if params is not None and qmode and not quant.is_quantized(
                params.get("layers", {}).get("wq")):
            # Caller provided full-precision weights: quantize on the fly.
            # (Real checkpoints should be quantized offline on the host —
            # this transient needs both copies in memory.)
            params = quant.quantize_params(params, mode=qmode)
        if qmode:
            axes = quant.quantize_logical_axes(axes, mode=qmode)
        # Long-extent int4 decode auto-route (r4 verdict, Weak 3): the
        # Pallas int4 decode path degrades far beyond its byte count at
        # long kv extents (measured 136 ms/step at 3072 vs the ~30 ms
        # bytes floor), exactly the capacity configuration int4 exists
        # for. Above this extent the DECODE program is traced with the
        # XLA dequant expression instead (thread-local override around
        # the decode dispatch; admission keeps the global route — the
        # prefill wave is MXU-bound and unaffected). None disables.
        self._decode_pallas_override: bool | None = None
        if (qmode == "int4" and int4_pallas_max_extent is not None
                and self.max_len > int4_pallas_max_extent
                and quant.pallas_qmatmul_enabled()):
            self._decode_pallas_override = False
        if (qmode == "int4" and mesh is None and not cfg.is_moe
                and quant.pallas_qmatmul_enabled()
                and jax.default_backend() == "tpu"):
            # Fused qkv / gate+up leaves: 4 Pallas calls per layer
            # instead of 7 — per-call overhead (~65 µs) is what erased
            # int4's halved-byte advantage. Single-chip serving only
            # (no sharding rules for the fused leaves). The fused
            # leaves stay compatible with the XLA dequant route (the
            # decode override above): int4_matmul_xla unpacks the same
            # packed layout.
            params = quant.fuse_int4_projections(params)
        if mesh is not None:
            # shard_pytree device_puts numpy leaves shard-by-shard, so a
            # host-resident (mmap'd) checkpoint never fully materializes
            # on one device. Head-structured axes tp does not divide
            # replicate instead of splitting within head_dim
            # (serving_param_rules — the PR-15 root cause of the mesh
            # bit-identity failure).
            params = shard_pytree(params, axes, mesh,
                                  serving_param_rules(cfg, mesh))
        else:
            params = jax.tree.map(jnp.asarray, params)
        self.params = params

        # kv_dtype below activation dtype (float8_e4m3fn) halves cache
        # HBM, doubling the slot count a chip fits — decode throughput is
        # weight-bandwidth-bound so tokens/step scales with slots. e4m3's
        # dynamic range covers KV activations; no per-tensor scales kept.
        self.kv_dtype = resolve_kv_dtype(kv_dtype, dtype)

        # ---- paged KV (kv_pool_blocks > 0): one block pool under
        # admission, decode, verify and chunked prefill ----------------
        # Slots stop reserving max_len columns each; positions map onto
        # pool blocks through per-slot block tables, blocks allocate on
        # demand, prefix hits are pointer handoffs, and the slot
        # ceiling lifts to whatever the pool holds. The contiguous
        # per-slot cache below is NOT allocated. Design: docs/
        # ENGINE_PREFIX_CACHE.md ("Paged KV") + ops/paged_attention.py.
        self.paged = bool(kv_pool_blocks)
        self._pool = None
        # Dispatch-route knob for the paged layout: the Pallas paged
        # kernel reads pool blocks IN PLACE by scalar-prefetched block
        # table (no working-set gather materializes), the XLA
        # reference route gathers the view the tables describe. "auto"
        # picks the kernel on TPU and the reference elsewhere (the
        # kernel still RUNS off-TPU via interpret mode — that is what
        # the parity gate exercises — but interpreted Pallas is not a
        # serving route). Explicit values pin a route for parity
        # tests and benches.
        if kv_kernel not in ("auto", "pallas", "reference"):
            raise ValueError(
                f"kv_kernel must be 'auto', 'pallas' or 'reference', "
                f"got {kv_kernel!r}")
        if kv_kernel != "auto" and not self.paged:
            raise ValueError(
                "kv_kernel selects the paged-attention dispatch route "
                "and requires kv_pool_blocks > 0")
        self.kv_kernel = kv_kernel
        if self.paged:
            from copilot_for_consensus_tpu.ops.paged_attention import (
                HAS_PALLAS,
            )
            if kv_kernel == "pallas" and not HAS_PALLAS:
                raise ValueError(
                    "kv_kernel='pallas' requires jax.experimental."
                    "pallas in this jax build")
            #: resolved dispatch route, labeled on every StepRecord
            #: and the copilot_engine_kv_route gauge ("" = contiguous)
            self._kv_route = "kernel" if (
                kv_kernel == "pallas"
                or (kv_kernel == "auto" and HAS_PALLAS
                    and jax.default_backend() == "tpu")) \
                else "reference"
            if self.telemetry is not None:
                self.telemetry.gauge_kv_route(self._kv_route)
        else:
            self._kv_route = ""
        # Disaggregated serving role (engine/roles.py): "both" is the
        # co-located default; "prefill" parks finished prefills for a
        # block-granular KV handoff instead of decoding them, "decode"
        # additionally accepts handed-off timelines via
        # ``admit_prefilled``. Roles ride the paged layout — the block
        # pool IS the handoff substrate.
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be 'both', 'prefill' or 'decode', "
                f"got {role!r}")
        if role != "both" and not self.paged:
            raise ValueError(
                "prefill/decode roles require kv_pool_blocks: the "
                "block-granular KV handoff moves pool blocks, not "
                "contiguous slot caches")
        self.role = role
        #: slot → (request, first_token, prompt_len, ready_at) parked
        #: for handoff (prefill role); the slot's blocks keep the
        #: prompt KV until ``take_prefilled`` exports them
        self._handoff: dict[int, tuple] = {}
        self.handoff_exported = 0
        self.handoff_imported = 0
        #: release hold: the prefill role stops releasing scheduler
        #: waves when this many finished prefills await handoff
        #: (parked here + exported-but-unadmitted, reported by the
        #: wrapper via set_handoff_external — decode is the
        #: bottleneck; prefilling further ahead only pins pool
        #: blocks). Parked entries are slot-keyed so they cap at
        #: num_slots: the default fires at HALF the slots parked,
        #: which is reachable, not ornamental.
        self._handoff_high = int(handoff_high) or max(1,
                                                      num_slots // 2)
        #: handoffs exported but not yet admitted downstream (the
        #: DisaggregatedEngine reports its pending-queue depth here so
        #: the backlog signal covers the whole handoff pipeline)
        self._handoff_external = 0
        #: dp degree of the paged layout (1 when unsharded) — the
        #: block pool's shard count and the slot partition
        self._dp = 1
        self._slots_ps = num_slots
        if self.paged:
            from copilot_for_consensus_tpu.engine.kv_pool import (
                BlockPool,
            )
            if mesh is not None:
                # Sharded paged serving: dp splits the BLOCK axis (and
                # the slot partition), tp splits kv-heads inside each
                # block (replicated when indivisible). Axes beyond
                # dp×tp have no paged dispatch plumbing yet.
                for ax in ("pp", "sp", "ep"):
                    if mesh.shape.get(ax, 1) != 1:
                        raise ValueError(
                            f"kv_pool_blocks shards over dp×tp only; "
                            f"mesh has {ax}={mesh.shape[ax]}")
                self._dp = int(mesh.shape["dp"])
                if num_slots % self._dp:
                    raise ValueError(
                        f"kv_pool_blocks on a mesh requires num_slots "
                        f"({num_slots}) divisible by dp ({self._dp}): "
                        f"slots partition over the dp shards")
                self._slots_ps = num_slots // self._dp
            block = self.prefill_chunk
            if 128 % block:
                raise ValueError(
                    f"kv_pool_blocks requires prefill_chunk (the block "
                    f"size) to divide 128, got {block}: decode kv "
                    f"extents bucket to 128-aligned widths and every "
                    f"bucket must be block-aligned")
            if self.max_len % block:
                raise ValueError(
                    f"kv_pool_blocks requires max_len % prefill_chunk "
                    f"== 0, got {self.max_len} % {block}")
            self._block = block
            self._max_blocks = self.max_len // block
            #: per-dispatch write margin: a decode window, a verify
            #: wave, or a chunk continuation never writes further than
            #: this past a slot's committed length
            self._write_margin = max(
                self._dispatch_steps,
                max(spec_draft_lens, default=0) + 1)
            #: worst-case blocks one slot can ever hold (the free-block
            #: admission accounting's unit)
            if kv_pool_blocks < (self._max_blocks + 1) * self._dp:
                raise ValueError(
                    f"kv_pool_blocks={kv_pool_blocks} cannot hold even "
                    f"one max_len={self.max_len} slot "
                    f"({self._max_blocks} blocks) plus headroom per "
                    f"dp shard (dp={self._dp})")
            self._pool = BlockPool(cfg, num_blocks=kv_pool_blocks,
                                   block_size=block,
                                   kv_dtype=self.kv_dtype, mesh=mesh)
            #: slot → block table (pool block ids, position p lives at
            #: table[p // block] offset p % block) and the index where
            #: OWNED blocks start (entries before it are BORROWED from
            #: the prefix trie — shared, read-only, pinned via the
            #: request's PrefixMatch until retire)
            self._tables: list[list[int]] = [[] for _ in range(num_slots)]
            self._owned_from: list[int] = [0] * num_slots
            #: zero-copy admission ledger: seeded admits that appended
            #: matched block ids instead of gathering pool→slot copies
            self.zero_copy_admits = 0
            self.paged_admits = 0
            #: high-water mark of concurrently active streams
            self.peak_active = 0
            # Piggyback packing binds rows to contiguous slot-cache
            # spans; the paged layout serves the same overlap goal via
            # chunked prefill, so the (default-off) path stays off.
            self._piggyback_ok = False
            self._cache = None
        else:
            cache = decoder.init_cache(cfg, num_slots, self.max_len,
                                       dtype=self.kv_dtype)
            if mesh is not None:
                # Replicate cache axes the mesh doesn't divide (e.g. tp
                # larger than the kv-head count — standard GQA serving
                # replicates kv).
                rules = dict(DEFAULT_RULES)
                if cfg.n_kv_heads % mesh.shape["tp"]:
                    rules["kv_heads"] = None
                if num_slots % mesh.shape["dp"]:
                    rules["batch"] = None
                cache = shard_pytree(cache, decoder.cache_logical_axes(),
                                     mesh, rules)
            self._cache = cache

        # ---- jitted programs -------------------------------------------
        impl = attn_impl

        def _insert_batch(cache, pref, slots):
            """Insert N prefilled kv blocks into their slots in one
            program. ``slots`` may contain out-of-range ids for padded
            prefill rows — 'drop' mode discards those updates."""
            s = pref["k"].shape[3]
            k = cache["k"].at[:, slots, :, :s, :].set(
                pref["k"].astype(cache["k"].dtype), mode="drop")
            v = cache["v"].at[:, slots, :, :s, :].set(
                pref["v"].astype(cache["v"].dtype), mode="drop")
            return {"k": k, "v": v}

        def _admit_fused(params, tokens, lengths, cache, slots, key):
            """Prefill + cache insert + first-token sample as ONE
            program — one dispatch and one sync per admission wave.
            The prefill scratch is born in the serving cache dtype:
            prefill attention uses the fresh bf16 k/v, the scratch only
            ferries them to the insert, and a bf16 scratch at full
            admission width was the largest admission-path transient
            (4.3 GB for 256×128 tokens)."""
            scratch = decoder.init_cache(cfg, tokens.shape[0],
                                         tokens.shape[1],
                                         dtype=self.kv_dtype)
            logits, scratch = decoder.prefill(params, tokens, lengths,
                                              cfg, scratch,
                                              attn_impl=impl)
            cache = _insert_batch(cache, scratch, slots)
            first = sample(logits, key, self.sampling)
            return first, cache

        self._admit_fn = jax.jit(_admit_fused, donate_argnums=(3,))

        # ---- prefix KV cache (cross-request reuse) ---------------------
        # Radix trie + device block pool (engine/prefix_cache.py). On a
        # hit the admission wave gathers the reused blocks from the
        # pool, scatters them into the slot's cache prefix, and
        # prefills ONLY the suffix — TTFT and admission FLOPs drop by
        # the shared-prefix fraction. Block size = prefill_chunk.
        #: one radix trie per dp shard (a zero-copy hit appends POINTERS
        #: into the slot's own shard's pool slice, so cached prefixes
        #: are shard-local by construction; dp=1 = one trie, the
        #: original design). ``_prefix`` below is the single-shard
        #: compatibility view.
        self._prefixes: list[Any] = []
        self._prefix_pins: dict[int, Any] = {}   # request_id → PrefixMatch
        #: prompt tokens actually prefilled / skipped via prefix reuse —
        #: the bench's savings accounting (prefix_stats()).
        self.prefill_tokens = 0
        self.prefill_tokens_saved = 0
        if prefix_cache_blocks:
            if mesh is not None and not self.paged:
                raise ValueError(
                    "prefix_cache_blocks on a mesh requires the paged "
                    "engine (kv_pool_blocks): the contiguous block "
                    "pool and a dp-sharded slot cache would live on "
                    "different shards; the paged pool shards WITH its "
                    "per-shard tries")
            if cfg.sliding_window and cfg.sliding_window < self.max_len:
                raise ValueError(
                    "prefix_cache_blocks requires full attention: a "
                    "reused prefix under a sliding window needs "
                    "absolute-timeline window masking the seeded "
                    "prefill path does not implement")
            from copilot_for_consensus_tpu.engine.prefix_cache import (
                PrefixCache,
            )
            # Paged engines share ONE pool between active slots and the
            # trie (prefix_cache_blocks acts as an enable flag; the
            # budget is kv_pool_blocks): publish is an adopt_blocks
            # refcount handoff, hits are pointer admissions.
            self._prefixes = [
                PrefixCache(
                    cfg, num_blocks=prefix_cache_blocks,
                    block_size=self.prefill_chunk,
                    kv_dtype=self.kv_dtype,
                    shared=self._pool if self.paged else None)
                for _ in range(self._dp if self.paged else 1)]

        def _admit_seeded(params, tokens, lengths, pool_k, pool_v,
                          bids_flat, pref_lens, cache, slots, key):
            """Admission wave with prefix-cache hits: gather reused
            blocks from the pool, seed them into the slot cache, prefill
            only the suffix (RoPE/attention offset by pref_lens), insert
            the suffix KV at the per-row offset, sample first tokens —
            still ONE program and one host sync per wave.

            tokens: [N, Sbuc] right-padded suffixes; bids_flat: [N*NB]
            pool block ids row-major (pad = pool size → gather clamps,
            scatter drops); pref_lens: [N] matched prefix tokens (0 =
            miss row — the same program serves mixed waves)."""
            n_l, hkv, dh = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
            n, sbuc = tokens.shape
            nb = bids_flat.shape[0] // n
            blk = pool_k.shape[3]
            pk_flat = pool_k[:, bids_flat]     # [L, N*NB, Hkv, B, Dh]
            pv_flat = pool_v[:, bids_flat]
            pk = pk_flat.reshape(n_l, n, nb, hkv, blk, dh).transpose(
                0, 1, 3, 2, 4, 5).reshape(n_l, n, hkv, nb * blk, dh)
            pv = pv_flat.reshape(n_l, n, nb, hkv, blk, dh).transpose(
                0, 1, 3, 2, 4, 5).reshape(n_l, n, hkv, nb * blk, dh)
            scratch = decoder.init_cache(cfg, n, sbuc,
                                         dtype=self.kv_dtype)
            logits, scratch = decoder.prefill_seeded(
                params, tokens, lengths, pk, pv, pref_lens, cfg,
                scratch)
            # seed the reused prefix blocks into the slot cache: block
            # j of row i lands at positions [j*blk, (j+1)*blk) of
            # slots[i]; pad entries (OOB bid) get an OOB slot and drop.
            m = n * nb
            valid = bids_flat < pool_k.shape[1]
            sidx_b = jnp.where(valid, jnp.repeat(slots, nb),
                               self.num_slots)
            sidx_b = jnp.broadcast_to(sidx_b[:, None], (m, blk))
            pidx_b = (jnp.tile(jnp.arange(nb), n) * blk)[:, None] \
                + jnp.arange(blk)[None, :]
            upd_k = pk_flat.transpose(1, 3, 0, 2, 4)  # [M, B, L, H, D]
            upd_v = pv_flat.transpose(1, 3, 0, 2, 4)
            ck = cache["k"].at[:, sidx_b, :, pidx_b, :].set(
                upd_k.astype(cache["k"].dtype), mode="drop")
            cv = cache["v"].at[:, sidx_b, :, pidx_b, :].set(
                upd_v.astype(cache["v"].dtype), mode="drop")
            # insert the fresh suffix KV at the per-row prefix offset
            sidx_s = jnp.broadcast_to(slots[:, None], (n, sbuc))
            pidx_s = pref_lens[:, None] + jnp.arange(sbuc)[None, :]
            ck = ck.at[:, sidx_s, :, pidx_s, :].set(
                scratch["k"].transpose(1, 3, 0, 2, 4).astype(ck.dtype),
                mode="drop")
            cv = cv.at[:, sidx_s, :, pidx_s, :].set(
                scratch["v"].transpose(1, 3, 0, 2, 4).astype(cv.dtype),
                mode="drop")
            first = sample(logits, key, self.sampling)
            return first, {"k": ck, "v": cv}

        self._admit_seeded_fn = jax.jit(_admit_seeded,
                                        donate_argnums=(7,))

        def _decode(params, tokens, positions, cache, key, *, kv_len,
                    n_windows=1):
            """``n_windows × decode_window`` steps fused in one program:
            decode → sample → feed back, all on-device. One dispatch and
            one host sync per program — the difference between
            dispatch-bound and HBM-bound decode (per-step dispatch
            measured 839 tok/s vs 2778 fused; the axon tunnel makes
            every dispatch/sync expensive, which is also why n_windows
            exists: chaining windows IN-program amortizes the sync
            without growing the window buffers).

            The big KV cache stays OUT of the inner scan carry: a
            per-step carried cache is re-materialized by XLA every token
            (~2× cache bytes — measured 2778→1841 tok/s going max_len
            256→512 with identical attended work, before this design).
            Fresh KV accumulates in small [L, B, Hkv, W, Dh] window
            buffers and merges into the cache once per window; only the
            OUTER per-window scan carries the cache, so its
            re-materialization amortizes over ``decode_window`` steps.
            ``kv_len`` (static, bucketed by the caller) bounds the cache
            prefix attention reads and must cover all n_windows."""
            w_sz = self.decode_window
            n_l = cfg.n_layers
            b = tokens.shape[0]
            shape = (n_l, b, cfg.n_kv_heads, w_sz, cfg.head_dim)

            def run_window(tok, key, done):
                k_win = jnp.zeros(shape, self.kv_dtype)
                v_win = jnp.zeros(shape, self.kv_dtype)
                k_done, v_done = done

                def body(carry, w):
                    tok, k_win, v_win, key = carry
                    key, sub = jax.random.split(key)
                    logits, k_cols, v_cols = decoder.decode_step_windowed(
                        params, tok, positions, w, cfg, cache, k_win,
                        v_win, kv_len=kv_len, k_done=k_done,
                        v_done=v_done)
                    # k_cols: [L, B, H, D] → window col [L, B, H, 1, D]
                    k_win = jax.lax.dynamic_update_slice_in_dim(
                        k_win, k_cols[:, :, :, None].astype(k_win.dtype),
                        w, axis=3)
                    v_win = jax.lax.dynamic_update_slice_in_dim(
                        v_win, v_cols[:, :, :, None].astype(v_win.dtype),
                        w, axis=3)
                    nxt = sample(logits, sub, self.sampling)
                    return (nxt, k_win, v_win, key), nxt

                (tok, k_win, v_win, key), toks = jax.lax.scan(
                    body, (tok, k_win, v_win, key), jnp.arange(w_sz))
                return tok, key, toks, k_win, v_win

            # Chain windows WITHOUT touching the big cache in between:
            # completed windows ride along as a fourth attention piece
            # (k_done) and everything merges once at the end. Merging
            # per window makes the cache a loop variable, which XLA
            # ping-pong double-buffers — a second full cache allocation
            # (+2x at 128x512 fp8: the r2 "compile crash" at kv extents
            # > 256 was this OOM). Here the cache stays a read-only
            # invariant until the single final scatter.
            tok, done, outs, wins = tokens, (None, None), [], []
            for widx in range(n_windows):
                tok, key, toks, k_win, v_win = run_window(tok, key, done)
                outs.append(toks)
                wins.append((k_win, v_win))
                if widx + 1 < n_windows:
                    done = (jnp.concatenate([kw for kw, _ in wins], 3),
                            jnp.concatenate([vw for _, vw in wins], 3))
            if n_windows == 1:
                k_all, v_all = wins[0]
                toks_all = outs[0]
            else:
                k_all = jnp.concatenate([kw for kw, _ in wins], 3)
                v_all = jnp.concatenate([vw for _, vw in wins], 3)
                toks_all = jnp.concatenate(outs, axis=0)
            cache = decoder.merge_window(cache, k_all, v_all, positions,
                                         steps=n_windows * w_sz)
            return toks_all, cache      # toks: [windows*w_sz, slots]

        self._decode_fn = jax.jit(_decode, donate_argnums=(3,),
                                  static_argnames=("kv_len", "n_windows"))

        def _decode_piggyback(params, tokens, positions, cache, key,
                              pre_tokens, pre_rope_base, pre_kv_begin,
                              pre_kv_len, pre_sel_rel, pre_sel_w,
                              pre_sel_p, pre_sidx, pre_pidx, *, kv_len):
            """One decode window where every step also prefills C-token
            chunks for P packed lanes (chunked-prefill piggybacking;
            see ``decoder.decode_step_piggyback``). All packing
            metadata is host-built (``_pack_prefill``): per-step arrays
            [W, P] scan alongside the step index; the completion list
            (sel_w, sel_p — up to W*P rows may finish per dispatch) and
            the buffer→cache scatter maps are dispatch-level. Chunk KV
            accumulates in dispatch buffers carried like the decode
            window buffers and merges into the cache once; first tokens
            for every completed row are sampled at the end from the
            gathered last-position hidden states."""
            w_sz = self.decode_window
            n_l = cfg.n_layers
            b = tokens.shape[0]
            p, chunk = pre_tokens.shape[1], pre_tokens.shape[2]
            win_shape = (n_l, b, cfg.n_kv_heads, w_sz, cfg.head_dim)
            buf_shape = (n_l, p, cfg.n_kv_heads, w_sz * chunk,
                         cfg.head_dim)

            def body(carry, scanned):
                tok, k_win, v_win, kbuf, vbuf, key = carry
                w, pre_tok_w, rope_b, kv_b, kv_l, sel_r = scanned
                key, sub = jax.random.split(key)
                (logits, k_cols, v_cols, pre_k, pre_v,
                 h_step) = decoder.decode_step_piggyback(
                    params, tok, positions, w, cfg, cache, k_win,
                    v_win, pre_tok_w, rope_b, kv_b, kv_l, sel_r,
                    kbuf, vbuf, kv_len=kv_len)
                k_win = jax.lax.dynamic_update_slice_in_dim(
                    k_win, k_cols[:, :, :, None].astype(k_win.dtype),
                    w, axis=3)
                v_win = jax.lax.dynamic_update_slice_in_dim(
                    v_win, v_cols[:, :, :, None].astype(v_win.dtype),
                    w, axis=3)
                kbuf = jax.lax.dynamic_update_slice_in_dim(
                    kbuf, pre_k.astype(kbuf.dtype), w * chunk, axis=3)
                vbuf = jax.lax.dynamic_update_slice_in_dim(
                    vbuf, pre_v.astype(vbuf.dtype), w * chunk, axis=3)
                nxt = sample(logits, sub, self.sampling)
                return (nxt, k_win, v_win, kbuf, vbuf, key), (nxt,
                                                              h_step)

            carry0 = (tokens,
                      jnp.zeros(win_shape, self.kv_dtype),
                      jnp.zeros(win_shape, self.kv_dtype),
                      jnp.zeros(buf_shape, self.kv_dtype),
                      jnp.zeros(buf_shape, self.kv_dtype),
                      key)
            (tok, k_win, v_win, kbuf, vbuf, key), (toks, h_all) = \
                jax.lax.scan(body, carry0,
                             (jnp.arange(w_sz), pre_tokens,
                              pre_rope_base, pre_kv_begin, pre_kv_len,
                              pre_sel_rel))
            new_cache = decoder.merge_window(cache, k_win, v_win,
                                            positions, steps=w_sz)
            new_cache = decoder.merge_prefill(new_cache, kbuf, vbuf,
                                              pre_sidx, pre_pidx)
            # first tokens for completed rows: gather [M, D] hidden
            # states at the host-chosen (step, lane) completion points
            h_sel = h_all[pre_sel_w, pre_sel_p]            # [M, D]
            first_logits = decoder._unembed(
                h_sel[:, None, :], params, cfg)[:, 0]
            key, sub = jax.random.split(key)
            first = sample(first_logits, sub, self.sampling)
            return toks, first, new_cache

        self._piggy_fn = jax.jit(_decode_piggyback, donate_argnums=(3,),
                                 static_argnames=("kv_len",))

        # ---- speculative decoding (prompt-lookup drafts) ---------------
        # Decode pays one full weight read per generated token; the
        # verify dispatch amortizes that read over k drafted tokens
        # scored in ONE pass. Draft lengths come from a STATIC bucket
        # set so retrace count stays bounded (one program per nonzero
        # bucket × kv bucket): a wave's k_max is the largest per-slot
        # bucketed draft, and slots with no hit ride the same program
        # in the k=0 lane (one real token, masked padding).
        self.spec_decode = bool(spec_decode)
        self.spec_draft_lens = tuple(sorted(
            {int(k) for k in spec_draft_lens} | {0}))
        if any(k < 0 for k in self.spec_draft_lens):
            raise ValueError(
                f"spec_draft_lens must be >= 0, got {spec_draft_lens}")
        self._spec_max_draft = max(self.spec_draft_lens)
        self.spec_ngram = int(spec_ngram)
        self.spec_min_ngram = int(spec_min_ngram)
        if self.spec_decode:
            if cfg.sliding_window and cfg.sliding_window < self.max_len:
                raise ValueError(
                    "spec_decode requires full attention: the verify "
                    "pass rides prefill_attention_seeded, which does "
                    "not implement absolute-timeline window masking")
            if self._spec_max_draft + 1 >= self.max_len:
                raise ValueError(
                    f"spec_draft_lens {spec_draft_lens} leave no cache "
                    f"room in max_len {self.max_len}")
        #: slot → NgramDraftIndex over (prompt + emitted tokens); built
        #: at admission, extended as tokens are accepted, dropped at
        #: retirement. Pure host state — the drafting side costs zero
        #: device work.
        self._draft_index: dict[int, NgramDraftIndex] = {}

        def _verify(params, tokens, qlens, positions, cache, key, *,
                    kv_len):
            """Score k+1 positions per slot in ONE weight pass and
            accept drafts exactly — the speculative-decoding dispatch.

            tokens: [B, S] (S = k_max+1): each row is the slot's
            committed next token followed by its drafted continuation,
            right-padded; qlens: [B] valid tokens per row (draft len
            + 1; 1 = the k=0 lane); positions: [B] committed cache
            prefix (free slots park out of range — their scatter rows
            drop). A short seeded prefill (``decoder.verify_seeded``)
            reads the slot cache as the seeded prefix, fresh KV for
            all S fed tokens scatters into the cache at the per-row
            offset in one ``merge_window`` (columns past the accept
            point are dead by the prefix-length masking and get
            overwritten by the next write at those positions — the
            same invalidation discipline the prefix-cache publish
            relies on), and ``verify_draft`` applies greedy
            (bit-identical) or rejection-rule (distribution-exact)
            acceptance in-program, so the host fetches only
            [B, S] + [B] ints."""
            logits, k_new, v_new = decoder.verify_seeded(
                params, tokens, qlens, positions, cfg, cache,
                kv_len=kv_len)
            cache = decoder.merge_window(cache, k_new, v_new, positions,
                                         steps=tokens.shape[1])
            out, n_accept = verify_draft(logits, tokens[:, 1:],
                                         qlens - 1, key, self.sampling)
            return out, n_accept, cache

        self._verify_fn = jax.jit(_verify, donate_argnums=(4,),
                                  static_argnames=("kv_len",))

        # ---- SLO-aware scheduler (engine/scheduler.py) -----------------
        # Admission policy owner: per-tenant weighted-DRR fairness with
        # priority lanes, closed-loop load shedding over the telemetry
        # signals, and CHUNKED PREFILL — prompts longer than the
        # configured chunk size split across continuation dispatches
        # co-scheduled with decode windows, so one long prompt costs
        # many small ITL bumps instead of a monolithic admission stall.
        # The continuation program below is the seeded-prefill path
        # (PR 1) generalized: ``decoder.verify_seeded`` reads the
        # slot's own partially-filled cache as the seeded prefix, the
        # chunk's fresh KV scatters in at the per-row fill offset, and
        # the FINAL chunk samples the first token from the last prompt
        # position — bit-identical (greedy) to the monolithic wave when
        # the cache dtype matches the compute dtype, same argument as
        # the prefix cache. Design: docs/SCHEDULER.md.
        self._sched = resolve_scheduler(scheduler,
                                        telemetry=self.telemetry)
        # Chunking rides prefill_attention_seeded, which (like spec
        # decode) does not implement absolute-timeline window masking.
        self._chunk_ok = (cfg.sliding_window == 0
                          or cfg.sliding_window >= self.max_len)
        ct = self.prompt_limit
        if self._sched is not None:
            ct = max(1, min(self._sched.cfg.chunk_tokens,
                            self.prompt_limit))
        #: static chunk-width bucket set — the retrace bound for the
        #: continuation program, exactly like the verify dispatch's
        #: draft-length buckets (shardcheck: scheduler-chunked-prefill)
        self._chunk_buckets = tuple(sorted(
            {min(b, ct) for b in self.buckets} | {ct}))
        #: released long prompts waiting for a slot to start chunking
        self._chunk_pending: list[Request] = []
        #: slot → [request, tokens filled so far, chunk-start time]
        self._chunking: dict[int, list] = {}
        #: chunked-prefill accounting (sched_stats())
        self.chunk_dispatches = 0
        self.chunk_prefill_tokens = 0
        self.chunk_s = 0.0

        def _prefill_chunk(params, tokens, qlens, positions, cache, key,
                           *, kv_len):
            """One chunked-prefill continuation dispatch: every
            chunking slot's next prompt chunk attends (its own cache
            prefix ++ fresh causal chunk) in ONE weight pass, fresh KV
            merges at the per-row fill offset, and each row samples a
            candidate first token from its last fed position (the host
            keeps it only for rows whose prompt completed this chunk).
            Non-chunking rows park at position max_len: their fresh KV
            drops in the merge and their logits are discarded — the
            same park-OOB discipline as the verify dispatch."""
            logits, k_new, v_new = decoder.verify_seeded(
                params, tokens, qlens, positions, cfg, cache,
                kv_len=kv_len)
            cache = decoder.merge_window(cache, k_new, v_new, positions,
                                         steps=tokens.shape[1])
            last = jnp.take_along_axis(
                logits, (qlens - 1)[:, None, None], axis=1)[:, 0]
            first = sample(last, key, self.sampling)
            return first, cache

        self._chunk_fn = jax.jit(_prefill_chunk, donate_argnums=(4,),
                                 static_argnames=("kv_len",))

        # ---- paged dispatch programs (kv_pool_blocks > 0) --------------
        # Two routes serve the same block-table semantics, selected by
        # ``kv_kernel`` into ``self._kv_route``:
        #
        # REFERENCE (``kv_kernel="reference"``, and "auto" off-TPU):
        # the contiguous program composed with the indirection of
        # ops/paged_attention.py — gather the working-set VIEW the
        # tables describe (a pure reordering, so greedy decode is
        # bit-identical at f32), run the UNCHANGED decoder program
        # over it, read the freshly merged columns back out of the
        # view, scatter them into the pool at host-built (block,
        # offset) maps. Simple and backend-portable, but it
        # materializes kv_len × rows working-set copies and a
        # view-sized round trip EVERY dispatch.
        #
        # KERNEL (``kv_kernel="pallas"``, and "auto" on TPU): the
        # Pallas paged kernel (ops.paged_attention.
        # paged_attention_partial_pallas) scores the committed pool
        # prefix IN PLACE — block tables ride the scalar-prefetch
        # lane, the traced layer index selects into the stacked pool
        # so no per-layer slice materializes either, and fp8 pools
        # dequantize on load inside the kernel. It emits flash
        # partials (acc, m, l) that ``ops.attention.combine_partials``
        # joins with the dispatch-local window/done/cur (decode) or
        # causal-suffix (seeded) pieces — one joint softmax, same
        # masking, parity-gated against the reference route under
        # interpret mode. Fresh KV then scatters as the SAME narrow
        # per-row write the reference route uses, but straight from
        # the window buffers: no view gather, no view read-back, no
        # full-pool-view round trip anywhere in the traced program
        # (pinned by a no-gather trace test).
        #
        # The pool halves are donated on both routes — they are the
        # one long-lived KV allocation and must never double-buffer.
        if self.paged:
            from copilot_for_consensus_tpu.ops.paged_attention import (
                paged_attention_partial_pallas,
                paged_gather_kv,
            )

            def _pool_scatter(pool_k, pool_v, k_new, v_new, sbids,
                              soffs):
                """Scatter fresh KV [L, R, Hkv, S, Dh] into the pool at
                per-(row, column) maps [R, S]: column j of row i lands
                in pool block ``sbids[i, j]`` offset ``soffs[i, j]``.
                OOB block ids (parked rows, masked padding) drop."""
                k_upd = k_new.transpose(1, 3, 0, 2, 4)
                v_upd = v_new.transpose(1, 3, 0, 2, 4)
                pk = pool_k.at[:, sbids, :, soffs, :].set(
                    k_upd.astype(pool_k.dtype), mode="drop")
                pv = pool_v.at[:, sbids, :, soffs, :].set(
                    v_upd.astype(pool_v.dtype), mode="drop")
                return pk, pv

            def _view_take(view, positions, steps):
                """Read the dispatch's freshly merged columns back out
                of the view: [L, B, Hkv, W, Dh]-shaped gather at
                positions + [0, steps) per row (parked rows clamp —
                their scatter map is OOB and drops)."""
                b = view.shape[1]
                s_v = view.shape[3]
                bidx = jnp.broadcast_to(jnp.arange(b)[:, None],
                                        (b, steps))
                pidx = jnp.clip(
                    positions[:, None] + jnp.arange(steps)[None, :],
                    0, s_v - 1)
                return view[:, bidx, :, pidx, :].transpose(2, 0, 3, 1, 4)

            def _admit_paged(params, tokens, lengths, pool_k, pool_v,
                             sbids, soffs, key):
                """Paged admission wave: prefill + pool scatter + first
                token sample as ONE program. The scratch ferries the
                fresh KV straight into pool blocks — no per-slot
                contiguous cache exists to insert into."""
                scratch = decoder.init_cache(cfg, tokens.shape[0],
                                             tokens.shape[1],
                                             dtype=self.kv_dtype)
                logits, scratch = decoder.prefill(params, tokens,
                                                  lengths, cfg, scratch,
                                                  attn_impl=impl)
                pool_k, pool_v = _pool_scatter(
                    pool_k, pool_v, scratch["k"], scratch["v"], sbids,
                    soffs)
                first = sample(logits, key, self.sampling)
                return first, pool_k, pool_v

            def _admit_seeded_paged(params, tokens, lengths, pool_k,
                                    pool_v, bids, pref_lens,
                                    sbids, soffs, key):
                """Zero-copy seeded admission: the matched prefix is
                READ from its pool blocks for the suffix attention
                (pointer indirection — the blocks were appended to the
                slot's table host-side, nothing is copied into any
                per-slot cache), the suffix prefills at the per-row
                offset, and only the fresh suffix KV scatters into the
                slot's OWN blocks. ``bids``: [N, NB] — 2-D so the dp
                shard_map splits the row axis with its rows' block ids
                (shard-local under dp sharding)."""
                n, sbuc = tokens.shape
                pk, pv = paged_gather_kv(pool_k, pool_v, bids)
                scratch = decoder.init_cache(cfg, n, sbuc,
                                             dtype=self.kv_dtype)
                logits, scratch = decoder.prefill_seeded(
                    params, tokens, lengths, pk, pv, pref_lens, cfg,
                    scratch)
                pool_k, pool_v = _pool_scatter(
                    pool_k, pool_v, scratch["k"], scratch["v"], sbids,
                    soffs)
                first = sample(logits, key, self.sampling)
                return first, pool_k, pool_v

            def _decode_paged(params, tokens, positions, pool_k,
                              pool_v, gbids, sbids, soffs, key, *,
                              kv_len, n_windows=1):
                """Windowed decode over the block tables: gather the
                view ``gbids`` describes (wide enough for this
                dispatch's writes), run the contiguous window program
                over it unchanged, scatter the freshly merged columns
                back into the pool."""
                vk, vv = paged_gather_kv(pool_k, pool_v, gbids)
                toks, view = _decode(params, tokens, positions,
                                     {"k": vk, "v": vv}, key,
                                     kv_len=kv_len,
                                     n_windows=n_windows)
                steps = n_windows * self.decode_window
                k_new = _view_take(view["k"], positions, steps)
                v_new = _view_take(view["v"], positions, steps)
                pool_k, pool_v = _pool_scatter(pool_k, pool_v, k_new,
                                               v_new, sbids, soffs)
                return toks, pool_k, pool_v

            def _verify_paged(params, tokens, qlens, positions,
                              pool_k, pool_v, gbids, sbids, soffs,
                              key, *, kv_len):
                vk, vv = paged_gather_kv(pool_k, pool_v, gbids)
                out, n_accept, view = _verify(
                    params, tokens, qlens, positions,
                    {"k": vk, "v": vv}, key, kv_len=kv_len)
                k_new = _view_take(view["k"], positions,
                                   tokens.shape[1])
                v_new = _view_take(view["v"], positions,
                                   tokens.shape[1])
                pool_k, pool_v = _pool_scatter(pool_k, pool_v, k_new,
                                               v_new, sbids, soffs)
                return out, n_accept, pool_k, pool_v

            def _chunk_paged(params, tokens, qlens, positions, pool_k,
                             pool_v, gbids, sbids, soffs, key, *,
                             kv_len):
                vk, vv = paged_gather_kv(pool_k, pool_v, gbids)
                first, view = _prefill_chunk(
                    params, tokens, qlens, positions,
                    {"k": vk, "v": vv}, key, kv_len=kv_len)
                k_new = _view_take(view["k"], positions,
                                   tokens.shape[1])
                v_new = _view_take(view["v"], positions,
                                   tokens.shape[1])
                pool_k, pool_v = _pool_scatter(pool_k, pool_v, k_new,
                                               v_new, sbids, soffs)
                return first, pool_k, pool_v

            if mesh is None:
                self._admit_paged_fn = jax.jit(
                    _admit_paged, donate_argnums=(3, 4))
                self._admit_seeded_paged_fn = jax.jit(
                    _admit_seeded_paged, donate_argnums=(3, 4))
                self._decode_paged_fn = jax.jit(
                    _decode_paged, donate_argnums=(3, 4),
                    static_argnames=("kv_len", "n_windows"))
                self._verify_paged_fn = jax.jit(
                    _verify_paged, donate_argnums=(4, 5),
                    static_argnames=("kv_len",))
                self._chunk_paged_fn = jax.jit(
                    _chunk_paged, donate_argnums=(4, 5),
                    static_argnames=("kv_len",))
            else:
                # ---- mesh-sharded paged dispatches ------------------
                # The block-table INDIRECTION (pool gather / pool
                # scatter — the two ops GSPMD cannot partition: their
                # indices are per-shard-local by the allocator's
                # design) runs under shard_map with dp MANUAL: each
                # body sees its own pool slice, its own slot rows, and
                # the shard-local ids the host built. The decoder math
                # between them — the UNCHANGED contiguous programs —
                # runs under plain GSPMD over tp×dp inside the same
                # jit, exactly the partitioning the contiguous mesh
                # engine serves with (and the one the bit-identity
                # test pins). tp stays an AUTO axis inside the
                # shard_map pieces so a tp-sharded kv-head axis passes
                # straight through; pp/sp/ep are size-1 here (checked
                # above). Both pool halves stay donated through the
                # outer jit — the one long-lived KV allocation must
                # never double-buffer, sharded or not.
                try:                              # jax >= 0.5
                    from jax import shard_map
                except ImportError:               # this toolchain
                    from jax.experimental.shard_map import shard_map
                from jax.sharding import PartitionSpec as P

                auto = frozenset({"tp"})
                POOL = P(None, "dp", None, None, None)
                VIEW = P(None, "dp", None, None, None)  # batch on dp
                ROW2 = P("dp", None)

                gather_sm = shard_map(
                    paged_gather_kv, mesh,
                    in_specs=(POOL, POOL, ROW2),
                    out_specs=(VIEW, VIEW),
                    check_rep=False, auto=auto)
                scatter_sm = shard_map(
                    _pool_scatter, mesh,
                    in_specs=(POOL, POOL, VIEW, VIEW, ROW2, ROW2),
                    out_specs=(POOL, POOL),
                    check_rep=False, auto=auto)

                def _admit_paged_mesh(params, tokens, lengths, pool_k,
                                      pool_v, sbids, soffs, key):
                    scratch = decoder.init_cache(
                        cfg, tokens.shape[0], tokens.shape[1],
                        dtype=self.kv_dtype)
                    logits, scratch = decoder.prefill(
                        params, tokens, lengths, cfg, scratch,
                        attn_impl=impl)
                    pool_k, pool_v = scatter_sm(
                        pool_k, pool_v, scratch["k"], scratch["v"],
                        sbids, soffs)
                    first = sample(logits, key, self.sampling)
                    return first, pool_k, pool_v

                self._admit_paged_fn = jax.jit(
                    _admit_paged_mesh, donate_argnums=(3, 4))

                def _admit_seeded_paged_mesh(params, tokens, lengths,
                                             pool_k, pool_v, bids,
                                             pref_lens, sbids, soffs,
                                             key):
                    pk, pv = gather_sm(pool_k, pool_v, bids)
                    scratch = decoder.init_cache(
                        cfg, tokens.shape[0], tokens.shape[1],
                        dtype=self.kv_dtype)
                    logits, scratch = decoder.prefill_seeded(
                        params, tokens, lengths, pk, pv, pref_lens,
                        cfg, scratch)
                    pool_k, pool_v = scatter_sm(
                        pool_k, pool_v, scratch["k"], scratch["v"],
                        sbids, soffs)
                    first = sample(logits, key, self.sampling)
                    return first, pool_k, pool_v

                self._admit_seeded_paged_fn = jax.jit(
                    _admit_seeded_paged_mesh, donate_argnums=(3, 4))

                def _decode_paged_mesh(params, tokens, positions,
                                       pool_k, pool_v, gbids, sbids,
                                       soffs, key, *, kv_len,
                                       n_windows=1):
                    vk, vv = gather_sm(pool_k, pool_v, gbids)
                    toks, view = _decode(params, tokens, positions,
                                         {"k": vk, "v": vv}, key,
                                         kv_len=kv_len,
                                         n_windows=n_windows)
                    steps = n_windows * self.decode_window
                    k_new = _view_take(view["k"], positions, steps)
                    v_new = _view_take(view["v"], positions, steps)
                    pool_k, pool_v = scatter_sm(pool_k, pool_v,
                                                k_new, v_new, sbids,
                                                soffs)
                    return toks, pool_k, pool_v

                self._decode_paged_fn = jax.jit(
                    _decode_paged_mesh, donate_argnums=(3, 4),
                    static_argnames=("kv_len", "n_windows"))

                def _verify_paged_mesh(params, tokens, qlens,
                                       positions, pool_k, pool_v,
                                       gbids, sbids, soffs, key, *,
                                       kv_len):
                    vk, vv = gather_sm(pool_k, pool_v, gbids)
                    out, n_accept, view = _verify(
                        params, tokens, qlens, positions,
                        {"k": vk, "v": vv}, key, kv_len=kv_len)
                    k_new = _view_take(view["k"], positions,
                                       tokens.shape[1])
                    v_new = _view_take(view["v"], positions,
                                       tokens.shape[1])
                    pool_k, pool_v = scatter_sm(pool_k, pool_v,
                                                k_new, v_new, sbids,
                                                soffs)
                    return out, n_accept, pool_k, pool_v

                self._verify_paged_fn = jax.jit(
                    _verify_paged_mesh, donate_argnums=(4, 5),
                    static_argnames=("kv_len",))

                def _chunk_paged_mesh(params, tokens, qlens,
                                      positions, pool_k, pool_v,
                                      gbids, sbids, soffs, key, *,
                                      kv_len):
                    vk, vv = gather_sm(pool_k, pool_v, gbids)
                    first, view = _prefill_chunk(
                        params, tokens, qlens, positions,
                        {"k": vk, "v": vv}, key, kv_len=kv_len)
                    k_new = _view_take(view["k"], positions,
                                       tokens.shape[1])
                    v_new = _view_take(view["v"], positions,
                                       tokens.shape[1])
                    pool_k, pool_v = scatter_sm(pool_k, pool_v,
                                                k_new, v_new, sbids,
                                                soffs)
                    return first, pool_k, pool_v

                self._chunk_paged_fn = jax.jit(
                    _chunk_paged_mesh, donate_argnums=(4, 5),
                    static_argnames=("kv_len",))

            if self._kv_route == "kernel":
                # ---- Pallas kernel route ----------------------------
                # Rebinds the FOUR gathering dispatches (plain paged
                # admission never gathered — it is route-agnostic)
                # under the same attribute names, signatures,
                # donations and static args as the reference
                # assignments above, so every call site, retrace
                # bound and shardcheck contract case carries over
                # unchanged. ``kv_len // block`` committed blocks are
                # a STATIC slice of the dispatch's gather table (the
                # view table is always at least that wide): the
                # kernel only ever reads committed positions — fresh
                # KV rides the window/suffix buffers until the one
                # narrow scatter.
                if mesh is None:
                    def _partial_for(window):
                        def call(pool_k, pool_v, tables, li, q_rows,
                                 lengths, q_pos):
                            return paged_attention_partial_pallas(
                                q_rows, pool_k, pool_v, li, tables,
                                lengths, q_pos, window=window)
                        return call

                    scatter_kfn = _pool_scatter
                else:
                    # dp MANUAL exactly like gather_sm/scatter_sm:
                    # the kernel indexes its shard-local pool slice
                    # with the shard-local ids the host built
                    # (per-shard OOB sentinel clamps in the wrapper,
                    # same park discipline as the gather). tp stays
                    # an AUTO axis — the pallas_call is opaque to
                    # GSPMD, so a tp-sharded kv-head axis replicates
                    # through it (docs/PERF.md "Kernel route" carries
                    # the honest accounting).
                    QROWS = P("dp", None, None, None)

                    def _partial_for(window):
                        def call(pool_k, pool_v, tables, li, q_rows,
                                 lengths, q_pos):
                            return paged_attention_partial_pallas(
                                q_rows, pool_k, pool_v, li, tables,
                                lengths, q_pos, window=window)
                        return shard_map(
                            call, mesh,
                            in_specs=(POOL, POOL, ROW2, P(), QROWS,
                                      P("dp"), P("dp")),
                            out_specs=(QROWS, QROWS, QROWS),
                            check_rep=False, auto=auto)

                    scatter_kfn = scatter_sm

                partial_dec = _partial_for(cfg.sliding_window)
                partial_seed = _partial_for(0)

                def _decode_paged_kernel(params, tokens, positions,
                                         pool_k, pool_v, gbids,
                                         sbids, soffs, key, *,
                                         kv_len, n_windows=1):
                    """Kernel-route windowed decode: the reference
                    ``_decode`` body verbatim (same key-split/sample
                    order, so greedy token streams match) except the
                    committed pool prefix is scored IN PLACE per
                    layer and the window buffers scatter straight to
                    the pool — no view gather, no view read-back."""
                    tables = gbids[:, :kv_len // self._block]

                    def partial_fn(li, q_rows, lengths, q_pos):
                        return partial_dec(pool_k, pool_v, tables,
                                           li, q_rows, lengths,
                                           q_pos)

                    w_sz = self.decode_window
                    b = tokens.shape[0]
                    shape = (cfg.n_layers, b, cfg.n_kv_heads, w_sz,
                             cfg.head_dim)

                    def run_window(tok, key, done):
                        k_win = jnp.zeros(shape, self.kv_dtype)
                        v_win = jnp.zeros(shape, self.kv_dtype)
                        k_done, v_done = done

                        def body(carry, w):
                            tok, k_win, v_win, key = carry
                            key, sub = jax.random.split(key)
                            logits, k_cols, v_cols = \
                                decoder.decode_step_windowed_paged(
                                    params, tok, positions, w, cfg,
                                    partial_fn, k_win, v_win,
                                    k_done=k_done, v_done=v_done)
                            k_win = \
                                jax.lax.dynamic_update_slice_in_dim(
                                    k_win, k_cols[:, :, :, None]
                                    .astype(k_win.dtype), w, axis=3)
                            v_win = \
                                jax.lax.dynamic_update_slice_in_dim(
                                    v_win, v_cols[:, :, :, None]
                                    .astype(v_win.dtype), w, axis=3)
                            nxt = sample(logits, sub, self.sampling)
                            return (nxt, k_win, v_win, key), nxt

                        (tok, k_win, v_win, key), toks = jax.lax.scan(
                            body, (tok, k_win, v_win, key),
                            jnp.arange(w_sz))
                        return tok, key, toks, k_win, v_win

                    tok, done = tokens, (None, None)
                    outs, wins = [], []
                    for widx in range(n_windows):
                        tok, key, toks, k_win, v_win = run_window(
                            tok, key, done)
                        outs.append(toks)
                        wins.append((k_win, v_win))
                        if widx + 1 < n_windows:
                            done = (
                                jnp.concatenate(
                                    [kw for kw, _ in wins], 3),
                                jnp.concatenate(
                                    [vw for _, vw in wins], 3))
                    if n_windows == 1:
                        k_all, v_all = wins[0]
                        toks_all = outs[0]
                    else:
                        k_all = jnp.concatenate(
                            [kw for kw, _ in wins], 3)
                        v_all = jnp.concatenate(
                            [vw for _, vw in wins], 3)
                        toks_all = jnp.concatenate(outs, axis=0)
                    pool_k, pool_v = scatter_kfn(
                        pool_k, pool_v, k_all, v_all, sbids, soffs)
                    return toks_all, pool_k, pool_v

                self._decode_paged_fn = jax.jit(
                    _decode_paged_kernel, donate_argnums=(3, 4),
                    static_argnames=("kv_len", "n_windows"))

                def _admit_seeded_paged_kernel(params, tokens,
                                               lengths, pool_k,
                                               pool_v, bids,
                                               pref_lens, sbids,
                                               soffs, key):
                    """Zero-copy seeded admission, kernel route: the
                    matched prefix blocks are scored in place off
                    ``bids`` (never gathered into a view), the fresh
                    suffix KV scatters from compute dtype — the same
                    single compute→kv_dtype cast the reference
                    scratch takes."""
                    def partial_fn(li, q_rows, lns, q_pos):
                        return partial_seed(pool_k, pool_v, bids, li,
                                            q_rows, lns, q_pos)

                    logits, k_new, v_new = decoder.prefill_seeded_paged(
                        params, tokens, lengths, pref_lens, cfg,
                        partial_fn, all_logits=False)
                    pool_k, pool_v = scatter_kfn(
                        pool_k, pool_v, k_new, v_new, sbids, soffs)
                    first = sample(logits, key, self.sampling)
                    return first, pool_k, pool_v

                self._admit_seeded_paged_fn = jax.jit(
                    _admit_seeded_paged_kernel, donate_argnums=(3, 4))

                def _verify_paged_kernel(params, tokens, qlens,
                                         positions, pool_k, pool_v,
                                         gbids, sbids, soffs, key, *,
                                         kv_len):
                    tables = gbids[:, :kv_len // self._block]

                    def partial_fn(li, q_rows, lns, q_pos):
                        return partial_seed(pool_k, pool_v, tables,
                                            li, q_rows, lns, q_pos)

                    logits, k_new, v_new = decoder.prefill_seeded_paged(
                        params, tokens, qlens, positions, cfg,
                        partial_fn, all_logits=True)
                    pool_k, pool_v = scatter_kfn(
                        pool_k, pool_v, k_new, v_new, sbids, soffs)
                    out, n_accept = verify_draft(
                        logits, tokens[:, 1:], qlens - 1, key,
                        self.sampling)
                    return out, n_accept, pool_k, pool_v

                self._verify_paged_fn = jax.jit(
                    _verify_paged_kernel, donate_argnums=(4, 5),
                    static_argnames=("kv_len",))

                def _chunk_paged_kernel(params, tokens, qlens,
                                        positions, pool_k, pool_v,
                                        gbids, sbids, soffs, key, *,
                                        kv_len):
                    tables = gbids[:, :kv_len // self._block]

                    def partial_fn(li, q_rows, lns, q_pos):
                        return partial_seed(pool_k, pool_v, tables,
                                            li, q_rows, lns, q_pos)

                    # all_logits=False: the last-valid-position
                    # select happens BEFORE the lm_head inside
                    # prefill_seeded_paged — same values as the
                    # reference's take-last over [B, S, V], without
                    # unembedding S-1 discarded positions.
                    last, k_new, v_new = decoder.prefill_seeded_paged(
                        params, tokens, qlens, positions, cfg,
                        partial_fn, all_logits=False)
                    pool_k, pool_v = scatter_kfn(
                        pool_k, pool_v, k_new, v_new, sbids, soffs)
                    first = sample(last, key, self.sampling)
                    return first, pool_k, pool_v

                self._chunk_paged_fn = jax.jit(
                    _chunk_paged_kernel, donate_argnums=(4, 5),
                    static_argnames=("kv_len",))

            # ---- KV handoff programs (disaggregated roles) ---------
            # Export gathers a parked slot's blocks into one dense
            # [L, 1, Hkv, NB*blk, Dh] view (plain jit: GLOBAL block
            # ids — GSPMD reads the dp-sharded pool directly); import
            # scatters a handed-off view into freshly allocated
            # blocks of THIS engine's pool. Import donates both pool
            # halves (same no-double-buffer rule as every paged
            # dispatch); export copies out by design — the source
            # blocks are freed right after.
            def _export_kv(pool_k, pool_v, bids):
                return paged_gather_kv(pool_k, pool_v, bids)

            # deliberate non-donation: the export is a pure READ of
            # the LIVE pool — the source blocks keep serving, and are
            # freed host-side only after the handoff object exists —
            # so donating would invalidate buffers the very next
            # dispatch reads.
            # jaxlint: disable=donation
            self._export_fn = jax.jit(_export_kv)

            def _import_kv(pool_k, pool_v, k_new, v_new, sbids,
                           soffs):
                k_upd = k_new.transpose(1, 3, 0, 2, 4)
                v_upd = v_new.transpose(1, 3, 0, 2, 4)
                pk = pool_k.at[:, sbids, :, soffs, :].set(
                    k_upd.astype(pool_k.dtype), mode="drop")
                pv = pool_v.at[:, sbids, :, soffs, :].set(
                    v_upd.astype(pool_v.dtype), mode="drop")
                return pk, pv

            self._import_fn = jax.jit(_import_kv,
                                      donate_argnums=(0, 1))

        # ---- host-side slot state --------------------------------------
        self._free = list(range(num_slots))
        self._active: dict[int, Request] = {}          # slot → request
        self._generated: dict[int, list[int]] = {}     # slot → new tokens
        # Free/prefilling slots park at position max_len (out of range):
        # every decode dispatch advances ALL rows and merges their
        # garbage KV at positions0+w — an in-range stale position would
        # let a freed slot's garbage overwrite a piggyback-prefilling
        # occupant's freshly written timeline.
        self._positions = np.full(num_slots, self.max_len,
                                  dtype=np.int32)
        self._next_tok = np.zeros(num_slots, dtype=np.int32)
        self._t_prefill: dict[int, float] = {}
        self._queue: list[Request] = []
        self._done: dict[int, Completion] = {}
        self._next_id = 0
        #: cumulative wall time spent in admission waves (prefill +
        #: insert + first-token sync) since engine build — benches
        #: snapshot it around a run to split admission from decode.
        self.admitted_s = 0.0
        #: dispatch accounting (benches read these to see where the
        #: time went): piggybacked vs plain decode dispatches, and how
        #: many prompt tokens / rows rode the piggyback path
        self.piggy_s = 0.0
        self.piggy_dispatches = 0
        self.plain_s = 0.0
        self.plain_dispatches = 0
        self.piggy_rows = 0
        self.piggy_tokens = 0
        #: speculative-decoding accounting (spec_stats()): lookups/hits
        #: count draft-index probes; drafted/accepted count draft
        #: tokens through verify; rows counts (slot, verify-dispatch)
        #: pairs; emitted counts tokens harvested from verify. The
        #: ``_row_*`` pair is the per-stream weight-pass ledger across
        #: BOTH decode paths (a verify dispatch is one weight pass per
        #: row; a plain dispatch is one per row per step), from which
        #: tokens_per_weight_pass — the number speculation exists to
        #: move — is computed.
        self.spec_lookups = 0
        self.spec_hits = 0
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_dispatches = 0
        self.spec_rows = 0
        self.spec_emitted_tokens = 0
        self.spec_s = 0.0
        self._row_tokens = 0
        self._row_passes = 0

        # Warm restart LAST: every queue/slot/scheduler structure above
        # must exist before recovered requests resubmit through the
        # normal submit() path (which rebuilds the scheduler ledgers
        # and telemetry spans as a side effect).
        if self.journal is not None and self.journal.depth():
            self._recover_from_journal()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    @classmethod
    def from_checkpoint(cls, path: str, *, dtype=jnp.bfloat16,
                        **engine_kw) -> "GenerationEngine":
        """Build an engine from a checkpoint directory — native (offline-
        quantized, mmap-fast) or HF safetensors (converted in memory).
        Replaces random-weight init as the serving path; the capability of
        the reference's ``factory.py:89-94`` driver dispatch to a real
        model."""
        import ml_dtypes

        from copilot_for_consensus_tpu import checkpoint as ckpt

        np_dtype = np.dtype(dtype) if dtype != jnp.bfloat16 else np.dtype(
            ml_dtypes.bfloat16)
        # Leaves stay numpy (mmap-backed): __init__ device-puts them —
        # shard-by-shard when a mesh is given, whole-tree otherwise.
        cfg, params, meta = ckpt.load_checkpoint(
            path, dtype=str(np_dtype))
        engine_kw.setdefault("eos_id", meta.get("eos_ids",
                                                meta.get("eos_id", 2)))
        return cls(cfg, params, dtype=dtype,
                   quantize=meta.get("quantized") or False, **engine_kw)

    @property
    def _prefix(self):
        """Single-trie compatibility view (every pre-mesh caller):
        shard-aware code paths index ``_prefixes`` by dp shard
        directly. With one shard (mesh=None, or dp=1) this IS the
        engine's prefix cache, unchanged."""
        return self._prefixes[0] if self._prefixes else None

    @property
    def prompt_limit(self) -> int:
        """Longest prompt served without tail-truncation (one decode
        window of cache headroom, capped by the largest prefill bucket).
        Callers with longer prompts should route to the long-context
        engine (``engine/longctx.py``)."""
        return min(self.max_len - self._dispatch_steps, self.buckets[-1])

    def submit(self, prompt: list[int], max_new_tokens: int = 256, *,
               cache_eligible_tokens: int | None = None,
               correlation_id: str = "", tenant: str = "",
               priority: str = "interactive",
               deadline_s: float | None = None) -> int:
        """Enqueue a tokenized prompt; returns a request id.

        ``cache_eligible_tokens`` caps how many leading prompt tokens
        the prefix cache may publish when this request completes (the
        summarization path marks its shared-template span here); None
        publishes the whole block-aligned prompt prefix.
        ``correlation_id`` tags the request's telemetry span (and any
        flight-recorder dump / error report naming it) with the
        pipeline event id that caused it. ``tenant``/``priority`` feed
        the scheduler's fairness/shedding policy when one is configured
        — an overloaded scheduler raises :class:`EngineOverloaded`
        HERE, at the door, instead of queueing work it cannot serve
        within SLO (the service layer maps it to HTTP 429 +
        Retry-After). ``deadline_s`` is the per-request wall-clock
        budget: once it expires the request is dropped (queued) or
        retired with its partial output (active), both with
        ``finish_reason="deadline"`` — expired work is never
        computed."""
        if not prompt:
            raise ValueError("empty prompt")
        limit = self.prompt_limit
        if len(prompt) > limit:
            # Keep the tail: instructions/questions sit at the end of RAG
            # prompts. The orchestrator budgets context to avoid this.
            prompt = prompt[-limit:]
            # the publish cap indexed the ORIGINAL prompt; the truncated
            # head no longer matches any cacheable span
            cache_eligible_tokens = 0 if cache_eligible_tokens \
                is not None else None
        if self._sched is not None and not self._journal_recovering:
            # Warm-restart resubmits bypass the shed gate: journaled
            # work was already admitted once, and shedding it at
            # restart would turn a crash into silent loss — exactly
            # what the journal exists to prevent. The recovered burst
            # still queues through the scheduler (fairness holds).
            self._sched.check_admission(
                tenant=tenant, priority=priority,
                prompt_tokens=len(prompt),
                correlation_id=correlation_id)
        rid = self._next_id
        self._next_id += 1
        if self.journal is not None:
            if not self._journal_suppress:
                # Journal BEFORE the request enters any queue: no
                # window where admitted work is journal-invisible.
                # Suppressed for continuation resubmits, whose row is
                # the atomic supersede re-key of the ORIGINAL row —
                # never insert-then-re-key, which would leave two live
                # rows if a crash landed between. Trace parent is
                # captured here so a restart's engine_replay span can
                # parent into the originating pipeline trace.
                from copilot_for_consensus_tpu.obs import (
                    trace as _trace,
                )

                ids = _trace.current_ids()
                self.journal.record_submit(
                    rid, prompt, max_new_tokens,
                    cache_eligible_tokens=cache_eligible_tokens,
                    correlation_id=correlation_id, tenant=tenant,
                    priority=priority,
                    deadline_wall=(time.time() + max(0.0, deadline_s)
                                   if deadline_s is not None else 0.0),
                    trace_id=ids[0] if ids else "",
                    span_id=ids[1] if ids else "")
            self._journal_ckpt[rid] = 0
        if deadline_s is not None:
            self._deadlines_in_use = True
        req = Request(
            rid, list(prompt), max_new_tokens,
            cache_eligible_tokens=cache_eligible_tokens,
            correlation_id=correlation_id, tenant=tenant,
            priority=priority,
            deadline_at=(time.monotonic() + max(0.0, deadline_s)
                         if deadline_s is not None else float("inf")))
        if self._sched is not None:
            self._sched.enqueue(req)
        else:
            self._queue.append(req)
        if self.telemetry is not None:
            self.telemetry.on_submit(rid, len(prompt), correlation_id)
        return rid

    def step(self) -> list[Completion]:
        """Admit queued requests into free slots, run one decode step for
        all active slots, retire finished ones. Returns completions.

        With a scheduler configured, admission is gated by it: the
        closed loop observes this step's signals, at most one wave's
        token budget is released (DRR order, interactive lane first),
        long prompts advance by ONE chunk dispatch, and only then does
        the decode window run — so the per-step prefill work, and with
        it ITL, stays bounded regardless of prompt mix."""
        self._expire_deadlines()
        if self._sched is not None:
            self._sched_pump()
        self._admit()
        if self._chunk_pending or self._chunking:
            self._chunk_step()
        if self.paged:
            self.peak_active = max(self.peak_active, self._occupied)
        if self._active or self._prefilling:
            self._decode_once()
        if self.journal is not None:
            self._journal_tick()
        if self.telemetry is not None:
            self.telemetry.gauge_queue(self.queue_depth,
                                       len(self._active))
            if self.role != "both":
                self.telemetry.gauge_role_occupancy(
                    self.role, self._occupied / self.num_slots
                    if self.num_slots else 0.0)
            if self.paged:
                # gauges straight off the pool counters — the full
                # kv_pool_stats() (headroom walk over active slots +
                # trie) is a stats/bench API, too heavy for every step
                self.telemetry.gauge_kv_pool(
                    self._pool.free_blocks, self._pool.pinned_blocks,
                    round(self._pool.fragmentation(
                        self._used_tokens()), 4))
        return self._drain_done()

    def generate(self, prompts: list[list[int]],
                 max_new_tokens: int = 256, *,
                 cache_eligible_tokens: int | None = None
                 ) -> list[Completion]:
        """Batch convenience: submit all, run to completion, return in
        submission order. Captures a jax.profiler trace when the engine
        was built with ``profile_dir``."""
        from copilot_for_consensus_tpu.obs.profile import maybe_profile

        ids = [self.submit(p, max_new_tokens,
                           cache_eligible_tokens=cache_eligible_tokens)
               for p in prompts]
        results: dict[int, Completion] = {}
        with maybe_profile(self.profile_dir):
            try:
                while len(results) < len(ids):
                    for c in self.step():
                        results[c.request_id] = c
            except Exception as exc:
                # post-mortem before the stack unwinds: the flight
                # recorder names the in-flight requests (correlation
                # ids included) and the last N dispatches
                if self.telemetry is not None:
                    self.telemetry.record_error(exc)
                raise
        return [results[i] for i in ids]

    def generate_text(self, prompts: list[str], tokenizer: Tokenizer,
                      max_new_tokens: int = 256) -> list[str]:
        if self.faults is not None:
            # tokenization is a host boundary of the serving path too —
            # the chaos harness scripts faults against it like any
            # dispatch kind (the summarizer's encode does the same)
            self.faults.check("tokenize")
        comps = self.generate(
            [tokenizer.encode(p, add_bos=True) for p in prompts],
            max_new_tokens)
        return [tokenizer.decode(c.tokens) for c in comps]

    def prefix_stats(self) -> dict:
        """Prefix-cache counters for benches/metrics. ``hit_rate`` is
        over admission lookups; ``prefill_tokens``/``..._saved`` are
        engine-wide prompt-token accounting (wave + piggyback paths)."""
        out = {
            "enabled": bool(self._prefixes),
            "prefill_tokens": self.prefill_tokens,
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "publish_failures": self.prefix_publish_failures,
        }
        if self._prefixes:
            # aggregate across the per-dp-shard tries (one trie with
            # mesh=None — the original single-cache ledger, unchanged)
            agg: dict[str, int] = {}
            for p in self._prefixes:
                for k, v in p.stats.as_dict().items():
                    agg[k] = agg.get(k, 0) + v
            out.update(agg)
            out["hit_rate"] = (agg["hits"] / agg["lookups"]
                               if agg["lookups"] else 0.0)
            out["blocks_in_use"] = sum(p.blocks_in_use
                                       for p in self._prefixes)
        return out

    def spec_stats(self) -> dict:
        """Speculative-decoding counters for benches/metrics (mirrors
        ``prefix_stats``). ``draft_hit_rate`` is over draft-index
        probes; ``acceptance_rate`` over drafted tokens;
        ``mean_accepted_per_step`` is the per-row average accepted
        draft tokens per verify dispatch; ``tokens_per_weight_pass``
        is the per-stream decode ledger across BOTH paths (1.0 is the
        vanilla wall, >1 is what speculation buys)."""
        out = {
            "enabled": self.spec_decode,
            "lookups": self.spec_lookups,
            "hits": self.spec_hits,
            "draft_hit_rate": (self.spec_hits / self.spec_lookups
                               if self.spec_lookups else 0.0),
            "drafted_tokens": self.spec_drafted_tokens,
            "accepted_tokens": self.spec_accepted_tokens,
            "acceptance_rate": (
                self.spec_accepted_tokens / self.spec_drafted_tokens
                if self.spec_drafted_tokens else 0.0),
            "verify_dispatches": self.spec_dispatches,
            "verify_rows": self.spec_rows,
            "emitted_tokens": self.spec_emitted_tokens,
            "mean_accepted_per_step": (
                self.spec_accepted_tokens / self.spec_rows
                if self.spec_rows else 0.0),
            "weight_row_passes": self._row_passes,
            "weight_row_tokens": self._row_tokens,
            "tokens_per_weight_pass": (
                self._row_tokens / self._row_passes
                if self._row_passes else 0.0),
        }
        return out

    def journal_stats(self) -> dict:
        """Durable-journal counters for benches/metrics (mirrors
        ``prefix_stats``). ``replayed`` counts this process's
        warm-restart resubmissions; ``abandoned`` counts rows that
        could not be resumed (continuation past ``prompt_limit``);
        the rest come from :meth:`EngineJournal.stats`."""
        out = {
            "enabled": self.journal is not None,
            "replayed": self.journal_replayed,
            "abandoned": self.journal_abandoned,
        }
        if self.journal is not None:
            s = self.journal.stats()
            out["depth"] = s["depth"]
            out["journaled"] = s["journaled"]
            out["retired"] = s["retired"]
            out["checkpoints"] = s["checkpoints"]
        return out

    def sched_stats(self) -> dict:
        """Scheduler counters for benches/metrics (mirrors
        ``prefix_stats``/``spec_stats``). ``shed_rate`` is over all
        admission attempts; ``fairness_jain_index`` is Jain's index
        over per-tenant admitted tokens normalized by DRR weight (1.0
        = perfectly weighted-fair)."""
        out = {
            "enabled": self._sched is not None,
            "chunk_dispatches": self.chunk_dispatches,
            "chunk_prefill_tokens": self.chunk_prefill_tokens,
        }
        if self._sched is None:
            return out
        s = self._sched
        attempts = s.shed_total + s.submitted_total
        fairness = s.fairness_snapshot()
        out.update({
            "submitted": s.submitted_total,
            "shed": s.shed_total,
            "shed_rate": s.shed_total / attempts if attempts else 0.0,
            "overload_level": s.overload_level,
            "fairness": {t: round(v, 1) for t, v in fairness.items()},
            "fairness_jain_index": round(
                jain_index(fairness.values()), 4),
            "signals": dict(s.last_signals),
        })
        return out

    @property
    def queue_depth(self) -> int:
        n = (len(self._queue) + len(self._prefilling)
             + len(self._chunk_pending) + len(self._chunking))
        if self._sched is not None:
            n += self._sched.queued
        return n

    @property
    def active_count(self) -> int:
        return len(self._active)

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    @contextlib.contextmanager
    def _dispatch_boundary(self, kind: str):
        """The host-side dispatch boundary every device program runs
        under: the fault plane's injection point (engine/faults.py —
        strictly BEFORE the jitted call, never inside traced code) and
        the supervisor's watchdog/outcome surface
        (engine/supervisor.py). On failure the kind is recorded so
        containment can classify without parsing tracebacks."""
        sup = self.supervisor
        if sup is not None:
            sup.begin_dispatch(kind)
        try:
            if self.faults is not None:
                self.faults.check(kind)
            yield
            if sup is not None:
                sup.on_dispatch_ok(kind)
        except Exception as exc:
            self._last_failed_kind = kind
            if isinstance(exc, InjectedFault) \
                    and self.telemetry is not None:
                self.telemetry.on_fault_injected(kind, exc.mode)
            if sup is not None:
                sup.on_dispatch_error(kind, exc)
            raise
        finally:
            if sup is not None:
                sup.end_dispatch(kind)

    def set_slot_cap(self, cap: int) -> None:
        """Occupancy cap (≤ num_slots): admission paths stop filling
        slots beyond it. The supervisor's resource breaker lowers it
        after repeated device resource exhaustion and restores it via
        half-open probes; already-active slots above a lowered cap
        drain naturally."""
        self._slot_cap = max(1, min(self.num_slots, int(cap)))

    @property
    def _occupied(self) -> int:
        # handoff-parked slots hold blocks until exported — they count
        # against the occupancy cap like any live timeline
        return (len(self._active) + len(self._chunking)
                + len(self._handoff))

    def _expire_deadlines(self) -> None:
        """Drop every request whose ``deadline_at`` has passed —
        queued work un-computed (empty completion), active work with
        its partial output — all with ``finish_reason="deadline"``.
        Runs at step start so a deep queue cannot burn dispatches on
        work nobody is waiting for anymore."""
        if not self._deadlines_in_use:
            return    # no deadline ever submitted: skip the queue walk
        now = time.monotonic()
        expired: list[Request] = []
        if self._queue:
            live = [r for r in self._queue if r.deadline_at > now]
            if len(live) != len(self._queue):
                expired += [r for r in self._queue
                            if r.deadline_at <= now]
                self._queue = live
        if self._chunk_pending:
            live = [r for r in self._chunk_pending
                    if r.deadline_at > now]
            if len(live) != len(self._chunk_pending):
                expired += [r for r in self._chunk_pending
                            if r.deadline_at <= now]
                self._chunk_pending = live
        if self._prefilling:
            live = [(r, t) for r, t in self._prefilling
                    if r.deadline_at > now]
            if len(live) != len(self._prefilling):
                expired += [r for r, _t in self._prefilling
                            if r.deadline_at <= now]
                self._prefilling = live
        for slot in list(self._chunking):
            req = self._chunking[slot][0]
            if req.deadline_at <= now:
                del self._chunking[slot]
                self._positions[slot] = self.max_len
                if self.paged:
                    self._paged_release_slot(slot)
                self._free.append(slot)
                expired.append(req)
        for slot in list(self._handoff):
            req = self._handoff[slot][0]
            if req.deadline_at <= now:
                del self._handoff[slot]
                m = self._prefix_pins.pop(req.request_id, None)
                if m is not None and self._prefixes:
                    self._prefixes[0].release(m)
                self._paged_release_slot(slot)
                self._free.append(slot)
                expired.append(req)
        if self._sched is not None:
            expired += self._sched.drop_expired(now)
        for req in expired:
            self.deadline_expired += 1
            self._done[req.request_id] = Completion(
                request_id=req.request_id, prompt_len=len(req.prompt),
                tokens=[], finish_reason="deadline")
            if self.telemetry is not None:
                self.telemetry.on_deadline_expired()
                self.telemetry.on_retire(req.request_id, new_tokens=0,
                                         finish_reason="deadline")
        # active slots: retire with whatever was accepted so far (the
        # partial output is real work — only FUTURE compute is dropped)
        for slot, req in list(self._active.items()):
            if req.deadline_at <= now:
                self.deadline_expired += 1
                if self.telemetry is not None:
                    self.telemetry.on_deadline_expired()
                self._retire(slot, "deadline")

    def _admit(self) -> None:
        """Admit every queued request a free slot can take, as ONE
        batched prefill. The r1 per-request path cost a full weight pass
        plus a host sync per admission — on hardware where a device→host
        round trip is tens of ms, 32 admissions burned seconds. Now:
        one prefill over [N, bucket] (reads the weights once), one
        batched cache insert, one sample, one host fetch of the N first
        tokens."""
        if not (self._queue and self._free):
            return
        if self._piggyback_ok:
            # Eligible prompts ride the decode dispatches chunk by
            # chunk (_decode_once) INSTEAD of a monolithic wave — up to
            # ~two dispatches' worth of backlog, the piggyback grid's
            # absorption rate. Beyond that the wave takes the overflow:
            # bulk cold-start admission is MXU-bound either way and the
            # wave's big matmuls do it at the best rate (measured on
            # the one-shot 32×2048 batch), while a steady trickle rides
            # the dispatches nearly free (measured: +0.18 s per
            # dispatch carrying 8192 prompt tokens vs 0.77 s as a
            # standalone wave). The backlog bound makes the policy
            # self-balancing with no occupancy heuristics.
            cap = self.decode_window * self.prefill_chunk
            budget = 2 * cap * self.prefill_rows - sum(
                len(r.prompt) for r, _ in self._prefilling)
            keep = []
            for req in self._queue:
                plen = len(req.prompt)
                # Prefix-cache integration with the piggyback path:
                # requests whose prefix is cached route to the SEEDED
                # admission wave instead — the piggyback chunk grid
                # attends only its own dispatch buffer, so a hit riding
                # it would re-prefill the cached span anyway. Misses
                # still piggyback, and their completions still publish.
                if (self._prefix is not None
                        and self._prefix.match_tokens(
                            req.prompt,
                            digests=self._req_digests(req)) > 0):
                    keep.append(req)
                    continue
                if (self.piggyback_min_prompt <= plen <= cap
                        and plen <= budget):
                    # whole prompts only: the packer places each row as
                    # one consecutive chunk run inside a single
                    # dispatch, so its kv never straddles buffers. NO
                    # slot yet — slots are taken at PACK time, so a
                    # slot is only occupied during the dispatch that
                    # prefills it (binding at admit time measured ~2
                    # dispatches of per-slot idleness under Poisson
                    # load, which ate the whole piggyback win).
                    self._prefilling.append((req, time.monotonic()))
                    budget -= plen
                else:
                    keep.append(req)
            self._queue = keep
            if not (self._queue and self._free):
                return
        if (len(self._queue) < self.admit_min_rows
                and (self.admit_hold_strict
                     or len(self._free) * 4 <= self.num_slots)
                and (time.monotonic() - self._queue[0].submitted_at
                     < self.admit_max_wait_s)):
            # Let the wave fill while decode keeps running — but only
            # while the batch is ≥75% occupied; holding arrivals back
            # while slots idle wastes more decode capacity than the
            # wave-padding it saves.
            return
        t0 = time.monotonic()
        batch: list[tuple[int, Request]] = []
        matches: list[Any] = []      # PrefixMatch | None, aligned w/ batch
        # Cap one admission wave at 128 rows AND ~16k prompt tokens:
        # prefill scratch + activations scale with rows × bucket (the
        # f32 swiglu transient is rows·bucket·d_ff·4 bytes — 0.9 GB at
        # 16k tokens, 7.5 GB if 128 rows of 2048-token prompts were
        # padded into one wave), and each extra wave costs a full
        # weight pass. 128×128 keeps the bench's all-at-once arrival in
        # one wave; long-prompt (RAG) waves chunk by token budget.
        # With the prefix cache the budget counts SUFFIX tokens — the
        # cached span never enters the prefill transient, which is
        # exactly why a shared-prefix wave packs more rows per dispatch.
        longest = 0
        # Free-BLOCK accounting (paged engines): the wave takes a
        # request only while its worst-case block footprint fits the
        # pool headroom (free + trie-evictable minus what active work
        # may still claim) — the slot count stops being the capacity
        # bound, the pool is. Sharded engines account PER DP SHARD and
        # place each request on a shard with a free slot, headroom for
        # its worst case, and (tie-break) the longest prefix match in
        # that shard's trie — prefix-aware shard placement.
        if self.paged:
            headroom = {s: self._shard_headroom(s)
                        for s in range(self._dp)}
            free_by_shard: dict[int, list[int]] = {
                s: [] for s in range(self._dp)}
            for sl in self._free:
                free_by_shard[self._slot_shard(sl)].append(sl)
        while (self._queue and self._free and len(batch) < 128
               and self._occupied + len(batch) < self._slot_cap):
            head = self._queue[0]
            digs = None
            if self._prefixes:
                # stat-free peek for the budget decision: a request the
                # budget defers would otherwise be looked up (and
                # counted in hits/tokens_matched) once per wave it
                # waits — inflating the stats the bench reports
                digs = self._req_digests(head)
            shard = 0
            match_len = 0
            if self.paged:
                # Charge the FULL worst case, borrowed prefix included:
                # admitting a seeded row pins its matched blocks (they
                # leave the evictable headroom this gate was computed
                # against), so discounting them would let the invariant
                # go negative by exactly the matched span — the
                # mid-decode KVPoolExhausted this accounting exists to
                # make unreachable.
                need = self._worst_blocks_total(head)
                cand = None
                for s in range(self._dp):
                    if not free_by_shard[s] or need > headroom[s]:
                        continue
                    mt = self._prefixes[s].match_tokens(
                        head.prompt, digests=digs) \
                        if self._prefixes else 0
                    if cand is None or mt > cand[1]:
                        cand = (s, mt)
                if cand is None:
                    break
                shard, match_len = cand
            elif self._prefix is not None:
                match_len = self._prefix.match_tokens(head.prompt,
                                                      digests=digs)
            suffix = len(head.prompt) - match_len
            longest = max(longest, suffix)
            if batch and (len(batch) + 1) * _next_bucket(
                    longest, self.buckets) > self.admission_token_budget:
                break
            m = None
            if self._prefixes:
                m = self._prefixes[shard].lookup(head.prompt,
                                                 digests=digs)
                if m.tokens == 0:       # miss: nothing pinned
                    m = None
            if self.paged:
                headroom[shard] -= self._worst_blocks_total(head)
                slot = free_by_shard[shard].pop(0)
                self._free.remove(slot)
            else:
                slot = self._free.pop(0)
            batch.append((slot, self._queue.pop(0)))
            matches.append(m)
        if not batch:
            return     # occupancy cap (supervisor resource breaker)
        plens = [len(req.prompt) for _, req in batch]
        suffix_lens = [plens[i] - (matches[i].tokens if matches[i]
                                   else 0) for i in range(len(batch))]
        bucket = _next_bucket(max(suffix_lens), self.buckets)
        # Pad N to the next power of two: bounds compile-shape count at
        # log2(num_slots) per bucket. Padded rows prefill garbage and are
        # dropped by the out-of-range slot id in the insert. Sharded
        # waves lay rows out [dp, rows_per_shard] row-major — the dp
        # shard_map splits the row axis, so a row MUST sit in the
        # stripe of the shard that owns its slot's blocks.
        if self.paged and self._dp > 1:
            by_shard: dict[int, list[int]] = {}
            for i, (slot, _req) in enumerate(batch):
                by_shard.setdefault(self._slot_shard(slot),
                                    []).append(i)
            rows_ps = 1
            while rows_ps < max(len(v) for v in by_shard.values()):
                rows_ps *= 2
            n = rows_ps * self._dp
            row_of = {}
            for s, idxs in by_shard.items():
                for j, i in enumerate(idxs):
                    row_of[i] = s * rows_ps + j
        else:
            n = 1
            while n < len(batch):
                n *= 2
            row_of = {i: i for i in range(len(batch))}
        tokens = np.zeros((n, bucket), dtype=np.int32)
        lengths = np.ones((n,), dtype=np.int32)
        slots = np.full((n,), self.num_slots, dtype=np.int32)  # OOB pad
        self._key, sub = jax.random.split(self._key)
        seeded = any(m is not None for m in matches)
        wave_kind = "prefill_seeded" if seeded else "prefill"
        seq = self.telemetry.next_step() if self.telemetry is not None \
            else None
        try:
            if self.paged:
                # Build the rows' block tables BEFORE the dispatch:
                # matched block ids are appended by POINTER (borrowed
                # from the trie, pinned via the row's PrefixMatch —
                # the zero-copy admission), suffix blocks allocate on
                # demand. All-or-nothing per row, so the unwind below
                # can free exactly what was taken.
                for i, (slot, req) in enumerate(batch):
                    tbl = list(matches[i].block_ids) \
                        if matches[i] is not None else []
                    self._owned_from[slot] = len(tbl)
                    need = self._pool.blocks_for(plens[i]) - len(tbl)
                    if need > 0:
                        tbl.extend(self._alloc_blocks(
                            need, self._slot_shard(slot)))
                    self._tables[slot] = tbl
            with step_annotation(wave_kind, seq), \
                    self._dispatch_boundary(wave_kind):
                if seeded:
                    # Seeded wave: rows prefill only their suffix; the
                    # matched blocks gather from the pool inside the
                    # same program. NB pads to a power of two (same
                    # compile-count bounding as N). Paged engines carry
                    # SHARD-LOCAL ids with the per-shard OOB sentinel
                    # (the dp shard_map indexes local pool slices);
                    # the contiguous prefix pool keeps its own ids.
                    bps = self._pool.blocks_per_shard if self.paged \
                        else self._prefix.num_blocks
                    nb = 1
                    while nb < max(len(m.block_ids) for m in matches
                                   if m is not None):
                        nb *= 2
                    bids = np.full((n, nb), bps,
                                   dtype=np.int32)           # OOB pad
                    pref_lens = np.zeros((n,), dtype=np.int32)
                    for i, (slot, req) in enumerate(batch):
                        r = row_of[i]
                        suf = req.prompt[plens[i] - suffix_lens[i]:]
                        tokens[r, :len(suf)] = suf
                        lengths[r] = len(suf)
                        slots[r] = slot
                        if matches[i] is not None:
                            bids[r, :len(matches[i].block_ids)] = \
                                np.asarray(matches[i].block_ids,
                                           dtype=np.int32) % bps \
                                if self.paged \
                                else matches[i].block_ids
                            pref_lens[r] = matches[i].tokens
                    if self.paged:
                        rows = [(row_of[i], self._tables[slot],
                                 plens[i] - suffix_lens[i],
                                 suffix_lens[i])
                                for i, (slot, _r) in enumerate(batch)]
                        sbids, soffs = self._write_maps(rows, bucket, n)
                        first_dev, pk, pv = self._admit_seeded_paged_fn(
                            self.params, jnp.asarray(tokens),
                            jnp.asarray(lengths),
                            self._pool.k, self._pool.v,
                            jnp.asarray(bids),
                            jnp.asarray(pref_lens),
                            jnp.asarray(sbids), jnp.asarray(soffs),
                            sub)
                        self._pool.k, self._pool.v = pk, pv
                    else:
                        first_dev, self._cache = self._admit_seeded_fn(
                            self.params, jnp.asarray(tokens),
                            jnp.asarray(lengths),
                            self._prefix.pool["k"],
                            self._prefix.pool["v"],
                            jnp.asarray(bids.reshape(-1)),
                            jnp.asarray(pref_lens),
                            self._cache, jnp.asarray(slots), sub)
                else:
                    for i, (slot, req) in enumerate(batch):
                        r = row_of[i]
                        tokens[r, :plens[i]] = req.prompt
                        lengths[r] = plens[i]
                        slots[r] = slot
                    if self.paged:
                        rows = [(row_of[i], self._tables[slot], 0,
                                 plens[i])
                                for i, (slot, _r) in enumerate(batch)]
                        sbids, soffs = self._write_maps(rows, bucket, n)
                        first_dev, pk, pv = self._admit_paged_fn(
                            self.params, jnp.asarray(tokens),
                            jnp.asarray(lengths),
                            self._pool.k, self._pool.v,
                            jnp.asarray(sbids), jnp.asarray(soffs),
                            sub)
                        self._pool.k, self._pool.v = pk, pv
                    else:
                        first_dev, self._cache = self._admit_fn(
                            self.params, jnp.asarray(tokens),
                            jnp.asarray(lengths),
                            self._cache, jnp.asarray(slots), sub)
                first = _host_fetch(first_dev)     # the ONE host sync
        except Exception:
            # Lossless unwind (crash containment): the wave's requests
            # were popped from queue+free but never activated — put
            # them back at the queue head (order preserved) and release
            # the lookup pins, so an admit failure costs one retried
            # wave, never a lost request. (Retried lookups re-count in
            # the prefix stats; the savings ledger only counts
            # successful waves, so it stays honest.) Paged rows also
            # hand their freshly allocated owned blocks back.
            for i, (slot, req) in enumerate(batch):
                self._free.append(slot)
                if self.paged:
                    self._paged_release_slot(slot)
                if matches[i] is not None:
                    self._prefix.release(matches[i])
            self._queue[0:0] = [req for _slot, req in batch]
            raise
        prefill_s = time.monotonic() - t0
        self.admitted_s += prefill_s
        if self.telemetry is not None:
            self.telemetry.record_step(
                wave_kind, prefill_s, seq=seq, rows=len(batch),
                batch=n, tokens=sum(suffix_lens),
                padded_tokens=n * bucket, route=self._kv_route)
        self.prefill_tokens += sum(suffix_lens)
        self.prefill_tokens_saved += sum(
            m.tokens for m in matches if m is not None)
        if self.paged:
            self.paged_admits += len(batch)
            hits = sum(1 for m in matches if m is not None)
            self.zero_copy_admits += hits
            if hits and self.telemetry is not None:
                self.telemetry.on_zero_copy_admits(hits)
        for i, (slot, req) in enumerate(batch):
            tok = int(first[row_of[i]])
            if matches[i] is not None:
                # pinned until retirement: an active slot's seeded
                # prefix blocks must not be evicted out from under a
                # publish that will re-walk the same path
                self._prefix_pins[req.request_id] = matches[i]
            if self.telemetry is not None:
                self.telemetry.on_admit(
                    req.request_id, wave_start=t0,
                    admit_kind="seeded" if matches[i] is not None
                    else "wave",
                    prefix_hit_tokens=(matches[i].tokens
                                       if matches[i] is not None
                                       else 0))
            if (self.role == "prefill" and tok not in self._eos_set
                    and req.max_new_tokens > 1):
                # Disaggregated prefill role: the prompt KV is done and
                # the first token sampled — park for the block-granular
                # handoff instead of decoding here. The slot (and its
                # blocks) stay held until ``take_prefilled`` exports.
                self._park_handoff(slot, req, tok, plens[i], prefill_s)
                continue
            self._active[slot] = req
            self._generated[slot] = [tok]
            self._spec_track(slot, req, tok)
            self._positions[slot] = plens[i]
            self._next_tok[slot] = tok
            self._t_prefill[slot] = prefill_s
            req.decode_started_at = time.monotonic()
            if tok in self._eos_set or req.max_new_tokens <= 1:
                self._retire(slot,
                             "eos" if tok in self._eos_set else "length")

    def _req_digests(self, req: Request) -> list:
        if req.block_digests is None:
            req.block_digests = self._prefix.prompt_digests(req.prompt)
        return req.block_digests

    def _kv_bucket(self) -> int:
        """Static attention extent for the next decode dispatch: the
        occupied cache prefix rounded up to 128, so only a handful of
        decode programs ever compile. The dispatch's own fresh KV lives
        in the window/done buffers until the final merge, so the extent
        covers only what was in the cache BEFORE the dispatch."""
        # piggyback-prefilling rows have no cache prefix (whole rows
        # pack into one dispatch), so only active decode positions
        # constrain the extent
        hi = max([int(self._positions[s]) for s in self._active] + [0])
        return self._kv_extent(hi)

    def _kv_extent(self, hi: int) -> int:
        """Bucket an occupied-prefix extent to the 128-aligned static
        set (shared by the decode and chunked-prefill dispatches)."""
        if hi == 0:
            return min(128, self.max_len)
        bucket = min(-(-(hi + 1) // 128) * 128, self.max_len)
        # A bucket below the full extent makes the decode program slice
        # the cache's sequence axis — a STRIDED slice XLA materializes
        # as a full prefix copy (4.3 GB at 32x2304 — the rag2k OOM).
        # Near the extent the read saving cannot pay for that copy, so
        # snap to the full cache (slice = identity, zero-copy).
        if bucket * 8 >= self.max_len * 7:
            return self.max_len
        return bucket

    # ------------------------------------------------------------------
    # paged KV host plumbing (kv_pool_blocks > 0)
    # ------------------------------------------------------------------

    def _worst_blocks_total(self, req: Request) -> int:
        """Most blocks this request's slot can ever hold (borrowed +
        owned): its full timeline — prompt, generation budget, and the
        per-dispatch write margin — capped at the cache ceiling. The
        free-block admission accounting reserves this much headroom
        per admitted request, which is what makes mid-decode pool
        exhaustion structurally unreachable (the paged replacement for
        the contiguous engine's per-slot max_len reservation — an
        ACCOUNTING number now, not an allocation)."""
        span = min(len(req.prompt) + req.max_new_tokens
                   + self._write_margin, self.max_len)
        return self._pool.blocks_for(span)

    def _slot_shard(self, slot: int) -> int:
        """The dp shard a slot (and therefore every block in its
        table) lives on. Slots partition contiguously: shard s owns
        slots [s*slots_ps, (s+1)*slots_ps)."""
        return slot // self._slots_ps

    def _shard_headroom(self, shard: int) -> int:
        """Free + trie-evictable blocks of ONE dp shard minus what
        already-admitted work on that shard may still allocate.
        Admission (wave, seeded, chunked, handoff import) only places
        a request on a shard whose headroom fits its worst case."""
        need = 0
        for slot, req in self._active.items():
            if self._slot_shard(slot) == shard:
                need += max(0, self._worst_blocks_total(req)
                            - len(self._tables[slot]))
        for slot, entry in self._chunking.items():
            if self._slot_shard(slot) == shard:
                need += max(0, self._worst_blocks_total(entry[0])
                            - len(self._tables[slot]))
        evictable = self._prefixes[shard].evictable_blocks \
            if self._prefixes else 0
        return (self._pool.free_blocks_shard(shard) + evictable
                - need)

    def _block_headroom(self) -> int:
        """Pool-wide headroom: the sum of per-shard headrooms (one
        shard with mesh=None — the original global accounting)."""
        return sum(self._shard_headroom(s) for s in range(self._dp))

    def _alloc_blocks(self, n: int, shard: int = 0) -> list[int]:
        """Allocate ``n`` pool blocks on ``shard``, reclaiming idle
        prefix-cache leaves of THAT shard's trie first when its free
        list runs short — cached-but-idle prefixes yield to live
        timelines. Raises :class:`KVPoolExhausted` (classified as
        resource exhaustion by the supervisor) if the shard truly
        cannot serve, which the admission accounting makes
        unreachable on the serving path."""
        free = self._pool.free_blocks_shard(shard)
        if n > free and self._prefixes:
            self._prefixes[shard].reclaim(n - free)
        return self._pool.alloc(n, shard=shard)

    def _ensure_blocks(self, slot: int, upto: int) -> None:
        """Grow the slot's table to cover positions [0, upto) with
        blocks from the slot's own dp shard."""
        tbl = self._tables[slot]
        need = self._pool.blocks_for(upto) - len(tbl)
        if need > 0:
            tbl.extend(self._alloc_blocks(need,
                                          self._slot_shard(slot)))

    def _paged_release_slot(self, slot: int, keep=frozenset()) -> None:
        """Return the slot's OWNED blocks to the pool (minus any the
        trie adopted at publish) and clear its table. Borrowed entries
        are the trie's — the request's PrefixMatch release is their
        handback."""
        tbl = self._tables[slot]
        owned = [b for b in tbl[self._owned_from[slot]:]
                 if b not in keep]
        if owned:
            self._pool.free(owned)
        self._tables[slot] = []
        self._owned_from[slot] = 0

    # ------------------------------------------------------------------
    # disaggregated prefill/decode KV handoff (engine/roles.py)
    # ------------------------------------------------------------------

    def _park_handoff(self, slot: int, req: Request, first_tok: int,
                      prompt_len: int, prefill_s: float) -> None:
        """Prefill-role parking: the slot's blocks hold the finished
        prompt KV (plus the sampled first token on the host side)
        until ``take_prefilled`` exports them. Parked slots sit OOB
        for every decode dispatch, exactly like free slots."""
        self._positions[slot] = self.max_len
        self._handoff[slot] = [req, first_tok, prompt_len,
                               time.monotonic(), prefill_s]

    def set_handoff_external(self, n: int) -> None:
        """Report exported-but-unadmitted handoffs queued OUTSIDE this
        engine (the DisaggregatedEngine's pending list) so the
        release hold and the scheduler's ``handoff_backlog`` shed
        signal see the whole handoff pipeline's depth, not just the
        slot-capped parked set."""
        self._handoff_external = max(0, int(n))

    def take_prefilled(self, limit: int | None = None
                       ) -> list[PrefilledHandoff]:
        """Export parked finished prefills as block-granular KV
        handoffs (prefill role). Per slot: gather its blocks dense in
        ONE jitted read (global ids — GSPMD reads the dp-sharded pool
        directly), publish the prompt prefix to the slot's shard trie
        (later same-prefix prompts still hit on the prefill chips),
        then release pins + owned blocks and free the slot. The
        journal row retires here: from the prefill role's point of
        view the work is done once the handoff exists; the decode
        role re-journals it on import (docs/RESILIENCE.md)."""
        out: list[PrefilledHandoff] = []
        for slot in list(self._handoff):
            if limit is not None and len(out) >= limit:
                break
            req, tok, plen, ready_at, prefill_s = \
                self._handoff.pop(slot)
            tbl = list(self._tables[slot])
            nb = self._pool.blocks_for(plen)
            nbp = 1
            while nbp < nb:
                nbp *= 2
            bids = np.full((1, nbp), self._pool.num_blocks,
                           dtype=np.int32)     # OOB pad: clamped, dead
            bids[0, :nb] = tbl[:nb]
            with self._dispatch_boundary("kv_export"):
                kv_k, kv_v = self._export_fn(
                    self._pool.k, self._pool.v, jnp.asarray(bids))
            adopted: frozenset | set = frozenset()
            pc = self._prefixes[self._slot_shard(slot)] \
                if self._prefixes else None
            if pc is not None:
                try:
                    with self._dispatch_boundary("prefix_publish"):
                        adopted = pc.adopt_blocks(
                            req.prompt, tbl, self._owned_from[slot],
                            eligible_tokens=req.cache_eligible_tokens)
                except Exception:
                    self.prefix_publish_failures += 1
                finally:
                    m = self._prefix_pins.pop(req.request_id, None)
                    if m is not None:
                        pc.release(m)
            self._paged_release_slot(slot, keep=adopted)
            self._free.append(slot)
            self.handoff_exported += 1
            if self.telemetry is not None:
                self.telemetry.on_retire(req.request_id, new_tokens=1,
                                         finish_reason="handoff")
            if self.journal is not None:
                self.journal.record_retire(req.request_id)
                self._journal_ckpt.pop(req.request_id, None)
            out.append(PrefilledHandoff(
                request=req, first_token=tok, prompt_len=plen,
                kv_k=kv_k, kv_v=kv_v, blocks=nb, ready_at=ready_at,
                prefill_s=prefill_s))
        return out

    def admit_prefilled(self, handoff: PrefilledHandoff, *,
                        correlation_id: str | None = None
                        ) -> int | None:
        """Decode-role import: accept a handed-off finished prefill.
        Allocates fresh blocks on a dp shard with slot + headroom,
        moves the KV device-to-device onto this engine's mesh,
        scatters it into the new blocks (both pool halves donated),
        and activates the slot at ``positions == prompt_len`` with
        the already-sampled first token — decode continues
        bit-identically (greedy f32) to a co-located engine, because
        the handoff moved the exact KV bytes. Returns the new request
        id, or None when no slot/blocks fit right now — the caller
        re-parks the handoff, which is the backpressure signal toward
        the prefill role."""
        if not self.paged:
            raise ValueError("admit_prefilled requires kv_pool_blocks")
        if self.role == "prefill":
            raise ValueError(
                "admit_prefilled on a prefill-role engine")
        req0 = handoff.request
        plen = handoff.prompt_len
        span = min(plen + req0.max_new_tokens + self._write_margin,
                   self.max_len)
        need = self._pool.blocks_for(span)
        slot = None
        for s in range(self._dp):
            cand = next((x for x in self._free
                         if self._slot_shard(x) == s), None)
            if cand is not None and need <= self._shard_headroom(s) \
                    and self._occupied < self._slot_cap:
                slot = cand
                break
        if slot is None:
            return None
        rid = self._next_id
        self._next_id += 1
        corr = correlation_id if correlation_id is not None \
            else req0.correlation_id
        req = Request(
            rid, list(req0.prompt), req0.max_new_tokens,
            cache_eligible_tokens=req0.cache_eligible_tokens,
            correlation_id=corr, tenant=req0.tenant,
            priority=req0.priority, deadline_at=req0.deadline_at)
        if req.deadline_at != float("inf"):
            # submit() is never called on this path: arm the per-step
            # expiry sweep or a handed-off deadline would never fire
            self._deadlines_in_use = True
        if self.journal is not None:
            self.journal.record_submit(
                rid, req.prompt, req.max_new_tokens,
                cache_eligible_tokens=req.cache_eligible_tokens,
                correlation_id=corr, tenant=req.tenant,
                priority=req.priority)
            self.journal.checkpoint_many(
                [(rid, [handoff.first_token])])
            self._journal_ckpt[rid] = 1
        nb = self._pool.blocks_for(plen)
        tbl = self._alloc_blocks(nb, self._slot_shard(slot))
        width = handoff.kv_k.shape[3]       # NBpad * block
        sbids = np.full((1, width), self._pool.num_blocks,
                        dtype=np.int32)     # GLOBAL ids (plain jit)
        soffs = np.zeros((1, width), dtype=np.int32)
        pos = np.arange(plen)
        sbids[0, :plen] = np.asarray(tbl, dtype=np.int32)[
            pos // self._block]
        soffs[0, :plen] = pos % self._block
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            target = NamedSharding(self.mesh, PartitionSpec())
        else:
            target = jax.devices()[0]
        with self._dispatch_boundary("kv_import"):
            kv_k = jax.device_put(handoff.kv_k, target)
            kv_v = jax.device_put(handoff.kv_v, target)
            self._pool.k, self._pool.v = self._import_fn(
                self._pool.k, self._pool.v, kv_k, kv_v,
                jnp.asarray(sbids), jnp.asarray(soffs))
        self._free.remove(slot)
        self._tables[slot] = tbl
        self._owned_from[slot] = 0
        self.handoff_imported += 1
        now = time.monotonic()
        if self.telemetry is not None:
            self.telemetry.on_submit(rid, len(req.prompt), corr)
            self.telemetry.on_admit(rid, wave_start=now,
                                    admit_kind="handoff")
        tok = int(handoff.first_token)
        self._active[slot] = req
        self._generated[slot] = [tok]
        self._spec_track(slot, req, tok)
        self._positions[slot] = plen
        self._next_tok[slot] = tok
        self._t_prefill[slot] = handoff.prefill_s
        req.decode_started_at = now
        if tok in self._eos_set or req.max_new_tokens <= 1:
            self._retire(slot,
                         "eos" if tok in self._eos_set else "length")
        return rid

    def _gather_bids(self, width_tokens: int) -> "np.ndarray":
        """[num_slots, width/block] block-id view map for a read of
        ``width_tokens`` columns per slot; rows pad OOB past their
        table (clamped garbage, masked by lengths downstream).

        Ids are SHARD-LOCAL (``gid % blocks_per_shard`` — a slot's
        blocks never leave its dp shard, so the modulo IS the base
        subtraction) with the per-shard block count as the OOB
        sentinel: inside the dp shard_map each body indexes only its
        own pool slice. One shard (mesh=None) makes local == global
        and the sentinel == num_blocks, the original map."""
        from copilot_for_consensus_tpu.engine.kv_pool import (
            BLOCK_TABLE_DTYPE,
        )

        bps = self._pool.blocks_per_shard
        nb = -(-width_tokens // self._block)
        arr = np.full((self.num_slots, nb), bps,
                      dtype=BLOCK_TABLE_DTYPE)
        for s in range(self.num_slots):
            tbl = self._tables[s]
            n = min(nb, len(tbl))
            if n:
                arr[s, :n] = np.asarray(
                    tbl[:n], dtype=BLOCK_TABLE_DTYPE) % bps
        return arr

    def _write_maps(self, rows, width: int, n_rows: int):
        """Per-(row, column) pool write maps for one dispatch:
        ``rows`` is ``[(row_idx, table, start_pos, n_valid)]`` — column
        j of row i targets block ``table[(start+j) // block]`` offset
        ``(start+j) % block`` for j < n_valid; everything else carries
        the OOB block id and drops in the scatter. Ids are shard-local
        with the per-shard OOB sentinel (see ``_gather_bids``)."""
        from copilot_for_consensus_tpu.engine.kv_pool import (
            BLOCK_TABLE_DTYPE,
        )

        bps = self._pool.blocks_per_shard
        bids = np.full((n_rows, width), bps, dtype=BLOCK_TABLE_DTYPE)
        offs = np.zeros((n_rows, width), dtype=BLOCK_TABLE_DTYPE)
        for idx, tbl, start, n_valid in rows:
            # columns at/past max_len are dead padding in every
            # dispatch (the contiguous merge drops them OOB); masking
            # them here keeps the map inside the table
            n = min(n_valid, width, self.max_len - start)
            if n <= 0:
                continue
            pos = start + np.arange(n)
            bids[idx, :n] = np.asarray(tbl, dtype=BLOCK_TABLE_DTYPE)[
                pos // self._block] % bps
            offs[idx, :n] = pos % self._block
        return bids, offs

    def _view_width(self, kv_len: int, steps: int) -> int:
        """Gather-view width for a dispatch that reads ``kv_len``
        committed columns and writes up to ``steps`` more: block-
        rounded so the view's reshape stays exact."""
        blk = self._block
        return kv_len + (-(-steps // blk)) * blk

    def _used_tokens(self) -> int:
        """Live cache positions across the pool's owners: committed
        slot timelines (minus their borrowed prefix spans — those live
        in trie blocks and are counted once via node_count, not per
        borrower), chunk fills, and published blocks (always full)."""
        used = sum(int(self._positions[s])
                   - self._owned_from[s] * self._block
                   for s in self._active)
        used += sum(e[1] for e in self._chunking.values())
        # handoff-parked slots hold their prompt KV until exported
        used += sum(h[2] - self._owned_from[s] * self._block
                    for s, h in self._handoff.items())
        used += sum(p.node_count for p in self._prefixes) * self._block
        return used

    def kv_pool_stats(self) -> dict:
        """Paged-KV counters for benches/metrics (mirrors
        ``prefix_stats``). ``fragmentation_ratio`` is internal: the
        reserved-but-dead fraction of allocated blocks;
        ``zero_copy_hit_rate`` is seeded (pointer) admissions over all
        paged admissions. Stats/bench API — the per-step gauges read
        the pool counters directly instead (hot-path economy)."""
        out = {"enabled": self.paged}
        if not self.paged:
            return out
        used_tokens = self._used_tokens()
        out.update({
            "num_blocks": self._pool.num_blocks,
            "block_size": self._block,
            "free_blocks": self._pool.free_blocks,
            "blocks_in_use": self._pool.blocks_in_use,
            "pinned_blocks": self._pool.pinned_blocks,
            "fragmentation_ratio": round(
                self._pool.fragmentation(used_tokens), 4),
            "zero_copy_admits": self.zero_copy_admits,
            "paged_admits": self.paged_admits,
            "zero_copy_hit_rate": (
                self.zero_copy_admits / self.paged_admits
                if self.paged_admits else 0.0),
            "peak_active": self.peak_active,
            "headroom_blocks": self._block_headroom(),
            "dp_shards": self._dp,
            "role": self.role,
            "handoff_parked": len(self._handoff),
            "handoff_exported": self.handoff_exported,
            "handoff_imported": self.handoff_imported,
        })
        return out

    # ------------------------------------------------------------------
    # SLO-aware scheduling (engine/scheduler.py)
    # ------------------------------------------------------------------

    def _sched_cost(self, req: Request) -> int:
        """What this request will actually prefill: its prompt minus
        the prefix-cache match — the DRR charge AND the chunk-vs-wave
        routing size, so cached prompts cost their suffix. Sharded
        engines take the BEST match across the per-dp-shard tries
        (the admission router places the request on that shard)."""
        if not self._prefixes:
            return len(req.prompt)
        digs = self._req_digests(req)
        best = max(p.match_tokens(req.prompt, digests=digs)
                   for p in self._prefixes)
        return max(1, len(req.prompt) - best)

    def _placement_key(self, req: Request):
        """Prefix-cache-aware placement key: the first radix block
        digest. Requests sharing it open with the same block-aligned
        span, so co-scheduling them into one wave makes the whole wave
        ride the seeded path (or publish one shared prefix)."""
        if self._prefix is None:
            return None
        digs = self._req_digests(req)
        return digs[0] if digs else None

    def _sched_pump(self) -> None:
        """One scheduler turn: feed the closed loop, release at most
        one wave's token budget of requests (DRR order), route
        long-prompt cache misses to the chunked-prefill path."""
        sched = self._sched
        backlog = len(self._handoff) + self._handoff_external
        sched.observe(queued=self.queue_depth,
                      active=len(self._active),
                      num_slots=self.num_slots,
                      telemetry=self.telemetry,
                      free_blocks=(self._block_headroom()
                                   if self.paged else None),
                      total_blocks=(self._pool.num_blocks
                                    if self.paged else None),
                      handoff_backlog=(backlog
                                       if self.role == "prefill"
                                       else None))
        if self.role == "prefill" and backlog >= self._handoff_high:
            # Role-aware release hold: finished prefills are piling up
            # faster than the decode role drains them — releasing more
            # waves would only pin pool blocks behind the handoff.
            # Decode ITL on the decode chips stays flat; the shed loop
            # (handoff_backlog signal) handles the door.
            return
        staged = (len(self._queue) + len(self._prefilling)
                  + len(self._chunk_pending))
        room = len(self._free) - staged
        if room <= 0:
            return
        reqs = sched.select(max_requests=room,
                            token_budget=sched.cfg.prefill_wave_tokens,
                            cost_fn=self._sched_cost,
                            placement_key=self._placement_key)
        ct = sched.cfg.chunk_tokens
        for req in reqs:
            # Prefix-cache hits keep the seeded wave (the pool gather
            # and the chunk continuation cannot share one program);
            # long cache-miss prompts chunk. A hit shows as suffix
            # cost < prompt length — no extra radix walk (the digests
            # are memoized on the Request, but the walk isn't free).
            cost = self._sched_cost(req)
            if self._chunk_ok and cost >= len(req.prompt) \
                    and len(req.prompt) > ct:
                self._chunk_pending.append(req)
            else:
                self._queue.append(req)

    def _chunk_step(self) -> None:
        """One chunked-prefill continuation dispatch: every chunking
        slot advances by at most one chunk-bucket of prompt tokens;
        rows whose prompt completes activate into decode with their
        first token (sampled in-program from the last prompt
        position). Free/active rows park OOB and drop."""
        while self._chunk_pending and self._free \
                and self._occupied < self._slot_cap:
            if self.paged:
                # free-block accounting per dp shard: place the chunk
                # on a shard with a free slot AND headroom for its
                # worst case (first fit, shard order — chunked prompts
                # are cache misses, so there is no prefix to chase)
                need = self._worst_blocks_total(self._chunk_pending[0])
                slot = None
                for s in range(self._dp):
                    cand = next((x for x in self._free
                                 if self._slot_shard(x) == s), None)
                    if cand is not None \
                            and need <= self._shard_headroom(s):
                        slot = cand
                        break
                if slot is None:
                    break   # every shard's pool is full
                self._free.remove(slot)
                req = self._chunk_pending.pop(0)
            else:
                req = self._chunk_pending.pop(0)
                slot = self._free.pop(0)
            self._chunking[slot] = [req, 0, time.monotonic()]
        if not self._chunking:
            return
        t0 = time.monotonic()
        ct = self._chunk_buckets[-1]
        rem_max = max(len(req.prompt) - filled
                      for req, filled, _ in self._chunking.values())
        width = _next_bucket(min(rem_max, ct), self._chunk_buckets)
        tokens = np.zeros((self.num_slots, width), dtype=np.int32)
        qlens = np.ones((self.num_slots,), dtype=np.int32)
        positions = np.full((self.num_slots,), self.max_len,
                            dtype=np.int32)
        fed: dict[int, int] = {}
        hi = 0
        for slot, (req, filled, _started) in self._chunking.items():
            n = min(len(req.prompt) - filled, width)
            tokens[slot, :n] = req.prompt[filled:filled + n]
            qlens[slot] = n
            positions[slot] = filled
            fed[slot] = n
            hi = max(hi, filled)
        self._key, sub = jax.random.split(self._key)
        seq = self.telemetry.next_step() if self.telemetry is not None \
            else None
        # On failure the _chunking entries are untouched (fill offsets
        # only advance after a successful host fetch): an injected
        # fault retries the same chunk next step; a real device failure
        # is evacuated by the supervisor, which restarts chunking
        # requests from token zero (their partial fill is not trusted).
        with step_annotation("prefill_chunk", seq), \
                self._dispatch_boundary("prefill_chunk"):
            with quant.pallas_qmatmul_override(
                    self._decode_pallas_override):
                if self.paged:
                    kv_len = self._kv_extent(hi)
                    for slot, n in fed.items():
                        self._ensure_blocks(
                            slot, self._chunking[slot][1] + n)
                    rows = [(slot, self._tables[slot],
                             self._chunking[slot][1], n)
                            for slot, n in fed.items()]
                    sbids, soffs = self._write_maps(rows, width,
                                                    self.num_slots)
                    first_dev, pk, pv = self._chunk_paged_fn(
                        self.params,
                        jnp.asarray(tokens),
                        jnp.asarray(qlens),
                        jnp.asarray(positions),
                        self._pool.k, self._pool.v,
                        jnp.asarray(self._gather_bids(
                            self._view_width(kv_len, width))),
                        jnp.asarray(sbids), jnp.asarray(soffs),
                        sub,
                        kv_len=kv_len,
                    )
                    self._pool.k, self._pool.v = pk, pv
                else:
                    first_dev, self._cache = self._chunk_fn(
                        self.params,
                        jnp.asarray(tokens),
                        jnp.asarray(qlens),
                        jnp.asarray(positions),
                        self._cache,
                        sub,
                        kv_len=self._kv_extent(hi),
                    )
            first = _host_fetch(first_dev)
        step_s = time.monotonic() - t0
        self.chunk_s += step_s
        self.chunk_dispatches += 1
        now = time.monotonic()
        rows = len(fed)
        for slot in list(self._chunking):
            entry = self._chunking[slot]
            req, _filled, started = entry
            entry[1] += fed[slot]
            self.prefill_tokens += fed[slot]
            self.chunk_prefill_tokens += fed[slot]
            if entry[1] < len(req.prompt):
                continue
            del self._chunking[slot]
            tok = int(first[slot])
            if self.telemetry is not None:
                self.telemetry.on_admit(req.request_id,
                                        wave_start=started,
                                        admit_kind="chunked")
            if (self.role == "prefill" and tok not in self._eos_set
                    and req.max_new_tokens > 1):
                # chunked prefills hand off exactly like wave admits
                self._park_handoff(slot, req, tok, len(req.prompt),
                                   now - started)
                continue
            self._active[slot] = req
            self._generated[slot] = [tok]
            self._spec_track(slot, req, tok)
            self._positions[slot] = len(req.prompt)
            self._next_tok[slot] = tok
            self._t_prefill[slot] = now - started
            req.decode_started_at = now
            if tok in self._eos_set or req.max_new_tokens <= 1:
                self._retire(slot,
                             "eos" if tok in self._eos_set else "length")
        if self.telemetry is not None:
            self.telemetry.record_step(
                "prefill_chunk", step_s, seq=seq, rows=rows,
                batch=self.num_slots, tokens=sum(fed.values()),
                padded_tokens=self.num_slots * width,
                route=self._kv_route)
            self.telemetry.on_prefill_chunks(rows)

    def _decode_once(self) -> None:
        window = self._dispatch_steps
        # Speculation routes a step to the verify dispatch whenever any
        # active slot's draft index hits (the no-hit slots ride the
        # same program in the k=0 lane). Steps with piggyback chunks
        # pending keep the piggyback dispatch — its chunk grid and the
        # verify suffix cannot share one program — and draft-less
        # steps keep the plain windowed path: a window amortizes the
        # host sync over ``decode_window`` tokens, which beats a
        # 1-token verify dispatch when there is nothing to verify.
        # _spec_allowed consults the supervisor's spec_verify circuit
        # breaker: open → plain decode serves (degraded mode), half-
        # open → exactly this step may probe with a verify dispatch.
        if (self.spec_decode and self._active and self._spec_allowed()
                and not (self._prefilling and self._free)):
            drafts = self._spec_drafts()
            if drafts:
                self._dispatch_verify(drafts)
                return
        self._key, sub = jax.random.split(self._key)
        # Snapshot BEFORE dispatch: rows the piggyback path activates
        # mid-call were prefilling during this window — their decode
        # lanes carried garbage and must not be harvested this round.
        active_before = list(self._active.items())
        t0 = time.monotonic()
        piggy = bool(self._prefilling and self._free)
        step_kind = "piggyback" if piggy else "decode"
        seq = self.telemetry.next_step() if self.telemetry is not None \
            else None
        piggy_tok0 = self.piggy_tokens
        with step_annotation(step_kind, seq), \
                self._dispatch_boundary(step_kind):
            if piggy:
                toks = self._dispatch_piggyback(sub)
                self.piggy_s += time.monotonic() - t0
                self.piggy_dispatches += 1
            else:
                # the override (if any) is read at TRACE time; holding
                # it around the call bakes the qmatmul route into the
                # decode program without touching other programs/engines
                with quant.pallas_qmatmul_override(
                        self._decode_pallas_override):
                    if self.paged:
                        kv_len = self._kv_bucket()
                        for slot in self._active:
                            self._ensure_blocks(
                                slot, int(self._positions[slot])
                                + window)
                        rows = [(s, self._tables[s],
                                 int(self._positions[s]), window)
                                for s in self._active]
                        sbids, soffs = self._write_maps(
                            rows, window, self.num_slots)
                        toks, pk, pv = self._decode_paged_fn(
                            self.params,
                            jnp.asarray(self._next_tok),
                            jnp.asarray(self._positions),
                            self._pool.k, self._pool.v,
                            jnp.asarray(self._gather_bids(
                                self._view_width(kv_len, window))),
                            jnp.asarray(sbids), jnp.asarray(soffs),
                            sub,
                            kv_len=kv_len,
                            n_windows=self.windows_per_dispatch,
                        )
                        self._pool.k, self._pool.v = pk, pv
                    else:
                        toks, self._cache = self._decode_fn(
                            self.params,
                            jnp.asarray(self._next_tok),
                            jnp.asarray(self._positions),
                            self._cache,
                            sub,
                            kv_len=self._kv_bucket(),
                            n_windows=self.windows_per_dispatch,
                        )
                toks = _host_fetch(toks)                 # [steps, slots]
                self.plain_s += time.monotonic() - t0
                self.plain_dispatches += 1
        step_s = time.monotonic() - t0
        harvested_total = 0
        for slot, req in active_before:
            gen = self._generated[slot]
            harvested0 = len(gen)
            finished = None
            for step in range(window):
                tok = int(toks[step, slot])
                gen.append(tok)
                if tok in self._eos_set:
                    finished = "eos"
                    break
                if len(gen) >= req.max_new_tokens:
                    finished = "length"
                    break
            harvested_total += len(gen) - harvested0
            if self.spec_decode:
                # weight-pass ledger + draft index upkeep: a plain
                # window costs one weight pass PER STEP per row
                self._row_tokens += len(gen) - harvested0
                self._row_passes += window
                idx = self._draft_index.get(slot)
                if idx is not None:
                    idx.extend(gen[harvested0:])
            self._positions[slot] += window
            self._next_tok[slot] = int(toks[window - 1, slot])
            # Keep a full window of cache headroom: the next window writes
            # positions [pos, pos+window).
            if (finished is None
                    and self._positions[slot] + window > self.max_len - 1):
                finished = "length"
            if finished:
                self._retire(slot, finished)
        if self.telemetry is not None:
            # tokens: harvested decode tokens + any prompt tokens the
            # piggyback chunk grid prefilled this dispatch; the padded
            # grid is window × slots (every row advances every step)
            self.telemetry.record_step(
                step_kind, step_s, seq=seq, rows=len(active_before),
                batch=self.num_slots,
                tokens=harvested_total
                + (self.piggy_tokens - piggy_tok0),
                padded_tokens=window * self.num_slots,
                route=self._kv_route)

    def _spec_allowed(self) -> bool:
        """Spec-decode degraded-mode gate: the supervisor's
        ``spec_verify`` circuit breaker (open after repeated verify
        failures) vetoes the verify dispatch; plain decode serves."""
        sup = self.supervisor
        return sup is None or sup.spec_allowed()

    def _spec_track(self, slot: int, req: Request, first_tok: int
                    ) -> None:
        """Build the stream's draft index at activation (spec engines):
        once over the full context (prompt + first generated token),
        extended per accepted token from then on."""
        if not self.spec_decode:
            return
        idx = NgramDraftIndex(req.prompt, ngram=self.spec_ngram,
                              min_ngram=self.spec_min_ngram)
        idx.extend([first_tok])
        self._draft_index[slot] = idx

    def _spec_bucket(self, n: int) -> int:
        """Largest declared draft length <= n (0 = no draft). Buckets
        are the retrace bound: every verify program's token width is
        some declared length + 1."""
        best = 0
        for k in self.spec_draft_lens:
            if k <= n:
                best = max(best, k)
        return best

    def _spec_drafts(self) -> dict[int, list[int]]:
        """Prompt-lookup drafts for the next verify dispatch: per
        active slot, probe its n-gram index and clamp to the cache
        headroom (the verify writes KV at [pos, pos+k]). The DISPATCH
        width snaps to the declared bucket set (that is the retrace
        bound: the program shape is the width, not the per-row
        lengths), and only fires when some slot's draft reaches a
        nonzero bucket — but once it fires, shorter drafts ride the
        same program for free via the per-row qlens masking, so a
        3-token draft still earns its tokens on an 8-wide wave.
        Empty dict = the step falls through to the plain windowed
        path (a window amortizes the host sync; a 1-token verify
        doesn't)."""
        cands: dict[int, list[int]] = {}
        k_max = 0
        for slot in self._active:
            idx = self._draft_index.get(slot)
            if idx is None:
                continue
            self.spec_lookups += 1
            d = idx.draft(self._spec_max_draft)
            room = self.max_len - 1 - int(self._positions[slot])
            d = d[:max(0, room)]
            if d:
                cands[slot] = d
                k_max = max(k_max, self._spec_bucket(len(d)))
        if k_max == 0:
            return {}
        drafts = {}
        for slot, d in cands.items():
            drafts[slot] = d[:k_max]
            self.spec_hits += 1
            self.spec_drafted_tokens += len(drafts[slot])
        return drafts

    def _dispatch_verify(self, drafts: dict[int, list[int]]) -> None:
        """One verify dispatch: every active slot's committed next
        token plus its (possibly empty) draft, one weight pass,
        exact accept/rewind on the host side."""
        k_max = max(len(d) for d in drafts.values())
        s = k_max + 1
        active_before = list(self._active.items())
        tokens = np.zeros((self.num_slots, s), dtype=np.int32)
        tokens[:, 0] = self._next_tok
        qlens = np.ones((self.num_slots,), dtype=np.int32)
        for slot, d in drafts.items():
            tokens[slot, 1:1 + len(d)] = d
            qlens[slot] = len(d) + 1
        self._key, sub = jax.random.split(self._key)
        t0 = time.monotonic()
        seq = self.telemetry.next_step() if self.telemetry is not None \
            else None
        with step_annotation("verify", seq), \
                self._dispatch_boundary("verify"):
            with quant.pallas_qmatmul_override(
                    self._decode_pallas_override):
                if self.paged:
                    kv_len = self._kv_bucket()
                    # The dispatch width s is global; near-cap rows'
                    # columns past max_len are dead padding (the
                    # contiguous merge drops them OOB) — cap the table
                    # growth at max_len so no slot ever allocates past
                    # its admission-time worst-case reservation.
                    for slot in self._active:
                        self._ensure_blocks(
                            slot, min(int(self._positions[slot]) + s,
                                      self.max_len))
                    rows = [(sl, self._tables[sl],
                             int(self._positions[sl]), s)
                            for sl in self._active]
                    sbids, soffs = self._write_maps(rows, s,
                                                    self.num_slots)
                    out_dev, acc_dev, pk, pv = self._verify_paged_fn(
                        self.params,
                        jnp.asarray(tokens),
                        jnp.asarray(qlens),
                        jnp.asarray(self._positions),
                        self._pool.k, self._pool.v,
                        jnp.asarray(self._gather_bids(
                            self._view_width(kv_len, s))),
                        jnp.asarray(sbids), jnp.asarray(soffs),
                        sub,
                        kv_len=kv_len,
                    )
                    self._pool.k, self._pool.v = pk, pv
                else:
                    out_dev, acc_dev, self._cache = self._verify_fn(
                        self.params,
                        jnp.asarray(tokens),
                        jnp.asarray(qlens),
                        jnp.asarray(self._positions),
                        self._cache,
                        sub,
                        kv_len=self._kv_bucket(),
                    )
            out = _host_fetch(out_dev)                     # [slots, S]
            acc = _host_fetch(acc_dev)                     # [slots]
        step_s = time.monotonic() - t0
        self.spec_s += step_s
        self.spec_dispatches += 1
        accepted0 = self.spec_accepted_tokens
        emitted0 = self.spec_emitted_tokens
        for slot, req in active_before:
            m = int(acc[slot]) + 1        # emitted: accepts + 1 sample
            self.spec_accepted_tokens += m - 1
            self.spec_rows += 1
            self._row_passes += 1
            gen = self._generated[slot]
            emitted = [int(t) for t in out[slot, :m]]
            finished = None
            kept = 0
            for tok in emitted:
                gen.append(tok)
                kept += 1
                if tok in self._eos_set:
                    finished = "eos"
                    break
                if len(gen) >= req.max_new_tokens:
                    finished = "length"
                    break
            self.spec_emitted_tokens += kept
            self._row_tokens += kept
            self._draft_index[slot].extend(emitted[:kept])
            # Rewind/advance the committed length to the accept point:
            # cache columns [pos+m, pos+k_max] hold rejected-draft KV,
            # dead by the prefix-length masking until the next write
            # lands on them (see _verify).
            self._positions[slot] += m
            self._next_tok[slot] = emitted[m - 1]
            if (finished is None
                    and self._positions[slot] + self._dispatch_steps
                    > self.max_len - 1):
                finished = "length"
            if finished:
                self._retire(slot, finished)
        if self.telemetry is not None:
            self.telemetry.record_step(
                "verify", step_s, seq=seq, rows=len(active_before),
                batch=self.num_slots,
                tokens=self.spec_emitted_tokens - emitted0,
                padded_tokens=s * self.num_slots,
                draft_tokens=sum(len(d) for d in drafts.values()),
                accepted_tokens=self.spec_accepted_tokens - accepted0,
                route=self._kv_route)

    def _pack_prefill(self):
        """Pack whole pending prompts into the W×P chunk grid.

        Each selected row occupies one consecutive run of steps in one
        lane (its buffer span is contiguous, so the flash begin/length
        bounds describe it exactly). First-fit over lanes; rows that
        don't fit wait for the next dispatch. Returns the per-step
        metadata arrays, the completion list, the buffer→cache scatter
        maps, and the selected (slot, req, started, lane, end_step)
        rows — everything ``_piggy_fn`` needs, all host-built.
        """
        w_sz, chunk = self.decode_window, self.prefill_chunk
        p = self.prefill_rows
        buf = w_sz * chunk
        m_sel = w_sz * p                       # max completions
        pre_tok = np.zeros((w_sz, p, chunk), dtype=np.int32)
        rope_base = np.zeros((w_sz, p), dtype=np.int32)
        kv_begin = np.full((w_sz, p), buf, dtype=np.int32)   # idle: all
        kv_len = np.zeros((w_sz, p), dtype=np.int32)         # masked
        sel_rel = np.zeros((w_sz, p), dtype=np.int32)
        sel_w = np.zeros(m_sel, dtype=np.int32)
        sel_p = np.zeros(m_sel, dtype=np.int32)
        sidx = np.full((p, buf), self.num_slots, dtype=np.int32)  # OOB
        pidx = np.full((p, buf), self.max_len, dtype=np.int32)
        lane_next = [0] * p
        placed = []
        deferred = []
        for req, started in self._prefilling:
            plen = len(req.prompt)
            steps = -(-plen // chunk)
            lane = min(range(p), key=lambda i: lane_next[i])
            if (lane_next[lane] + steps > w_sz or not self._free
                    or self._occupied + len(placed) >= self._slot_cap):
                deferred.append((req, started))
                continue                        # wait for next dispatch
            slot = self._free.pop(0)
            s0 = lane_next[lane]
            lane_next[lane] = s0 + steps
            flat = np.zeros(steps * chunk, dtype=np.int32)
            flat[:plen] = req.prompt
            pre_tok[s0:s0 + steps, lane] = flat.reshape(steps, chunk)
            rope_base[s0:s0 + steps, lane] = np.arange(steps) * chunk
            kv_begin[s0:s0 + steps, lane] = s0 * chunk
            kv_len[s0:s0 + steps, lane] = s0 * chunk + np.minimum(
                (np.arange(steps) + 1) * chunk, plen)
            end = s0 + steps - 1
            sel_rel[end, lane] = (plen - 1) % chunk
            sel_w[len(placed)] = end
            sel_p[len(placed)] = lane
            sidx[lane, s0 * chunk:s0 * chunk + plen] = slot
            pidx[lane, s0 * chunk:s0 * chunk + plen] = np.arange(plen)
            placed.append((slot, req, started, len(placed)))
            self.piggy_rows += 1
            self.piggy_tokens += plen
            self.prefill_tokens += plen
        self._prefilling = deferred
        return (pre_tok, rope_base, kv_begin, kv_len, sel_rel, sel_w,
                sel_p, sidx, pidx, placed)

    def _dispatch_piggyback(self, key) -> np.ndarray:
        """One decode window with packed prefill chunks riding it.
        Returns the decoded tokens [window, slots]; completed prompts
        are activated into their slots here."""
        (pre_tok, rope_base, kv_begin, kv_len, sel_rel, sel_w, sel_p,
         sidx, pidx, placed) = self._pack_prefill()
        try:
            toks = self._piggy_dispatch(
                key, pre_tok, rope_base, kv_begin, kv_len, sel_rel,
                sel_w, sel_p, sidx, pidx, placed)
        except Exception:
            # Lossless unwind (crash containment): packed rows took
            # slots and left _prefilling but never activated — requeue
            # them (queue head) and free their slots, and back out the
            # accounting _pack_prefill charged for work that never ran.
            for slot, req, _started, _i in placed:
                self._free.append(slot)
            self._queue[0:0] = [req for _s, req, _t, _i in placed]
            n_tok = sum(len(req.prompt) for _s, req, _t, _i in placed)
            self.piggy_rows -= len(placed)
            self.piggy_tokens -= n_tok
            self.prefill_tokens -= n_tok
            raise
        return toks

    def _piggy_dispatch(self, key, pre_tok, rope_base, kv_begin,
                        kv_len, sel_rel, sel_w, sel_p, sidx, pidx,
                        placed) -> np.ndarray:
        with quant.pallas_qmatmul_override(self._decode_pallas_override):
            toks_dev, first_dev, self._cache = self._piggy_fn(
                self.params,
                jnp.asarray(self._next_tok),
                jnp.asarray(self._positions),
                self._cache,
                key,
                jnp.asarray(pre_tok),
                jnp.asarray(rope_base),
                jnp.asarray(kv_begin),
                jnp.asarray(kv_len),
                jnp.asarray(sel_rel),
                jnp.asarray(sel_w),
                jnp.asarray(sel_p),
                jnp.asarray(sidx),
                jnp.asarray(pidx),
                kv_len=self._kv_bucket(),
            )
        toks = _host_fetch(toks_dev)
        first = _host_fetch(first_dev)
        now = time.monotonic()
        for slot, req, started, i in placed:
            # every placed row completed (whole prompts only); its
            # first generated token was sampled in-program from the
            # last prompt position
            tok = int(first[i])
            if self.telemetry is not None:
                self.telemetry.on_admit(req.request_id,
                                        wave_start=started,
                                        admit_kind="piggyback")
            self._active[slot] = req
            self._generated[slot] = [tok]
            self._spec_track(slot, req, tok)
            self._positions[slot] = len(req.prompt)
            self._next_tok[slot] = tok
            self._t_prefill[slot] = now - started
            req.decode_started_at = now
            if tok in self._eos_set or req.max_new_tokens <= 1:
                self._retire(slot,
                             "eos" if tok in self._eos_set else "length")
        return toks

    def _retire(self, slot: int, reason: str) -> None:
        self._positions[slot] = self.max_len   # park OOB (see __init__)
        self._draft_index.pop(slot, None)
        req = self._active.pop(slot)
        adopted: frozenset | set = frozenset()
        pc = self._prefixes[self._slot_shard(slot)] \
            if self._prefixes else None
        if pc is not None:
            # Publish BEFORE the slot returns to the free list: the
            # cache still holds this prompt's KV at [0, plen). Prompt
            # KV is temperature-independent (it never saw a sampled
            # token), so it is safe to share across sampling configs.
            # A publish failure is CONTAINED here (counted, pin still
            # released): it loses only this prompt's cache
            # contribution, and must never take the completion — or
            # the whole step — down with it.
            try:
                with self._dispatch_boundary("prefix_publish"):
                    if self.paged:
                        # Refcount handoff, zero device work: the
                        # slot's own shard's trie adopts its
                        # prompt-prefix blocks by id
                        # (docs/ENGINE_PREFIX_CACHE.md).
                        adopted = pc.adopt_blocks(
                            req.prompt, self._tables[slot],
                            self._owned_from[slot],
                            eligible_tokens=req.cache_eligible_tokens)
                    else:
                        pc.publish(
                            req.prompt, self._cache, slot,
                            eligible_tokens=req.cache_eligible_tokens)
            except Exception:
                self.prefix_publish_failures += 1
            finally:
                m = self._prefix_pins.pop(req.request_id, None)
                if m is not None:
                    pc.release(m)
        if self.paged:
            # tail blocks (generated-token KV + unpublished prompt
            # tail) go straight back to the allocator
            self._paged_release_slot(slot, keep=adopted)
        gen = self._generated.pop(slot)
        if gen and gen[-1] in self._eos_set:
            gen = gen[:-1]
        self._done[req.request_id] = Completion(
            request_id=req.request_id,
            prompt_len=len(req.prompt),
            tokens=gen,
            finish_reason=reason,
            prefill_s=self._t_prefill.pop(slot, 0.0),
            decode_s=time.monotonic() - req.decode_started_at,
        )
        if self.telemetry is not None:
            self.telemetry.on_retire(req.request_id,
                                     new_tokens=len(gen),
                                     finish_reason=reason)
            # ledger gauges at retire cadence: the stats are cumulative
            # engine-wide counters, so per-step export buys nothing
            self.telemetry.update_ledgers(
                self.prefix_stats() if self._prefix is not None
                else None,
                self.spec_stats() if self.spec_decode else None)
        self._free.append(slot)

    def _journal_tick(self) -> None:
        """Incremental token checkpoints (engine/journal.py): every
        ``checkpoint_every`` decode steps, and on any step that retired
        a request (``per-retire``: the surviving slots' progress is
        durable before the completed work's rows delete). Also exports
        the journal gauges."""
        j = self.journal
        self._journal_steps += 1
        if self._active and (self._done
                             or self._journal_steps
                             >= j.checkpoint_every):
            self._journal_steps = 0
            pairs = []
            for slot, req in self._active.items():
                gen = self._generated.get(slot)
                if gen:
                    pairs.append((req.request_id, gen))
                    self._journal_ckpt[req.request_id] = len(gen)
            if pairs:
                j.checkpoint_many(pairs)
        if self.telemetry is not None:
            lag = 0
            for slot, req in self._active.items():
                gen = self._generated.get(slot)
                if gen:
                    lag = max(lag, len(gen) - self._journal_ckpt.get(
                        req.request_id, 0))
            self.telemetry.gauge_journal(j.depth(), lag)

    def _drain_done(self) -> list[Completion]:
        out = []
        for c in self._done.values():
            st = self._journal_stitch.pop(c.request_id, None)
            if st is not None:
                # Stitch the continuation back onto the ORIGINAL
                # identity (the runner's _ReplayState move, one level
                # down): the harvester sees one completion with the
                # original prompt length and the full token stream.
                plen, prefix = st
                c = Completion(
                    request_id=c.request_id, prompt_len=plen,
                    tokens=prefix + c.tokens,
                    finish_reason=c.finish_reason,
                    prefill_s=c.prefill_s, decode_s=c.decode_s)
            out.append(c)
        if self.journal is not None and out:
            # Retire at harvest: the row leaves the journal in the same
            # step() call that returns the completion. A SIGKILL inside
            # this window replays the request — at-least-once, absorbed
            # by the pipeline supersede contract (docs/RESILIENCE.md).
            for c in out:
                self.journal.record_retire(c.request_id)
                self._journal_ckpt.pop(c.request_id, None)
        self._done.clear()
        return out

    def _recover_from_journal(self) -> int:
        """Warm restart (construction time, single-owner thread):
        resubmit every unfinished journaled request as a
        prompt+generated continuation through the normal submit path —
        scheduler ledgers and telemetry spans rebuild as a side effect
        — and re-key each row onto its continuation id. Requests whose
        wall-clock deadline expired during the outage complete as
        honest ``finish_reason="deadline"`` drops; continuations that
        no longer fit ``prompt_limit`` are abandoned (counted), never
        silently head-truncated into divergence."""
        from copilot_for_consensus_tpu.obs import trace as _trace

        entries = self.journal.unfinished()
        if not entries:
            return 0
        # Continuation ids must never collide with journaled ids: a
        # fresh engine counts from 0, and a reused id would make the
        # supersede re-key and the retire delete hit the WRONG row.
        self._next_id = max(self._next_id,
                            max(e.request_id for e in entries) + 1)
        now_wall = time.time()
        self._journal_recovering = True
        self._journal_suppress = True
        try:
            for e in entries:
                done = min(len(e.tokens), e.max_new_tokens)
                remaining = e.max_new_tokens - done
                if e.deadline_wall and e.deadline_wall <= now_wall:
                    self.deadline_expired += 1
                    self._done[e.request_id] = Completion(
                        request_id=e.request_id,
                        prompt_len=len(e.prompt),
                        tokens=list(e.tokens)[:done],
                        finish_reason="deadline")
                    continue
                if remaining <= 0:
                    # Fully generated before the crash (which landed
                    # between the final checkpoint and the retire):
                    # emit, don't recompute.
                    self._done[e.request_id] = Completion(
                        request_id=e.request_id,
                        prompt_len=len(e.prompt),
                        tokens=list(e.tokens)[:e.max_new_tokens],
                        finish_reason="length")
                    continue
                prompt = list(e.prompt) + list(e.tokens)
                if len(prompt) > self.prompt_limit:
                    # submit() would head-truncate and the continuation
                    # would diverge from the fault-free stream — honest
                    # abandonment over silent divergence.
                    self.journal.record_abandon(e.request_id)
                    self.journal_abandoned += 1
                    continue
                kw: dict = {}
                if e.deadline_wall:
                    kw["deadline_s"] = e.deadline_wall - now_wall
                rid = self.submit(
                    prompt, remaining,
                    cache_eligible_tokens=e.cache_eligible_tokens,
                    correlation_id=e.correlation_id, tenant=e.tenant,
                    priority=e.priority or "interactive", **kw)
                self.journal.supersede(e.request_id, rid, e.tokens)
                self._journal_stitch[rid] = (len(e.prompt),
                                             list(e.tokens))
                self._journal_ckpt[rid] = 0
                self.journal_recovered.append((rid, e.correlation_id))
                self.journal_replayed += 1
                if self.telemetry is not None:
                    self.telemetry.on_journal_replayed()
                if e.trace_id and e.span_id:
                    # attempt-numbered replay annotation in the
                    # ORIGINATING pipeline trace (never a fresh orphan
                    # root — parentless recoveries skip the span)
                    with _trace.span(
                            "engine_replay", kind="engine_replay",
                            service="engine",
                            correlation_id=e.correlation_id,
                            attempt=e.attempt + 1,
                            parent=(e.trace_id, e.span_id),
                            request_id=rid, restart=True):
                        pass
        finally:
            self._journal_recovering = False
            self._journal_suppress = False
        return self.journal_replayed


# ---------------------------------------------------------------------------
# shardcheck contracts (analysis/shardcheck.py)
# ---------------------------------------------------------------------------


@checkable("generation-engine")
def _shardcheck_generation_engine():
    """Declare the engine's jitted programs on a tiny config (CPU-built
    in well under a second) and verify, by tracing:

    * every ``donate_argnums`` entry aliases a shape/dtype-matching
      output (an undonated slot cache double-allocates per dispatch);
    * admit / seeded admit / decode / piggyback / verify / prefix-pool
      publish all agree on ONE KV-cache layout (L, Hkv, Dh, dtype) —
      the cache is handed between these six programs every serving
      step;
    * the prefill bucket table covers the longest admissible prompt
      (``prompt_limit``), and the verify dispatch's token-width table
      covers every declared speculative draft length, both bounding
      compile count.

    The tiny shapes don't weaken the checks: layout agreement, alias
    feasibility, and bucket coverage are shape-RELATION properties, and
    the relations here are the same ones the serving-size engine
    builds.

    Cases carrying an ``hlo=HloSpec(...)`` are ADDITIONALLY lowered and
    compiled by the post-lowering pass (analysis/hlocheck.py): donated
    args must survive as compiled input_output_alias entries, the
    kernel route must lower with no pool-working-set gather, sharded
    dispatches must keep their declared collective counts, and every
    dispatch's compiled memory peak is gated (budgets carry ~2×
    headroom over the measured tiny-config peak — see
    docs/artifacts/HLO_BUDGETS.json for the measured numbers)."""
    import functools

    from copilot_for_consensus_tpu.models.configs import DecoderConfig

    cfg = DecoderConfig(name="shardcheck-tiny", vocab_size=64,
                        d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
                        d_ff=64, max_seq_len=128)
    eng = GenerationEngine(cfg, num_slots=4, max_len=64,
                           prefill_buckets=(16, 32), decode_window=4,
                           windows_per_dispatch=1, prefill_chunk=8,
                           prefill_rows=2, prefix_cache_blocks=4,
                           spec_decode=True, spec_draft_lens=(0, 2, 4))

    def aval(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)

    S = jax.ShapeDtypeStruct
    i32 = jnp.int32
    cache = aval(eng._cache)
    pool = aval(eng._prefix.pool)
    key = jax.random.PRNGKey(0)
    n, bucket, w, p, chunk = 4, 16, eng.decode_window, eng.prefill_rows, \
        eng.prefill_chunk
    group = "engine.generation-kv"
    return [
        ContractCase(
            label="admit", fn=eng._admit_fn,
            args=(eng.params, S((n, bucket), i32), S((n,), i32), cache,
                  S((n,), i32), key),
            donate_argnums=(3,), kv_group=group,
            kv_caches=(("slot-cache", cache),),
            buckets=eng.buckets, bucket_covers=(eng.prompt_limit,),
            hlo=HloSpec(peak_bytes=470_000)),
        ContractCase(
            label="admit-seeded", fn=eng._admit_seeded_fn,
            args=(eng.params, S((n, bucket), i32), S((n,), i32),
                  pool["k"], pool["v"], S((n * 2,), i32), S((n,), i32),
                  cache, S((n,), i32), key),
            donate_argnums=(7,), kv_group=group,
            kv_caches=(("slot-cache", cache), ("prefix-pool", pool))),
        ContractCase(
            label="decode",
            fn=functools.partial(eng._decode_fn, kv_len=eng.max_len,
                                 n_windows=1),
            args=(eng.params, S((eng.num_slots,), i32),
                  S((eng.num_slots,), i32), cache, key),
            donate_argnums=(3,), kv_group=group,
            kv_caches=(("slot-cache", cache),),
            hlo=HloSpec(peak_bytes=470_000)),
        ContractCase(
            label="verify",
            # token width = largest declared draft length + 1 (the
            # committed next token); the bucket table is the declared
            # draft-length set so a new spec_draft_lens entry must be
            # covered here or the lane goes red
            fn=functools.partial(eng._verify_fn, kv_len=eng.max_len),
            args=(eng.params,
                  S((eng.num_slots, max(eng.spec_draft_lens) + 1), i32),
                  S((eng.num_slots,), i32), S((eng.num_slots,), i32),
                  cache, key),
            donate_argnums=(4,), kv_group=group,
            kv_caches=(("slot-cache", cache),),
            buckets=tuple(k + 1 for k in eng.spec_draft_lens),
            bucket_covers=(max(eng.spec_draft_lens) + 1,),
            hlo=HloSpec(peak_bytes=510_000)),
        ContractCase(
            label="piggyback",
            fn=functools.partial(eng._piggy_fn, kv_len=eng.max_len),
            args=(eng.params, S((eng.num_slots,), i32),
                  S((eng.num_slots,), i32), cache, key,
                  S((w, p, chunk), i32), S((w, p), i32), S((w, p), i32),
                  S((w, p), i32), S((w, p), i32), S((w * p,), i32),
                  S((w * p,), i32), S((p, w * chunk), i32),
                  S((p, w * chunk), i32)),
            donate_argnums=(3,), kv_group=group,
            kv_caches=(("slot-cache", cache),)),
        ContractCase(
            label="prefix-publish", fn=eng._prefix._publish_fn,
            args=(pool, cache["k"], cache["v"], S((2,), i32),
                  S((2, chunk), i32), S((2, chunk), i32)),
            donate_argnums=(0,), kv_group=group,
            kv_caches=(("prefix-pool", pool),)),
    ] + _paged_contract_cases(cfg, group) \
        + _paged_mesh_contract_cases(cfg, group)


def _paged_contract_cases(cfg, group):
    """The paged engine's dispatch contracts (kv_pool_blocks > 0):

    * every paged dispatch donates BOTH pool halves (the one long-lived
      KV allocation — a dropped alias double-buffers the whole pool);
    * the pool rides the same ``engine.generation-kv`` layout group as
      the contiguous slot cache (one (L, Hkv, Dh, dtype) convention
      under both layouts — the bit-identity gate depends on it);
    * block tables form their own ``engine.generation-kv-table`` layout
      group: the anchor case declares the canonical
      ``kv_pool.BLOCK_TABLE_DTYPE`` and every dispatch's table must
      match it — flipping the dispatch-side table dtype (the tripwire
      in tests/test_shardcheck.py) is a ``shard-kv-layout`` finding;
    * the KERNEL route's dispatches (``kv_kernel="pallas"``) declare
      into the SAME ``engine.generation-kv`` group with the same
      donations and the same table dtype — the two routes must agree
      on one pool layout, or the ``kv_kernel`` knob would silently
      change serving semantics;
    * block packing forms the ``engine.generation-kv-pack`` layout
      group: the anchor declares the kernel's
      ``ops.paged_attention.KERNEL_BLOCK_PACK``, the pool layout
      declares ``kv_pool.POOL_BLOCK_PACK``, and the dispatch side
      declares its own literal — flipping any one of the three (the
      block-pack tripwire) is a ``shard-kv-layout`` finding;
    * the KERNEL route's dispatches additionally declare an
      ``hlo-materialize`` fingerprint (no gather at/above the pool
      working-set size in the lowered StableHLO) — the gather
      elimination PR 16 shipped is a CONTRACT here, not a test detail,
      and re-introducing a ``paged_gather_kv`` call turns the hlo lane
      red; the reference route declares the same budget family WITHOUT
      the fingerprint (its gather is the design being replaced) so the
      two routes' compiled peaks stay individually gated;
    * the ``program-cache`` case lowers one variant per declared
      bucket (prefill buckets × verify draft widths × the chunk
      program) and pins the distinct-program count to the literal
      cross-product — widening any bucket table without updating the
      declaration is an ``hlo-program-cache`` finding.
    """
    import functools

    from copilot_for_consensus_tpu.engine.kv_pool import (
        BLOCK_TABLE_DTYPE,
        POOL_BLOCK_PACK,
    )
    from copilot_for_consensus_tpu.ops.paged_attention import (
        KERNEL_BLOCK_PACK,
    )

    eng = GenerationEngine(cfg, num_slots=4, max_len=64,
                           prefill_buckets=(16, 32), decode_window=4,
                           windows_per_dispatch=1, prefill_chunk=8,
                           prefill_rows=2, prefix_cache_blocks=4,
                           kv_pool_blocks=16, spec_decode=True,
                           spec_draft_lens=(0, 2, 4))
    eng_k = GenerationEngine(cfg, num_slots=4, max_len=64,
                             prefill_buckets=(16, 32), decode_window=4,
                             windows_per_dispatch=1, prefill_chunk=8,
                             prefill_rows=2, prefix_cache_blocks=4,
                             kv_pool_blocks=16, kv_kernel="pallas",
                             spec_decode=True, spec_draft_lens=(0, 2, 4))
    S = jax.ShapeDtypeStruct
    i32 = jnp.int32
    table_dtype = jnp.int32       # dispatch-side block-table dtype
    block_pack = 128              # dispatch-side kernel lane packing
    pool = {"k": S(eng._pool.k.shape, eng._pool.k.dtype),
            "v": S(eng._pool.v.shape, eng._pool.v.dtype)}
    key = jax.random.PRNGKey(0)
    n, bucket = 4, 16
    b = eng.num_slots
    w = eng._dispatch_steps
    s_v = max(eng.spec_draft_lens) + 1
    kv_len = 64
    nb_view = eng._view_width(kv_len, w) // eng._block
    tgroup = "engine.generation-kv-table"
    pgroup = "engine.generation-kv-pack"
    # hlo-materialize fingerprint: one gather materializing the pool
    # working set (L × B × Hkv × kv_len × Dh result elements) is the
    # paged_gather_kv pattern the kernel route exists to eliminate;
    # legitimate small gathers (embedding lookup: B × bucket × d_model
    # = 2048 elements here) sit well below the threshold
    ws_elems = cfg.n_layers * b * cfg.n_kv_heads * kv_len * cfg.head_dim
    no_gather = (("gather", ws_elems),)

    def tbl(rows, width):
        return S((rows, width), table_dtype)

    return [
        # the canonical table layout, declared FIRST so it is the
        # group's reference signature (kv_pool.BLOCK_TABLE_DTYPE)
        ContractCase(
            label="paged-table-layout", kv_group=tgroup,
            kv_caches=(("block-table",
                        {"table": S((b, nb_view),
                                    jnp.dtype(BLOCK_TABLE_DTYPE))}),)),
        ContractCase(
            label="admit-paged", fn=eng._admit_paged_fn,
            args=(eng.params, S((n, bucket), i32), S((n,), i32),
                  pool["k"], pool["v"], tbl(n, bucket), tbl(n, bucket),
                  key),
            donate_argnums=(3, 4), kv_group=group,
            kv_caches=(("kv-pool", pool),),
            buckets=eng.buckets, bucket_covers=(eng.prompt_limit,),
            # admission scatters into the pool; it must never gather
            # the working set back out on EITHER route
            hlo=HloSpec(forbid_ops=no_gather, peak_bytes=440_000)),
        ContractCase(
            label="admit-seeded-paged", fn=eng._admit_seeded_paged_fn,
            args=(eng.params, S((n, bucket), i32), S((n,), i32),
                  pool["k"], pool["v"], S((n, 2), i32), S((n,), i32),
                  tbl(n, bucket), tbl(n, bucket), key),
            donate_argnums=(3, 4), kv_group=group,
            kv_caches=(("kv-pool", pool),)),
        ContractCase(
            label="decode-paged",
            fn=functools.partial(eng._decode_paged_fn, kv_len=kv_len,
                                 n_windows=1),
            args=(eng.params, S((b,), i32), S((b,), i32),
                  pool["k"], pool["v"],
                  S((b, nb_view), jnp.dtype(BLOCK_TABLE_DTYPE)),
                  tbl(b, w), tbl(b, w), key),
            donate_argnums=(3, 4), kv_group=group,
            kv_caches=(("kv-pool", pool),),
            # the REFERENCE route gathers its working set by design —
            # no forbid_ops; the peak budget documents (and caps) the
            # materialization cost the kernel route removes (measured
            # 327K vs the kernel decode's 189K)
            hlo=HloSpec(peak_bytes=650_000)),
        ContractCase(
            label="decode-paged-table", kv_group=tgroup,
            kv_caches=(("block-table",
                        {"table": S((b, nb_view), table_dtype)}),)),
        ContractCase(
            label="verify-paged",
            fn=functools.partial(eng._verify_paged_fn, kv_len=kv_len),
            args=(eng.params, S((b, s_v), i32), S((b,), i32),
                  S((b,), i32), pool["k"], pool["v"],
                  S((b, eng._view_width(kv_len, s_v) // eng._block),
                    jnp.dtype(BLOCK_TABLE_DTYPE)),
                  tbl(b, s_v), tbl(b, s_v), key),
            donate_argnums=(4, 5), kv_group=group,
            kv_caches=(("kv-pool", pool),),
            buckets=tuple(k + 1 for k in eng.spec_draft_lens),
            bucket_covers=(max(eng.spec_draft_lens) + 1,),
            hlo=HloSpec(peak_bytes=700_000)),
        ContractCase(
            label="chunk-paged",
            fn=functools.partial(eng._chunk_paged_fn, kv_len=kv_len),
            args=(eng.params, S((b, eng._block), i32), S((b,), i32),
                  S((b,), i32), pool["k"], pool["v"],
                  S((b, eng._view_width(kv_len, eng._block)
                     // eng._block), jnp.dtype(BLOCK_TABLE_DTYPE)),
                  tbl(b, eng._block), tbl(b, eng._block), key),
            donate_argnums=(4, 5), kv_group=group,
            kv_caches=(("kv-pool", pool),)),
        # ---- Pallas kernel route (kv_kernel="pallas"): the same four
        # gathering dispatches rebound over the in-place kernel, same
        # signatures, same donations, same pool layout group — route
        # selection must never change the serving contract ----------
        ContractCase(
            label="admit-seeded-paged-kernel",
            fn=eng_k._admit_seeded_paged_fn,
            args=(eng_k.params, S((n, bucket), i32), S((n,), i32),
                  pool["k"], pool["v"], S((n, 2), i32), S((n,), i32),
                  tbl(n, bucket), tbl(n, bucket), key),
            donate_argnums=(3, 4), kv_group=group,
            kv_caches=(("kv-pool", pool),),
            hlo=HloSpec(forbid_ops=no_gather, peak_bytes=460_000)),
        ContractCase(
            label="decode-paged-kernel",
            fn=functools.partial(eng_k._decode_paged_fn, kv_len=kv_len,
                                 n_windows=1),
            args=(eng_k.params, S((b,), i32), S((b,), i32),
                  pool["k"], pool["v"],
                  S((b, nb_view), jnp.dtype(BLOCK_TABLE_DTYPE)),
                  tbl(b, w), tbl(b, w), key),
            donate_argnums=(3, 4), kv_group=group,
            kv_caches=(("kv-pool", pool),),
            # PR 16's gather-elimination guarantee, as a contract: the
            # kernel decode lowers with NO working-set gather
            hlo=HloSpec(forbid_ops=no_gather, peak_bytes=380_000)),
        ContractCase(
            label="decode-paged-kernel-table", kv_group=tgroup,
            kv_caches=(("block-table",
                        {"table": S((b, nb_view), table_dtype)}),)),
        ContractCase(
            label="verify-paged-kernel",
            fn=functools.partial(eng_k._verify_paged_fn,
                                 kv_len=kv_len),
            args=(eng_k.params, S((b, s_v), i32), S((b,), i32),
                  S((b,), i32), pool["k"], pool["v"],
                  S((b, eng_k._view_width(kv_len, s_v) // eng_k._block),
                    jnp.dtype(BLOCK_TABLE_DTYPE)),
                  tbl(b, s_v), tbl(b, s_v), key),
            donate_argnums=(4, 5), kv_group=group,
            kv_caches=(("kv-pool", pool),),
            buckets=tuple(k + 1 for k in eng_k.spec_draft_lens),
            bucket_covers=(max(eng_k.spec_draft_lens) + 1,),
            hlo=HloSpec(forbid_ops=no_gather, peak_bytes=360_000)),
        ContractCase(
            label="chunk-paged-kernel",
            fn=functools.partial(eng_k._chunk_paged_fn, kv_len=kv_len),
            args=(eng_k.params, S((b, eng_k._block), i32),
                  S((b,), i32), S((b,), i32), pool["k"], pool["v"],
                  S((b, eng_k._view_width(kv_len, eng_k._block)
                     // eng_k._block), jnp.dtype(BLOCK_TABLE_DTYPE)),
                  tbl(b, eng_k._block), tbl(b, eng_k._block), key),
            donate_argnums=(4, 5), kv_group=group,
            kv_caches=(("kv-pool", pool),),
            hlo=HloSpec(forbid_ops=no_gather, peak_bytes=380_000)),
        # ---- block packing (engine.generation-kv-pack): kernel-side
        # KERNEL_BLOCK_PACK (anchor), pool-side POOL_BLOCK_PACK, and
        # the dispatch-side literal must all name the same lane width
        # — the pool layout, the kernel BlockSpecs, and the engine's
        # bucket alignment are compiled against it independently ----
        ContractCase(
            label="kernel-block-pack-layout", kv_group=pgroup,
            kv_caches=(("block-pack",
                        {"pack": S((KERNEL_BLOCK_PACK,), i32)}),)),
        ContractCase(
            label="pool-block-pack", kv_group=pgroup,
            kv_caches=(("block-pack",
                        {"pack": S((POOL_BLOCK_PACK,), i32)}),)),
        ContractCase(
            label="dispatch-block-pack", kv_group=pgroup,
            kv_caches=(("block-pack",
                        {"pack": S((block_pack,), i32)}),)),
        # ---- program-cache cardinality: one variant per declared
        # bucket; the distinct compiled-program count must equal the
        # LITERAL cross-product below. Widening prefill_buckets or
        # spec_draft_lens (or chunking by a new width) without
        # updating this declaration is an hlo-program-cache finding —
        # the silent version of that drift is a retrace explosion ----
        ContractCase(
            label="program-cache",
            hlo=HloSpec(
                variants=tuple(
                    (f"admit@{bk}", eng._admit_paged_fn,
                     (eng.params, S((n, bk), i32), S((n,), i32),
                      pool["k"], pool["v"], tbl(n, bk), tbl(n, bk),
                      key))
                    for bk in eng.buckets
                ) + tuple(
                    (f"verify@{k + 1}",
                     functools.partial(eng._verify_paged_fn,
                                       kv_len=kv_len),
                     (eng.params, S((b, k + 1), i32), S((b,), i32),
                      S((b,), i32), pool["k"], pool["v"],
                      S((b, eng._view_width(kv_len, k + 1)
                         // eng._block),
                        jnp.dtype(BLOCK_TABLE_DTYPE)),
                      tbl(b, k + 1), tbl(b, k + 1), key))
                    for k in eng.spec_draft_lens
                ) + (
                    ("chunk@block",
                     functools.partial(eng._chunk_paged_fn,
                                       kv_len=kv_len),
                     (eng.params, S((b, eng._block), i32),
                      S((b,), i32), S((b,), i32), pool["k"],
                      pool["v"],
                      S((b, eng._view_width(kv_len, eng._block)
                         // eng._block),
                        jnp.dtype(BLOCK_TABLE_DTYPE)),
                      tbl(b, eng._block), tbl(b, eng._block), key)),
                ),
                # 2 prefill buckets + 3 verify draft widths + 1 chunk
                expected_programs=6)),
    ]


def _paged_mesh_contract_cases(cfg, group):
    """The MESH-sharded paged dispatch contracts (kv_pool_blocks > 0 on
    a dp×tp mesh — ISSUE 15):

    * every sharded dispatch still donates BOTH pool halves through
      the outer jit (the shard_map indirection must not cost the pool
      a double-buffer);
    * the sharded pool rides the same ``engine.generation-kv`` layout
      group as the single-device pool and the contiguous slot cache —
      dp/tp sharding must never change the (L, Hkv, Dh, dtype)
      convention the bit-identity gate depends on;
    * the pool's PartitionSpec is declared as a divisibility contract:
      the BLOCK axis must divide dp (per-shard allocators own equal
      slices); kv-heads replicate here (tiny config: tp ∤ Hkv — the
      same fallback rule the engine applies);
    * the dispatch-side block tables keep the canonical
      ``kv_pool.BLOCK_TABLE_DTYPE`` under dp sharding
      (``engine.generation-kv-table`` group membership);
    * the KV handoff import (disaggregated roles) donates both pool
      halves like every other pool writer;
    * the two decode dispatches (reference and kernel route) declare
      exact ``hlo-collective-budget`` counts: GSPMD reshard insertion
      — the RoPE-miscompile class — shows up as a changed collective
      count in the compiled program long before a TPU run shows it as
      a wrong answer or a step-time cliff. The budgets are the
      compiled ground truth of this mesh/config; a legitimate
      partitioning change updates them HERE, next to the declaration,
      never in the baseline file.
    """
    import functools

    from copilot_for_consensus_tpu.analysis.contracts import (
        require_devices,
    )
    from copilot_for_consensus_tpu.engine.kv_pool import (
        BLOCK_TABLE_DTYPE,
    )
    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    require_devices(8)
    mesh = build_mesh(MeshConfig(dp=2, tp=4))
    eng = GenerationEngine(cfg, num_slots=4, max_len=64,
                           prefill_buckets=(16, 32), decode_window=4,
                           windows_per_dispatch=1, prefill_chunk=8,
                           prefix_cache_blocks=4, kv_pool_blocks=32,
                           spec_decode=True, spec_draft_lens=(0, 2, 4),
                           mesh=mesh)
    eng_k = GenerationEngine(cfg, num_slots=4, max_len=64,
                             prefill_buckets=(16, 32), decode_window=4,
                             windows_per_dispatch=1, prefill_chunk=8,
                             prefix_cache_blocks=4, kv_pool_blocks=32,
                             kv_kernel="pallas", spec_decode=True,
                             spec_draft_lens=(0, 2, 4), mesh=mesh)
    S = jax.ShapeDtypeStruct
    i32 = jnp.int32
    pool = {"k": S(eng._pool.k.shape, eng._pool.k.dtype),
            "v": S(eng._pool.v.shape, eng._pool.v.dtype)}
    key = jax.random.PRNGKey(0)
    n, bucket = 4, 16
    b = eng.num_slots
    w = eng._dispatch_steps
    s_v = max(eng.spec_draft_lens) + 1
    kv_len = 64
    nb_view = eng._view_width(kv_len, w) // eng._block
    tgroup = "engine.generation-kv-table"
    # the pool's PartitionSpec as a divisibility contract: blocks/dp
    pool_logical = {"k": (None, "kv_blocks", "kv_heads", None, None),
                    "v": (None, "kv_blocks", "kv_heads", None, None)}
    pool_rules = {"kv_blocks": "dp",
                  # tiny config: tp ∤ Hkv → replicated, the engine's
                  # own fallback (BlockPool.spec does the same)
                  "kv_heads": None}

    def tbl(rows, width):
        return S((rows, width), jnp.dtype(BLOCK_TABLE_DTYPE))

    return [
        ContractCase(
            label="pool-partition-spec", mesh=mesh, rules=pool_rules,
            logical=(("kv-pool-mesh", pool, pool_logical),)),
        ContractCase(
            label="admit-paged-mesh", fn=eng._admit_paged_fn,
            args=(eng.params, S((n, bucket), i32), S((n,), i32),
                  pool["k"], pool["v"], tbl(n, bucket),
                  tbl(n, bucket), key),
            donate_argnums=(3, 4), kv_group=group,
            kv_caches=(("kv-pool-mesh", pool),),
            buckets=eng.buckets, bucket_covers=(eng.prompt_limit,)),
        ContractCase(
            label="admit-seeded-paged-mesh",
            fn=eng._admit_seeded_paged_fn,
            args=(eng.params, S((n, bucket), i32), S((n,), i32),
                  pool["k"], pool["v"], tbl(n, 2), S((n,), i32),
                  tbl(n, bucket), tbl(n, bucket), key),
            donate_argnums=(3, 4), kv_group=group,
            kv_caches=(("kv-pool-mesh", pool),)),
        ContractCase(
            label="decode-paged-mesh",
            fn=functools.partial(eng._decode_paged_fn, kv_len=kv_len,
                                 n_windows=1),
            args=(eng.params, S((b,), i32), S((b,), i32),
                  pool["k"], pool["v"], tbl(b, nb_view),
                  tbl(b, w), tbl(b, w), key),
            donate_argnums=(3, 4), kv_group=group,
            kv_caches=(("kv-pool-mesh", pool),),
            hlo=HloSpec(
                collectives={"all-reduce": 5, "all-gather": 10,
                             "collective-permute": 8,
                             "all-to-all": 1},
                peak_bytes=240_000)),
        ContractCase(
            label="decode-paged-mesh-table", kv_group=tgroup,
            kv_caches=(("block-table",
                        {"table": tbl(b, nb_view)}),)),
        ContractCase(
            label="verify-paged-mesh",
            fn=functools.partial(eng._verify_paged_fn, kv_len=kv_len),
            args=(eng.params, S((b, s_v), i32), S((b,), i32),
                  S((b,), i32), pool["k"], pool["v"],
                  tbl(b, eng._view_width(kv_len, s_v) // eng._block),
                  tbl(b, s_v), tbl(b, s_v), key),
            donate_argnums=(4, 5), kv_group=group,
            kv_caches=(("kv-pool-mesh", pool),),
            buckets=tuple(k + 1 for k in eng.spec_draft_lens),
            bucket_covers=(max(eng.spec_draft_lens) + 1,)),
        ContractCase(
            label="chunk-paged-mesh",
            fn=functools.partial(eng._chunk_paged_fn, kv_len=kv_len),
            args=(eng.params, S((b, eng._block), i32), S((b,), i32),
                  S((b,), i32), pool["k"], pool["v"],
                  tbl(b, eng._view_width(kv_len, eng._block)
                      // eng._block),
                  tbl(b, eng._block), tbl(b, eng._block), key),
            donate_argnums=(4, 5), kv_group=group,
            kv_caches=(("kv-pool-mesh", pool),)),
        ContractCase(
            label="kv-handoff-import", fn=eng._import_fn,
            args=(pool["k"], pool["v"],
                  S((cfg.n_layers, 1, cfg.n_kv_heads, 16,
                     cfg.head_dim), eng.kv_dtype),
                  S((cfg.n_layers, 1, cfg.n_kv_heads, 16,
                     cfg.head_dim), eng.kv_dtype),
                  S((1, 16), i32), S((1, 16), i32)),
            donate_argnums=(0, 1), kv_group=group,
            kv_caches=(("kv-pool-mesh", pool),),
            hlo=HloSpec(peak_bytes=140_000)),
        # ---- kernel route under the mesh: the shard-mapped partial
        # keeps the dp-sharded pool donated and the shard-local block
        # tables on the canonical dtype (same layout groups — the
        # route knob changes how blocks are read, never the sharded
        # pool contract) -------------------------------------------
        ContractCase(
            label="decode-paged-mesh-kernel",
            fn=functools.partial(eng_k._decode_paged_fn,
                                 kv_len=kv_len, n_windows=1),
            args=(eng_k.params, S((b,), i32), S((b,), i32),
                  pool["k"], pool["v"], tbl(b, nb_view),
                  tbl(b, w), tbl(b, w), key),
            donate_argnums=(3, 4), kv_group=group,
            kv_caches=(("kv-pool-mesh", pool),),
            # the kernel route reads pool blocks in place — fewer
            # gather-side collectives than the reference route above
            hlo=HloSpec(
                collectives={"all-reduce": 3, "all-gather": 6},
                peak_bytes=175_000)),
        ContractCase(
            label="decode-paged-mesh-kernel-table", kv_group=tgroup,
            kv_caches=(("block-table",
                        {"table": tbl(b, nb_view)}),)),
        ContractCase(
            label="verify-paged-mesh-kernel",
            fn=functools.partial(eng_k._verify_paged_fn,
                                 kv_len=kv_len),
            args=(eng_k.params, S((b, s_v), i32), S((b,), i32),
                  S((b,), i32), pool["k"], pool["v"],
                  tbl(b, eng_k._view_width(kv_len, s_v)
                      // eng_k._block),
                  tbl(b, s_v), tbl(b, s_v), key),
            donate_argnums=(4, 5), kv_group=group,
            kv_caches=(("kv-pool-mesh", pool),),
            buckets=tuple(k + 1 for k in eng_k.spec_draft_lens),
            bucket_covers=(max(eng_k.spec_draft_lens) + 1,)),
    ]
