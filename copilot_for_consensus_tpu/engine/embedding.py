"""Cross-text-batching embedding engine.

Replaces the sentence-transformers role in the reference's embedding
service — and fixes its central inefficiency: the reference embeds one
text at a time inside its "batch" loop
(``embedding/app/service.py:284,393`` — per-text ``embed()``, no
cross-text batching). Here texts are tokenized, grouped into
(batch, bucket) tiles with a handful of static shapes, and pushed through
the encoder in single MXU passes; the dp mesh axis shards the batch.
"""

from __future__ import annotations

import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from copilot_for_consensus_tpu.engine.faults import resolve_faults
from copilot_for_consensus_tpu.engine.scheduler import resolve_scheduler
from copilot_for_consensus_tpu.engine.telemetry import resolve_telemetry
from copilot_for_consensus_tpu.engine.tokenizer import (
    HashWordTokenizer,
    Tokenizer,
)
from copilot_for_consensus_tpu.models import encoder
from copilot_for_consensus_tpu.models.configs import EncoderConfig
from copilot_for_consensus_tpu.obs.profile import step_annotation
from copilot_for_consensus_tpu.parallel.sharding import shard_pytree


class EmbeddingEngine:
    """Batched text → vector encoder."""

    def __init__(
        self,
        cfg: EncoderConfig,
        params: Any | None = None,
        *,
        mesh=None,
        tokenizer: Tokenizer | None = None,
        batch_size: int = 64,
        buckets: tuple[int, ...] = (32, 64, 128, 256, 512),
        seed: int = 0,
        dtype=jnp.bfloat16,
        attn_impl: str = "auto",
        telemetry: Any = True,
        scheduler: Any = None,
        faults: Any = None,
    ):
        self.cfg = cfg
        self.mesh = mesh
        # Fault-injection plane (engine/faults.py): the chaos harness
        # scripts kind="embed" faults against the encode dispatch
        # boundary. Share the generation engine's injector to chaos
        # both engines under one seeded plan.
        self.faults = resolve_faults(faults)
        # Step telemetry (engine/telemetry.py): one StepRecord per
        # encode dispatch (kind="embed") with tile occupancy and
        # bucket-padding waste — the embedding engine has no request
        # lifecycle, so spans stay on the generation side.
        self.telemetry = resolve_telemetry(telemetry, engine="embedding",
                                           num_slots=batch_size)
        # SLO-aware scheduler (engine/scheduler.py): the embedding
        # engine has no request queue, so the scheduler's role here is
        # batch SIZING and burst shedding — oversized embed bursts get
        # an honest EngineOverloaded (→ 429 / bus retry) and, under
        # overload, encode tiles shrink so a burst yields the host
        # loop between dispatches. Pass the GENERATION engine's
        # Scheduler instance to close the loop across engines: embed
        # bursts then back off exactly when chat traffic is hurting.
        self.scheduler = resolve_scheduler(scheduler,
                                           telemetry=self.telemetry)
        self.batch_size = batch_size
        self.buckets = tuple(sorted(set(
            min(b, cfg.max_positions) for b in buckets)))
        self.tokenizer = tokenizer or HashWordTokenizer(cfg.vocab_size)
        if self.tokenizer.vocab_size > cfg.vocab_size:
            raise ValueError(
                f"tokenizer vocab {self.tokenizer.vocab_size} exceeds "
                f"encoder vocab {cfg.vocab_size}")
        if params is None:
            params = encoder.init_params(jax.random.PRNGKey(seed), cfg,
                                         dtype=dtype)
        if mesh is not None:
            params = shard_pytree(params, encoder.logical_axes(cfg), mesh)
        self.params = params
        self._encode_fn = jax.jit(
            lambda p, t, l: encoder.encode(p, t, l, cfg,
                                           attn_impl=attn_impl))

    @classmethod
    def from_checkpoint(cls, path: str, *, mesh=None, tokenizer=None,
                        batch_size: int = 64,
                        buckets: tuple[int, ...] = (32, 64, 128, 256, 512),
                        dtype: str = "float32",
                        attn_impl: str = "auto") -> "EmbeddingEngine":
        """Serve real encoder weights: a BERT/MiniLM-family HF checkpoint
        dir (config.json + model.safetensors [+ tokenizer.json]) → a
        ready engine. The weights role of the reference's
        ``sentence_transformer_provider.py:19-51`` without the
        sentence-transformers/torch dependency."""
        import pathlib

        from copilot_for_consensus_tpu.checkpoint import (
            load_hf_encoder_checkpoint,
        )

        cfg, params = load_hf_encoder_checkpoint(path, dtype)
        if tokenizer is None:
            tok_file = pathlib.Path(path) / "tokenizer.json"
            if tok_file.exists():
                from copilot_for_consensus_tpu.engine.tokenizer import (
                    HFTokenizer,
                )
                tokenizer = HFTokenizer(str(tok_file), bos_id=0, eos_id=0)
                pad = tokenizer._tok.token_to_id("[PAD]")
                tokenizer.pad_id = 0 if pad is None else int(pad)
            else:
                # WordPiece ids are meaningless to any fallback tokenizer;
                # refuse instead of silently serving garbage vectors.
                raise ValueError(
                    f"checkpoint {path} has no tokenizer.json; pass "
                    "tokenizer= explicitly")
        params = {k: (jnp.asarray(v) if not isinstance(v, dict) else
                      {kk: jnp.asarray(vv) for kk, vv in v.items()})
                  for k, v in params.items()}
        return cls(cfg, params, mesh=mesh, tokenizer=tokenizer,
                   batch_size=batch_size, buckets=buckets,
                   dtype=params["tok_emb"].dtype, attn_impl=attn_impl)

    @property
    def dimension(self) -> int:
        return self.cfg.d_model

    def embed(self, text: str) -> list[float]:
        """Single-text parity with the reference's
        ``EmbeddingProvider.embed(text) -> list[float]``
        (``copilot_embedding/base.py:12-25``)."""
        return self.embed_batch([text])[0].tolist()

    def embed_batch(self, texts: Sequence[str], *, tenant: str = "",
                    correlation_id: str = "") -> np.ndarray:
        """[N] texts → [N, dim] fp32, L2-normalized. Order preserved.

        With a scheduler configured, the call is admission-checked
        (oversized bursts shed with ``EngineOverloaded``) and the
        per-dispatch tile rows come from ``Scheduler.embed_admit`` —
        smaller under overload, so one burst cannot monopolize the
        device while latency-sensitive traffic is suffering."""
        if not texts:
            return np.zeros((0, self.cfg.d_model), dtype=np.float32)
        rows_cap = self.batch_size
        if self.scheduler is not None:
            rows_cap = self.scheduler.embed_admit(
                len(texts), tenant=tenant, batch_size=self.batch_size,
                correlation_id=correlation_id)
        max_bucket = self.buckets[-1]
        encoded: list[list[int]] = []
        for t in texts:
            ids = self.tokenizer.encode(t)[:max_bucket]
            encoded.append(ids or [self.tokenizer.pad_id])

        out = np.zeros((len(texts), self.cfg.d_model), dtype=np.float32)
        # Group indices by bucket so each jitted shape sees full tiles.
        by_bucket: dict[int, list[int]] = {}
        for i, ids in enumerate(encoded):
            b = next(bb for bb in self.buckets if len(ids) <= bb)
            by_bucket.setdefault(b, []).append(i)

        for bucket, idxs in by_bucket.items():
            for start in range(0, len(idxs), rows_cap):
                group = idxs[start:start + rows_cap]
                n = len(group)
                # Row count pads to the next power of two (bounds the
                # compile-shape count at log2(batch_size) per bucket —
                # the same discipline as the generation engine's
                # admission wave), so a scheduler-shrunk tile really
                # is a smaller program, not a full-width tile with
                # more padding.
                rows = 1
                while rows < n:
                    rows *= 2
                rows = min(rows, self.batch_size)
                tokens = np.zeros((rows, bucket), dtype=np.int32)
                lengths = np.ones(rows, dtype=np.int32)
                for row, i in enumerate(group):
                    ids = encoded[i]
                    tokens[row, :len(ids)] = ids
                    lengths[row] = len(ids)
                if self.faults is not None:
                    # host dispatch boundary — never inside jitted code
                    self.faults.check("embed")
                seq = self.telemetry.next_step() \
                    if self.telemetry is not None else None
                t0 = time.monotonic()
                with step_annotation("embed", seq):
                    vecs = self._encode_fn(self.params,
                                           jnp.asarray(tokens),
                                           jnp.asarray(lengths))
                    out[group] = np.asarray(jax.device_get(vecs))[:n]
                if self.telemetry is not None:
                    self.telemetry.record_step(
                        "embed", time.monotonic() - t0, seq=seq,
                        rows=n, batch=rows,
                        tokens=int(lengths[:n].sum()),
                        padded_tokens=rows * bucket)
        return out
