"""Cross-request prefix KV-cache reuse (radix prefix cache).

The serving workload is prefix-heavy: every summarization prompt opens
with the same system prompt + template head, and thread re-summarization
re-sends a mostly-identical context prefix. The engine's admission path
nevertheless prefilled every prompt from token zero. This module is the
vLLM-automatic-prefix-caching / SGLang-RadixAttention idea rebuilt for
this engine's contiguous slot cache:

* **Host-side radix trie.** Prompts are cut into fixed token blocks
  (block size = the engine's ``prefill_chunk``). Each trie node is one
  block, keyed by a CHAINED digest (``tokenizer.stable_block_hash``) so
  a node commits to its entire token prefix — longest-prefix match is a
  hash walk from the root, no token comparisons on the hot path.
* **Bounded device block pool.** Node KV lives in a device-resident
  pool ``[L, num_blocks, Hkv, block, Dh]`` in the serving cache dtype.
  The pool is fixed-size; when full, the least-recently-used *leaf*
  with refcount 0 is evicted (leaves only: an interior eviction would
  orphan descendants that can then never be matched — the standard
  radix-cache discipline).
* **Refcount pinning.** ``lookup`` pins every matched node until the
  request retires (``release``); ``publish`` temp-pins the path while
  it allocates, so eviction can never free a block an admission wave is
  about to gather or a publish is mid-way through chaining.
* **Publish on completion.** When a request retires, the block-aligned
  prefix of its PROMPT (never generated tokens — those depend on
  sampling; prompt KV is temperature-independent) is inserted into the
  trie and its KV copied cache→pool in one jitted scatter. Callers may
  cap eligibility (``eligible_tokens``) to e.g. the shared template
  span so thread-unique tails don't churn the bounded pool.

The trie/accounting is pure host Python; the only device code is the
publish copy here and the seeded admission gather in
``GenerationEngine`` — everything is exercisable on CPU
(``JAX_PLATFORMS=cpu``), which is how the correctness and token-savings
tests run (``tests/test_engine_prefix_cache.py``).

Scope: single-process engines (``mesh=None``). A dp-sharded slot cache
would put pool blocks and slots on different shards; cross-shard block
copies are future work and the engine refuses the combination loudly.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from copilot_for_consensus_tpu.analysis.contracts import checkable
from copilot_for_consensus_tpu.engine.tokenizer import stable_block_hash


class _Node:
    """One cached block: a radix-trie edge + its pool block id."""

    __slots__ = ("digest", "parent", "children", "block_id", "refcount",
                 "last_used")

    def __init__(self, digest: bytes, parent: "_Node | None",
                 block_id: int):
        self.digest = digest
        self.parent = parent
        self.children: dict[bytes, _Node] = {}
        self.block_id = block_id
        self.refcount = 0
        self.last_used = 0

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"_Node(block={self.block_id}, ref={self.refcount}, "
                f"children={len(self.children)})")


@dataclass
class PrefixMatch:
    """A pinned longest-prefix match. Hold it while the request is
    active; hand it back through ``PrefixCache.release`` on retire."""

    nodes: list[_Node]
    block_ids: list[int]
    tokens: int                     # == len(block_ids) * block_size


@dataclass
class PrefixCacheStats:
    lookups: int = 0
    hits: int = 0                   # lookups matching >= 1 block
    misses: int = 0
    tokens_matched: int = 0         # prompt tokens NOT re-prefilled
    blocks_published: int = 0
    blocks_evicted: int = 0
    publish_skips: int = 0          # pool full of pinned/interior blocks

    def as_dict(self) -> dict:
        return {f: getattr(self, f) for f in self.__dataclass_fields__}


@dataclass
class _PoolPrograms:
    """Jitted device programs, built once per (shape, dtype)."""

    publish: object = field(default=None)


class PrefixCache:
    """Radix trie + bounded device block pool + LRU/refcount policy.

    Two ownership modes:

    * **Owned pool** (default, the contiguous engine): this cache owns
      its ``pool`` arrays and free list; ``publish`` COPIES prompt KV
      cache→pool on retire (one jitted scatter).
    * **Shared pool** (``shared=`` a :class:`engine.kv_pool.BlockPool`,
      the paged engine): the trie references blocks of the engine-wide
      pool by id. Publish becomes :meth:`adopt_blocks` — a refcount
      HANDOFF of the retiring slot's own blocks, no device copy — and
      eviction returns blocks to the shared allocator. The trie pins
      each adopted block once in the pool for itself; per-request match
      pins stay node-level exactly as before.
    """

    def __init__(self, cfg, *, num_blocks: int, block_size: int,
                 kv_dtype=jnp.bfloat16, shared=None):
        if num_blocks < 1:
            raise ValueError("prefix cache needs num_blocks >= 1")
        if block_size < 1:
            raise ValueError("prefix cache needs block_size >= 1")
        self.cfg = cfg
        self.block = int(block_size)
        self.shared = shared
        if shared is not None:
            if shared.block != self.block:
                raise ValueError(
                    f"shared pool block size {shared.block} != prefix "
                    f"cache block size {block_size}")
            self.num_blocks = shared.num_blocks
            self.kv_dtype = shared.kv_dtype
            self.pool = None          # the engine owns the arrays
            self._free = None
        else:
            self.num_blocks = int(num_blocks)
            self.kv_dtype = kv_dtype
            shape = (cfg.n_layers, num_blocks, cfg.n_kv_heads,
                     block_size, cfg.head_dim)
            #: device-resident KV blocks; ``num_blocks`` is the OOB
            #: sentinel id (gathers clamp, scatters drop).
            self.pool = {"k": jnp.zeros(shape, kv_dtype),
                         "v": jnp.zeros(shape, kv_dtype)}
            self._free: list[int] | None = list(range(num_blocks))
        self._root = _Node(b"", None, -1)
        self._nodes: list[_Node] = []       # every live non-root node
        self._tick = 0
        self.stats = PrefixCacheStats()

        def _publish(pool, cache_k, cache_v, bids, sidx, pidx):
            """Copy M blocks out of the slot cache into pool rows.

            bids: [M] destination block ids (pad = num_blocks → drop);
            sidx/pidx: [M, B] source (slot, position) per block column.
            Advanced indices on cache axes 1 and 3 put the [M, B] index
            shape in front: gather result [M, B, L, Hkv, Dh].
            """
            blk_k = cache_k[:, sidx, :, pidx, :]
            blk_v = cache_v[:, sidx, :, pidx, :]
            k = pool["k"].at[:, bids].set(
                blk_k.transpose(2, 0, 3, 1, 4).astype(pool["k"].dtype),
                mode="drop")
            v = pool["v"].at[:, bids].set(
                blk_v.transpose(2, 0, 3, 1, 4).astype(pool["v"].dtype),
                mode="drop")
            return {"k": k, "v": v}

        # Donating the pool makes the scatter an in-place update — the
        # pool is the long-lived resident allocation and must not
        # double-buffer on every publish.
        self._publish_fn = jax.jit(_publish, donate_argnums=(0,))

    # -- introspection --------------------------------------------------

    @property
    def blocks_in_use(self) -> int:
        if self.shared is not None:
            return len(self._nodes)
        return self.num_blocks - len(self._free)

    @property
    def node_count(self) -> int:
        return len(self._nodes)

    @property
    def evictable_blocks(self) -> int:
        """Blocks the cache could hand back under pool pressure: every
        unpinned node (interior nodes become evictable once their
        descendants go, so the whole refcount-0 population is
        reclaimable by cascaded LRU eviction). The paged engine's
        free-block admission accounting counts these as headroom."""
        return sum(1 for n in self._nodes if n.refcount == 0)

    @property
    def pinned_refcount(self) -> int:
        """Total outstanding pins across all nodes — the supervisor's
        invariant audit compares this against the engine's live pin
        table to spot leaks."""
        return sum(n.refcount for n in self._nodes)

    def flush(self) -> int:
        """Drop EVERY cached block (trie + pool accounting) and return
        how many were freed. The engine supervisor calls this after a
        failure that may have corrupted device state: pool blocks of
        unknown integrity must never seed a future admission wave. Any
        still-held ``PrefixMatch`` is force-orphaned (its nodes leave
        the trie; ``release`` on it stays safe because it only
        decrements node refcounts we are discarding anyway)."""
        n = len(self._nodes)
        if self.shared is not None:
            # return every trie block to the shared allocator (the
            # engine evacuated its slots first, so borrowed references
            # are gone and the trie's own pin is the last one)
            for node in self._nodes:
                self.shared.release([node.block_id])
                self.shared.free([node.block_id])
        self._nodes.clear()
        self._root.children.clear()
        if self.shared is None:
            self._free = list(range(self.num_blocks))
        self.stats.blocks_evicted += n
        return n

    # -- hashing / matching ---------------------------------------------

    def _block_digests(self, tokens, n_blocks: int):
        """Yield the chained digest of each of the first n_blocks."""
        prev = b""
        for j in range(n_blocks):
            prev = stable_block_hash(
                prev, tokens[j * self.block:(j + 1) * self.block])
            yield prev

    def prompt_digests(self, tokens) -> list[bytes]:
        """Every matchable block digest for a prompt (the last token is
        never matchable — see lookup). Hashing is the only per-token
        host cost on the admission path, so callers compute this ONCE
        per request and pass it to match_tokens/lookup; the engine
        memoizes it on the Request (the router re-checks every queued
        request every step while it waits)."""
        cap = (len(tokens) - 1) // self.block
        return list(self._block_digests(tokens, cap))

    def _walk(self, digests) -> list[_Node]:
        node = self._root
        nodes: list[_Node] = []
        for digest in digests:
            child = node.children.get(digest)
            if child is None:
                break
            nodes.append(child)
            node = child
        return nodes

    def match_tokens(self, tokens, digests=None) -> int:
        """Peek: longest cached prefix length in tokens. No pinning, no
        LRU touch, no stats — the admission router uses this to decide
        which path a request takes before committing to a wave."""
        if digests is None:
            digests = self.prompt_digests(tokens)
        return len(self._walk(digests)) * self.block

    def lookup(self, tokens, digests=None) -> PrefixMatch:
        """Longest-prefix match, PINNED. Always leaves >= 1 prompt token
        for the suffix prefill (the admission wave samples the first
        generated token from the last prompt position, so a whole-prompt
        hit would have nothing to run the lm_head on).

        Every matched node's refcount is incremented; the caller MUST
        ``release`` the match when the request retires. A zero-token
        match (miss) needs no release."""
        self._tick += 1
        self.stats.lookups += 1
        if digests is None:
            digests = self.prompt_digests(tokens)
        nodes = self._walk(digests)
        for n in nodes:
            n.last_used = self._tick
            n.refcount += 1
        if nodes:
            self.stats.hits += 1
            self.stats.tokens_matched += len(nodes) * self.block
        else:
            self.stats.misses += 1
        return PrefixMatch(nodes=nodes,
                           block_ids=[n.block_id for n in nodes],
                           tokens=len(nodes) * self.block)

    def release(self, match: PrefixMatch) -> None:
        for n in match.nodes:
            n.refcount -= 1
            assert n.refcount >= 0, "prefix-cache refcount underflow"
        match.nodes = []
        match.block_ids = []

    # -- eviction / allocation -------------------------------------------

    def _evict_one(self) -> bool:
        """Free the least-recently-used unpinned LEAF. Returns False if
        every node is pinned or interior (nothing evictable)."""
        victim: _Node | None = None
        for n in self._nodes:
            if n.children or n.refcount > 0:
                continue
            if victim is None or n.last_used < victim.last_used:
                victim = n
        if victim is None:
            return False
        victim.parent.children.pop(victim.digest, None)
        self._nodes.remove(victim)
        if self.shared is not None:
            self.shared.release([victim.block_id])
            self.shared.free([victim.block_id])
        else:
            self._free.append(victim.block_id)
        self.stats.blocks_evicted += 1
        return True

    def _alloc(self) -> int | None:
        if self.shared is not None:
            # shared-pool mode allocates only through adopt_blocks —
            # the trie never copies, so it never needs a fresh block
            raise RuntimeError(
                "PrefixCache._alloc in shared-pool mode (use "
                "adopt_blocks)")
        if not self._free and not self._evict_one():
            return None
        return self._free.pop()

    def reclaim(self, n: int) -> int:
        """Evict up to ``n`` unpinned leaves back to the shared pool —
        the paged engine calls this when the allocator runs dry, so
        cached-but-idle prefixes yield to live decode timelines.
        Returns how many blocks were actually reclaimed."""
        got = 0
        while got < n and self._evict_one():
            got += 1
        return got

    # -- publish ----------------------------------------------------------

    def publish(self, tokens, cache: dict, slot: int,
                eligible_tokens: int | None = None) -> int:
        """Insert the block-aligned prefix of ``tokens`` into the trie,
        copying KV for NEW blocks out of ``cache[:, slot]`` (which must
        hold the prompt's KV at positions [0, len(tokens))). Returns the
        number of blocks newly published.

        The invariant this relies on — cache columns below a slot's
        committed length are exactly the prompt/accepted-token KV, and
        columns at or past it are dead (masked by every attention read
        and overwritten before they can matter) — is the same
        invalidation discipline the engine's speculative verify
        dispatch uses to rewind past rejected draft tokens, so a
        publish after a speculative run copies only committed KV.

        ``eligible_tokens`` caps how deep the publish goes — the
        summarization service passes the shared-template span here so a
        small pool isn't churned by thread-unique context tails.
        Dedup is free: blocks already in the trie are just LRU-touched.
        """
        if self.shared is not None:
            raise RuntimeError(
                "copy-publish on a shared-pool PrefixCache (the paged "
                "engine publishes by adopt_blocks refcount handoff)")
        self._tick += 1
        limit = len(tokens)
        if eligible_tokens is not None:
            limit = min(limit, max(0, int(eligible_tokens)))
        n_blocks = limit // self.block
        if n_blocks == 0:
            return 0
        node = self._root
        path: list[_Node] = []      # temp-pinned while we allocate
        new_rows: list[tuple[int, int]] = []   # (block_id, start_pos)
        try:
            for j, digest in enumerate(
                    self._block_digests(tokens, n_blocks)):
                child = node.children.get(digest)
                if child is None:
                    bid = self._alloc()
                    if bid is None:
                        self.stats.publish_skips += 1
                        break
                    child = _Node(digest, node, bid)
                    node.children[digest] = child
                    self._nodes.append(child)
                    new_rows.append((bid, j * self.block))
                child.last_used = self._tick
                # Temp-pin: a later _alloc in THIS walk may evict, and a
                # just-created node is an unpinned leaf — without the pin
                # it could evict its own path's tail.
                child.refcount += 1
                path.append(child)
                node = child
        finally:
            for n in path:
                n.refcount -= 1
        if new_rows:
            self._copy_blocks(cache, slot, new_rows)
            self.stats.blocks_published += len(new_rows)
        return len(new_rows)

    def adopt_blocks(self, tokens, table, owned_from: int,
                     eligible_tokens: int | None = None) -> set[int]:
        """Shared-pool publish: the refcount handoff that replaces the
        cache→pool copy. ``table`` is the retiring slot's block table;
        its first ``owned_from`` entries are BORROWED (they came from a
        prefix match and already live in the trie, pinned by the
        match), the rest are slot-owned. For each block-aligned prompt
        block: an existing trie node is LRU-touched (dedup — a racing
        earlier retiree published the same span first); a new node
        ADOPTS the slot's own block by id — the pool pin moves to the
        trie, zero bytes copied. Returns the adopted block ids — the
        caller frees the slot's remaining owned blocks, NOT these.

        ``eligible_tokens`` caps publish depth exactly as in the copy
        path."""
        if self.shared is None:
            raise RuntimeError(
                "adopt_blocks on an owned-pool PrefixCache (use "
                "publish)")
        self._tick += 1
        limit = len(tokens)
        if eligible_tokens is not None:
            limit = min(limit, max(0, int(eligible_tokens)))
        n_blocks = min(limit // self.block, len(table))
        adopted: set[int] = set()
        if n_blocks == 0:
            return adopted
        # TRANSACTIONAL in three phases — the caller frees the slot's
        # non-adopted blocks right after this returns, so a partial
        # adoption (some blocks pinned, exception, empty return) would
        # turn the publish-failure containment in _retire into an
        # uncontained free-of-pinned-block error. Phase 1 (digests) and
        # phase 2 (validation) touch no state; phase 3 cannot raise.
        digests = list(self._block_digests(tokens, n_blocks))
        # phase 1: walk the existing path (dedup — LRU touches only).
        # The path is linear, so the first missing child means every
        # deeper node is missing too.
        node = self._root
        j = 0
        while j < n_blocks:
            child = node.children.get(digests[j])
            if child is None:
                break
            child.last_used = self._tick
            node = child
            j += 1
        if j >= n_blocks:
            return adopted
        if j < owned_from:
            # a borrowed block whose node is gone can only mean the
            # trie was flushed out from under an active match —
            # nothing to hand off.
            self.stats.publish_skips += 1
            return adopted
        # phase 2: validate every block to adopt BEFORE mutating
        bids = [int(table[i]) for i in range(j, n_blocks)]
        if any(not 0 <= b < self.shared.num_blocks
               or self.shared.is_free(b) for b in bids):
            # corrupted table entry: adopt nothing (the caller frees
            # the slot's owned blocks; audit repairs the rest)
            self.stats.publish_skips += 1
            return adopted
        # phase 3: apply — plain appends, dict inserts, validated pins
        for i, bid in zip(range(j, n_blocks), bids):
            child = _Node(digests[i], node, bid)
            node.children[digests[i]] = child
            self._nodes.append(child)
            self.shared.pin([bid])            # the trie's own reference
            adopted.add(bid)
            self.stats.blocks_published += 1
            child.last_used = self._tick
            node = child
        return adopted

    def _copy_blocks(self, cache: dict, slot: int,
                     rows: list[tuple[int, int]]) -> None:
        """One jitted cache→pool scatter for all new blocks of one
        publish. M pads to a power of two so compile count stays
        log-bounded; pad rows carry the OOB block id and drop."""
        m = 1
        while m < len(rows):
            m *= 2
        bids = np.full((m,), self.num_blocks, dtype=np.int32)
        sidx = np.zeros((m, self.block), dtype=np.int32)
        pidx = np.zeros((m, self.block), dtype=np.int32)
        for i, (bid, start) in enumerate(rows):
            bids[i] = bid
            sidx[i, :] = slot
            pidx[i, :] = start + np.arange(self.block)
        self.pool = self._publish_fn(
            self.pool, cache["k"], cache["v"], jnp.asarray(bids),
            jnp.asarray(sidx), jnp.asarray(pidx))


# ---------------------------------------------------------------------------
# shardcheck contracts (analysis/shardcheck.py)
# ---------------------------------------------------------------------------


@checkable("prefix-publish")
def _shardcheck_prefix_publish():
    """The cache→pool publish scatter, standalone: the donated pool must
    alias the output (this is the long-lived resident allocation — a
    dropped alias means a full second pool per publish), and the pool's
    k/v halves must share one block layout with the slot cache they
    gather from. The engine-level agreement with admit/decode programs
    is declared in ``engine/generation.py``.

    The ``hlo`` spec sends the same program through the post-lowering
    pass: the donated pool must survive as compiled input_output_alias
    entries (not just shape-match the trace) and the compiled peak is
    gated — a publish that copies the pool would double the resident
    allocation, which is exactly a peak-budget breach."""
    from copilot_for_consensus_tpu.analysis.contracts import (
        ContractCase,
        HloSpec,
    )
    from copilot_for_consensus_tpu.models.configs import DecoderConfig

    cfg = DecoderConfig(name="shardcheck-tiny", vocab_size=64,
                        d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
                        d_ff=64, max_seq_len=128)
    pc = PrefixCache(cfg, num_blocks=4, block_size=8,
                     kv_dtype=jnp.bfloat16)
    S = jax.ShapeDtypeStruct
    i32 = jnp.int32
    pool = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), pc.pool)
    cache_leaf = S((cfg.n_layers, 2, cfg.n_kv_heads, 32, cfg.head_dim),
                   jnp.bfloat16)
    return ContractCase(
        fn=pc._publish_fn,
        args=(pool, cache_leaf, cache_leaf, S((2,), i32),
              S((2, pc.block), i32), S((2, pc.block), i32)),
        donate_argnums=(0,),
        kv_group="engine.prefix-cache-kv",
        kv_caches=(("pool", pool),
                   ("slot-cache", {"k": cache_leaf, "v": cache_leaf})),
        hlo=HloSpec(peak_bytes=40_000),
    )
