"""Long-context serving: sequence-parallel prefill + distributed-cache decode.

The capability SURVEY.md §5 flags as the flagship TPU-native addition:
summarize a WHOLE thread/archive in one context instead of the
reference's top-k truncation to a 3000-token budget
(``orchestrator/app/context_selectors.py:94-107``). The continuous-batching
engine (``engine/generation.py``) serves many short requests; this engine
serves one long request whose context exceeds a single chip's comfortable
KV footprint, by sharding the *sequence* axis over the ``sp`` mesh axis:

* **Prefill** runs ring attention (``parallel/ring.py``): each device
  holds S/n positions, KV blocks rotate over ICI via ``ppermute``, and
  the resulting per-layer KV cache [L, 1, Hkv, S, D] stays sharded over
  ``sp`` — it is never gathered.
* **Decode** treats that cache as a frozen, distributed prefix. The new
  token's query attends to it with plain masked attention written over
  the GLOBAL sequence — the cache's NamedSharding makes XLA partition the
  einsum and turn the softmax max/sum into ``sp`` collectives (GSPMD);
  no hand-written ring is needed for a 1-token query. Generated tokens'
  KV land in a small replicated suffix buffer, and the two attention
  pieces merge by online softmax in fp32.

Both phases honor sliding-window attention (Mistral) and right-padded
prompts; decode fuses ``decode_window`` steps per dispatch like the main
engine.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    checkable,
    require_devices,
)
from copilot_for_consensus_tpu.engine.generation import Completion
from copilot_for_consensus_tpu.engine.sampling import SamplingConfig, sample
from copilot_for_consensus_tpu.engine.telemetry import resolve_telemetry
from copilot_for_consensus_tpu.obs.profile import step_annotation
from copilot_for_consensus_tpu.models import decoder, layers as L, quant
from copilot_for_consensus_tpu.models.configs import DecoderConfig
from copilot_for_consensus_tpu.parallel.ring import make_ring_attention
from copilot_for_consensus_tpu.parallel.sharding import (
    DEFAULT_RULES,
    shard_pytree,
)

NEG_INF = -1e30


def _round_up(n: int, mult: int) -> int:
    return -(-n // mult) * mult


class LongContextEngine:
    """One long-context generation at a time, sequence-sharded over
    ``axis``. Use ``GenerationEngine`` for short-prompt throughput."""

    def __init__(
        self,
        cfg: DecoderConfig,
        params: Any | None = None,
        *,
        mesh: Mesh,
        axis: str = "sp",
        sampling: SamplingConfig = SamplingConfig(),
        eos_id: int | list[int] = 2,
        seed: int = 0,
        dtype=jnp.bfloat16,
        max_new_tokens: int = 512,
        decode_window: int = 8,
        ctx_block: int = 64,
        profile_dir: str | None = None,
        sp_impl: str = "ring",
        telemetry: Any = True,
    ):
        self.cfg = cfg
        self.profile_dir = profile_dir
        # Flight recorder + spans (engine/telemetry.py): one span per
        # generate() call, one StepRecord per prefill/decode dispatch.
        # The single-request engine has batch width 1 by construction.
        self.telemetry = resolve_telemetry(telemetry, engine="longctx",
                                           num_slots=1)
        self._tele_rid = 0
        self.sp_impl = sp_impl
        self.mesh = mesh
        self.axis = axis
        self.n_shards = mesh.shape[axis]
        self.sampling = sampling
        eos_list = list(eos_id) if isinstance(eos_id, (list, tuple)) \
            else [int(eos_id)]
        self._eos_set = frozenset(int(e) for e in eos_list)
        self.dtype = dtype
        self.decode_window = max(1, decode_window)
        # Context lengths bucket to multiples of (shards × ctx_block) so a
        # handful of prefill programs cover every prompt length.
        self.ctx_quantum = self.n_shards * ctx_block
        self.suffix_len = _round_up(max_new_tokens + 1, 64)
        self._key = jax.random.PRNGKey(seed)

        axes = decoder.logical_axes(cfg)
        if params is None:
            params = decoder.init_params(jax.random.PRNGKey(seed), cfg,
                                         dtype=dtype)
        kind = quant.quant_kind(
            (params.get("layers", {}) or {}).get("wq"))
        if kind:
            # Propagate the detected mode: int4 leaves are {'q4','scale'}
            # with a [G, F] group-wise scale whose axes differ from the
            # int8 [1, F] per-channel scale — the default-int8 axes tree
            # would mismatch the params in shard_pytree.
            axes = quant.quantize_logical_axes(axes, mode=kind)
            quant.set_pallas_qmatmul(False)   # GSPMD path under the mesh
        self.params = shard_pytree(params, axes, mesh, self._param_rules())

        # SP strategy is pluggable: ring (KV rotation, any head count)
        # or Ulysses (one all-to-all each way; needs heads % sp == 0).
        if sp_impl == "ring":
            self._ring = make_ring_attention(mesh, axis)
        elif sp_impl == "ulysses":
            from copilot_for_consensus_tpu.parallel.ulysses import (
                make_ulysses_attention,
            )

            self._ring = make_ulysses_attention(mesh, axis)
        else:
            raise ValueError(f"unknown sp_impl {sp_impl!r} (ring|ulysses)")
        self._prefill_cache_spec = P(None, None, None, axis, None)
        self._prefill_jits: dict[int, Any] = {}
        self._decode_jit = None
        self._sample_fn = jax.jit(
            lambda logits, key: sample(logits, key, self.sampling))

    def _param_rules(self):
        # tp/ep shard as usual when those axes exist on the mesh; any rule
        # naming a mesh axis this mesh lacks falls back to replication.
        rules = dict(DEFAULT_RULES)
        present = set(self.mesh.axis_names)
        for k, v in rules.items():
            if isinstance(v, str) and v not in present:
                rules[k] = None
        return rules

    # ------------------------------------------------------------------
    # prefill
    # ------------------------------------------------------------------

    def _build_prefill(self, s_ctx: int):
        cfg, ring = self.cfg, self._ring
        mesh, dtype = self.mesh, self.dtype

        def _prefill(params, tokens, length):
            """tokens [1, s_ctx] right-padded; length [1]. Returns
            (last-valid-position logits [1, V], prefix cache
            [L, 1, Hkv, s_ctx, D] sharded over the sequence axis)."""
            x = params["tok_emb"][tokens]

            def body(x, layer):
                h, k, v = L.attn_prefill(
                    L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
                    layer, cfg, lengths=length, impl=ring)
                x = x + h
                x = x + (decoder._ffn(
                    L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                    layer, cfg))
                return x, (k.astype(dtype), v.astype(dtype))

            x, (ks, vs) = jax.lax.scan(body, x, params["layers"])
            last = jnp.take_along_axis(
                x, (length - 1)[:, None, None], axis=1)      # [1, 1, D]
            logits = decoder._unembed(last, params, cfg)[:, 0]
            return logits, {"k": ks, "v": vs}

        cache_sh = NamedSharding(mesh, self._prefill_cache_spec)
        return jax.jit(
            _prefill,
            in_shardings=(None, NamedSharding(mesh, P(None, self.axis)),
                          None),
            out_shardings=(None, {"k": cache_sh, "v": cache_sh}),
        )

    # ------------------------------------------------------------------
    # decode
    # ------------------------------------------------------------------

    def _build_decode(self):
        cfg = self.cfg
        window = cfg.sliding_window
        dw = self.decode_window

        def attend(q, k_pre, v_pre, k_suf, v_suf, prefix_len, suf_len,
                   gpos):
            """q [1, Hq, D]; prefix k/v [1, Hkv, S, D] (sp-sharded);
            suffix k/v [1, Hkv, W, D] replicated. Online-softmax merge of
            the two attention pieces, fp32."""
            b, hq, d = q.shape
            hkv = k_pre.shape[1]
            g = hq // hkv
            qg = (q.reshape(b, hkv, g, d).astype(jnp.float32)
                  * d ** -0.5)
            s_ctx, w = k_pre.shape[2], k_suf.shape[2]

            s1 = jnp.einsum("bhgd,bhsd->bhgs", qg,
                            k_pre.astype(jnp.float32),
                            preferred_element_type=jnp.float32)
            pos1 = jnp.arange(s_ctx)[None, None, None, :]
            m1 = pos1 < prefix_len
            if window > 0:
                m1 &= pos1 > gpos - window
            s1 = jnp.where(m1, s1, NEG_INF)

            s2 = jnp.einsum("bhgd,bhwd->bhgw", qg,
                            k_suf.astype(jnp.float32),
                            preferred_element_type=jnp.float32)
            kpos2 = prefix_len + jnp.arange(w)[None, None, None, :]
            m2 = jnp.arange(w)[None, None, None, :] <= suf_len
            if window > 0:
                m2 &= kpos2 > gpos - window
            s2 = jnp.where(m2, s2, NEG_INF)

            m = jnp.maximum(jnp.max(s1, -1, keepdims=True),
                            jnp.max(s2, -1, keepdims=True))
            p1 = jnp.where(m1, jnp.exp(s1 - m), 0.0)
            p2 = jnp.where(m2, jnp.exp(s2 - m), 0.0)
            l = (jnp.sum(p1, -1, keepdims=True)
                 + jnp.sum(p2, -1, keepdims=True))
            acc = (jnp.einsum("bhgs,bhsd->bhgd", p1,
                              v_pre.astype(jnp.float32))
                   + jnp.einsum("bhgw,bhwd->bhgd", p2,
                                v_suf.astype(jnp.float32)))
            out = acc / jnp.where(l == 0.0, 1.0, l)
            return out.reshape(b, hq, d)

        def one_token(params, tok, gpos, prefix, prefix_len,
                      suffix, suf_len):
            """tok [1]; gpos scalar global position of this token."""
            x = params["tok_emb"][tok][:, None, :]            # [1, 1, D]
            positions = gpos[None, None]                      # [1, 1]

            def layer_body(carry, scanned):
                x, k_suf_all, v_suf_all = carry
                layer, k_pre, v_pre, li = scanned
                xn = L.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
                q, k, v = L._project_qkv(xn, layer, cfg, positions)
                # Append this token's kv to the suffix buffer, layer li.
                k_suf_all = k_suf_all.at[li, :, :, suf_len, :].set(
                    k[:, :, 0, :].astype(k_suf_all.dtype))
                v_suf_all = v_suf_all.at[li, :, :, suf_len, :].set(
                    v[:, :, 0, :].astype(v_suf_all.dtype))
                o = attend(q[:, :, 0, :], k_pre, v_pre,
                           k_suf_all[li], v_suf_all[li],
                           prefix_len, suf_len, gpos)
                o = o.reshape(1, 1, cfg.n_heads * cfg.head_dim
                              ).astype(x.dtype)
                x = x + L.qmatmul(o, layer["wo"])
                x = x + decoder._ffn(
                    L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                    layer, cfg)
                return (x, k_suf_all, v_suf_all), None

            (x, k_suf, v_suf), _ = jax.lax.scan(
                layer_body, (x, suffix["k"], suffix["v"]),
                (params["layers"], prefix["k"], prefix["v"],
                 jnp.arange(cfg.n_layers)))
            logits = decoder._unembed(x, params, cfg)[:, 0]   # [1, V]
            return logits, {"k": k_suf, "v": v_suf}

        def _decode(params, tok, gpos, prefix, prefix_len, suffix,
                    suf_len, key):
            """``decode_window`` decode→sample→feed-back steps fused in
            one dispatch."""

            def step(carry, _):
                tok, gpos, suffix, suf_len, key = carry
                key, sub = jax.random.split(key)
                logits, suffix = one_token(params, tok, gpos, prefix,
                                           prefix_len, suffix, suf_len)
                nxt = sample(logits, sub, self.sampling)
                return (nxt, gpos + 1, suffix, suf_len + 1, key), nxt

            (tok, gpos, suffix, suf_len, _), toks = jax.lax.scan(
                step, (tok, gpos, suffix, suf_len, key), None, length=dw)
            return toks, suffix                      # toks [dw, 1]

        cache_sh = NamedSharding(self.mesh, self._prefill_cache_spec)
        return jax.jit(
            _decode,
            in_shardings=(None, None, None,
                          {"k": cache_sh, "v": cache_sh},
                          None, None, None, None),
            donate_argnums=(5,),
        )

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def generate(self, prompt: list[int],
                 max_new_tokens: int = 256, *,
                 correlation_id: str = "") -> Completion:
        """Generate against the FULL prompt, however long — no truncation.
        Returns the same Completion record as the batch engine. Captures
        a jax.profiler trace when built with ``profile_dir``.
        ``correlation_id`` tags the telemetry span (and any flight-
        recorder dump) with the pipeline event that asked."""
        from copilot_for_consensus_tpu.obs.profile import maybe_profile

        with maybe_profile(self.profile_dir):
            try:
                return self._generate(prompt, max_new_tokens,
                                      correlation_id)
            except Exception as exc:
                if self.telemetry is not None:
                    self.telemetry.record_error(exc)
                raise

    def _generate(self, prompt: list[int], max_new_tokens: int,
                  correlation_id: str = "") -> Completion:
        if not prompt:
            raise ValueError("empty prompt")
        tele = self.telemetry
        rid = self._tele_rid
        self._tele_rid += 1
        if tele is not None:
            tele.on_submit(rid, len(prompt), correlation_id)
        max_new_tokens = min(max_new_tokens, self.suffix_len - 1)
        t0 = time.monotonic()
        s_ctx = _round_up(len(prompt), self.ctx_quantum)
        if s_ctx not in self._prefill_jits:
            self._prefill_jits[s_ctx] = self._build_prefill(s_ctx)
        tokens = np.zeros((1, s_ctx), dtype=np.int32)
        tokens[0, :len(prompt)] = prompt
        length = jnp.asarray([len(prompt)], dtype=jnp.int32)
        seq = tele.next_step() if tele is not None else None
        with step_annotation("prefill", seq):
            logits, prefix = self._prefill_jits[s_ctx](
                self.params, jnp.asarray(tokens), length)
            self._key, sub = jax.random.split(self._key)
            first = int(jax.device_get(self._sample_fn(logits, sub))[0])
        prefill_s = time.monotonic() - t0
        if tele is not None:
            tele.record_step("prefill", prefill_s, seq=seq, rows=1,
                             batch=1, tokens=len(prompt),
                             padded_tokens=s_ctx)
            tele.on_admit(rid, wave_start=t0, admit_kind="longctx")

        t1 = time.monotonic()
        generated = [first]
        if first in self._eos_set or max_new_tokens <= 1:
            out_toks = [] if first in self._eos_set else [first]
            if tele is not None:
                tele.on_retire(rid, new_tokens=len(out_toks),
                               finish_reason=("eos" if first in
                                              self._eos_set
                                              else "length"))
            return Completion(
                request_id=0, prompt_len=len(prompt),
                tokens=out_toks,
                finish_reason=("eos" if first in self._eos_set
                               else "length"),
                prefill_s=prefill_s, decode_s=0.0)

        if self._decode_jit is None:
            self._decode_jit = self._build_decode()
        hkv, dh = self.cfg.n_kv_heads, self.cfg.head_dim
        suffix = {
            "k": jnp.zeros((self.cfg.n_layers, 1, hkv, self.suffix_len,
                            dh), self.dtype),
            "v": jnp.zeros((self.cfg.n_layers, 1, hkv, self.suffix_len,
                            dh), self.dtype),
        }
        tok = jnp.asarray([first], dtype=jnp.int32)
        gpos = jnp.asarray(len(prompt), dtype=jnp.int32)
        suf_len = jnp.asarray(0, dtype=jnp.int32)
        prefix_len = jnp.asarray(len(prompt), dtype=jnp.int32)
        finish = "length"
        while len(generated) < max_new_tokens:
            self._key, sub = jax.random.split(self._key)
            td = time.monotonic()
            seq = tele.next_step() if tele is not None else None
            with step_annotation("decode", seq):
                toks, suffix = self._decode_jit(
                    self.params, tok, gpos, prefix, prefix_len, suffix,
                    suf_len, sub)
                host = np.asarray(jax.device_get(toks))[:, 0]
            if tele is not None:
                tele.record_step("decode", time.monotonic() - td,
                                 seq=seq, rows=1, batch=1,
                                 tokens=len(host),
                                 padded_tokens=self.decode_window)
            done = False
            for t in host:
                generated.append(int(t))
                if int(t) in self._eos_set:
                    finish, done = "eos", True
                    break
                if len(generated) >= max_new_tokens:
                    done = True
                    break
            if done:
                break
            tok = jnp.asarray([int(host[-1])], dtype=jnp.int32)
            gpos = gpos + self.decode_window
            suf_len = suf_len + self.decode_window
        if generated and generated[-1] in self._eos_set:
            generated = generated[:-1]
        if tele is not None:
            tele.on_retire(rid, new_tokens=len(generated),
                           finish_reason=finish)
        return Completion(
            request_id=0, prompt_len=len(prompt), tokens=generated,
            finish_reason=finish, prefill_s=prefill_s,
            decode_s=time.monotonic() - t1)

    def generate_text(self, prompt: str, tokenizer,
                      max_new_tokens: int = 256) -> str:
        comp = self.generate(tokenizer.encode(prompt, add_bos=True),
                             max_new_tokens)
        return tokenizer.decode(comp.tokens)


# ---------------------------------------------------------------------------
# shardcheck contracts (analysis/shardcheck.py)
# ---------------------------------------------------------------------------


@checkable("longctx-engine")
def _shardcheck_longctx_engine():
    """Build a tiny long-context engine on the real sp mesh (both SP
    strategies route through here, ring by default) and trace its two
    programs: prefill exercises the ring collectives under the engine's
    OWN mesh/axis plumbing, decode exercises the GSPMD distributed-
    prefix attention plus the donated suffix buffer (which must alias
    the output — it is re-dispatched every window). The prefix and
    suffix caches must share one KV layout: decode's online-softmax
    merge reads both every token."""
    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    require_devices(8)
    cfg = DecoderConfig(name="shardcheck-tiny", vocab_size=64,
                        d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
                        d_ff=64, max_seq_len=256)
    mesh = build_mesh(MeshConfig(sp=4), devices=jax.devices()[:8])
    eng = LongContextEngine(cfg, mesh=mesh, max_new_tokens=16,
                            decode_window=4, ctx_block=16)
    s_ctx = eng.ctx_quantum                     # one prefill bucket
    S = jax.ShapeDtypeStruct
    i32 = jnp.int32
    hkv, dh = cfg.n_kv_heads, cfg.head_dim
    prefix = {
        "k": S((cfg.n_layers, 1, hkv, s_ctx, dh), eng.dtype),
        "v": S((cfg.n_layers, 1, hkv, s_ctx, dh), eng.dtype),
    }
    suffix = {
        "k": S((cfg.n_layers, 1, hkv, eng.suffix_len, dh), eng.dtype),
        "v": S((cfg.n_layers, 1, hkv, eng.suffix_len, dh), eng.dtype),
    }
    key = jax.random.PRNGKey(0)
    group = "engine.longctx-kv"
    return [
        ContractCase(
            label="prefill", fn=eng._build_prefill(s_ctx),
            args=(eng.params, S((1, s_ctx), i32), S((1,), i32)),
            mesh=mesh, rules=eng._param_rules(),
            logical=(("params",
                      jax.tree.map(lambda x: S(x.shape, x.dtype),
                                   eng.params),
                      decoder.logical_axes(cfg)),)),
        ContractCase(
            label="decode", fn=eng._build_decode(),
            args=(eng.params, S((1,), i32), S((), i32), prefix,
                  S((), i32), suffix, S((), i32), key),
            donate_argnums=(5,), mesh=mesh, kv_group=group,
            kv_caches=(("sp-prefix", prefix),
                       ("suffix-buffer", suffix))),
    ]
