"""Durable engine request journal: a serving-process crash costs
latency, not work.

PR 8 gave the *pipeline* that contract for broker outages (the durable
publish outbox) and PR 7 contains *in-process* engine failures
(supervisor + request replay) — but a serving-process death still lost
every queued and in-flight engine request plus all generated-so-far
tokens. This module is the process-level mirror of both: the same
sqlite-WAL file discipline as the publish outbox
(``bus/broker.py:_Outbox``), holding one row per live engine request.

Contract (docs/RESILIENCE.md#process-lifecycle):

* ``engine.submit`` journals the request — prompt, params, scheduling
  identity, correlation/trace ids — BEFORE the request enters any
  engine queue, so there is no window where admitted work is
  journal-invisible.
* Accepted tokens checkpoint incrementally: every ``checkpoint_every``
  decode steps and on every step that retires a request. A crash loses
  at most the tokens accepted since the last checkpoint — and loses
  them as *latency* (they are recomputed from the checkpoint), never
  as work.
* Retirement deletes the row at harvest. Terminal structured failures
  delivered to a live caller (``EngineFailed``, watchdog suspects)
  *abandon* the row — the caller owns the retry now, and replaying it
  at the next restart would duplicate work the caller already saw
  fail.
* On restart, :meth:`unfinished` rows resubmit as prompt+generated
  continuations through the PR-7 replay machinery (seeded prefill;
  greedy bit-identical at f32); :meth:`supersede` re-keys the row to
  the continuation's request id while preserving the ORIGINAL identity
  (prompt, budget, accepted tokens, attempt count), so a second crash
  still recovers the original request.

Everything here is import-light host code (sqlite + json only — no
jax): the journal is unit-testable against stub engines and usable
from host-only processes.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterable


@dataclass
class JournalEntry:
    """One unfinished request as recovered from the journal."""

    request_id: int
    prompt: list[int]
    max_new_tokens: int
    #: accepted tokens as of the last checkpoint (recovery resumes the
    #: continuation from here; anything accepted after the checkpoint
    #: is recomputed — latency, not loss)
    tokens: list[int] = field(default_factory=list)
    #: process-restart / replay attempts already consumed
    attempt: int = 0
    cache_eligible_tokens: int | None = None
    correlation_id: str = ""
    tenant: str = ""
    priority: str = ""
    #: absolute wall-clock deadline (0.0 = none). Wall clock, not
    #: monotonic: a monotonic stamp is meaningless across processes.
    deadline_wall: float = 0.0
    #: pipeline trace parent captured at submit (attempt-numbered
    #: ``engine_replay`` spans parent here on recovery)
    trace_id: str = ""
    span_id: str = ""
    journaled_wall: float = 0.0


class EngineJournal:
    """Bounded-risk durable request journal (sqlite WAL; ``:memory:``
    for tests — pass a path when rows must survive a process death,
    which is the point). Thread-safe: the engine's dispatcher thread
    writes the hot path, runner/watchdog threads abandon rows, and the
    metrics scrape reads ``depth()``.

    ``checkpoint_every`` is the decode-step cadence between incremental
    token checkpoints — the knob behind the
    ``copilot_engine_journal_checkpoint_lag`` gauge: smaller loses
    fewer tokens to a crash, larger costs fewer sqlite writes."""

    def __init__(self, path: str = ":memory:", *,
                 checkpoint_every: int = 8):
        self.path = path
        self.checkpoint_every = max(1, int(checkpoint_every))
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._lock = threading.Lock()
        with self._lock, self._db:
            self._db.execute("""
                CREATE TABLE IF NOT EXISTS requests (
                    rid INTEGER PRIMARY KEY,
                    prompt TEXT NOT NULL,
                    max_new_tokens INTEGER NOT NULL,
                    resumed TEXT NOT NULL DEFAULT '[]',
                    tokens TEXT NOT NULL DEFAULT '[]',
                    attempt INTEGER NOT NULL DEFAULT 0,
                    cache_eligible INTEGER,
                    correlation_id TEXT NOT NULL DEFAULT '',
                    tenant TEXT NOT NULL DEFAULT '',
                    priority TEXT NOT NULL DEFAULT '',
                    deadline_wall REAL NOT NULL DEFAULT 0.0,
                    trace_id TEXT NOT NULL DEFAULT '',
                    span_id TEXT NOT NULL DEFAULT '',
                    journaled_at REAL NOT NULL
                )""")
            # cached row count, seeded from the durable file: depth()
            # is read every engine step for the gauge — that must not
            # cost a sqlite COUNT(*) per step (the _Outbox move)
            self._n = int(self._db.execute(
                "SELECT COUNT(*) FROM requests").fetchone()[0])
        # counters (stats(); process-local, not durable)
        self._journaled = 0
        self._retired = 0
        self._abandoned = 0
        self._checkpoints = 0

    # -- hot path --------------------------------------------------------

    def record_submit(self, request_id: int, prompt: Iterable[int],
                      max_new_tokens: int, *,
                      cache_eligible_tokens: int | None = None,
                      correlation_id: str = "", tenant: str = "",
                      priority: str = "",
                      deadline_wall: float = 0.0,
                      trace_id: str = "", span_id: str = "") -> None:
        """Journal one request BEFORE it enters any engine queue."""
        with self._lock, self._db:
            existed = self._db.execute(
                "SELECT 1 FROM requests WHERE rid = ?",
                (int(request_id),)).fetchone() is not None
            self._db.execute(
                "INSERT OR REPLACE INTO requests (rid, prompt, "
                "max_new_tokens, resumed, tokens, attempt, "
                "cache_eligible, correlation_id, tenant, priority, "
                "deadline_wall, trace_id, span_id, journaled_at) "
                "VALUES (?, ?, ?, '[]', '[]', 0, ?, ?, ?, ?, ?, ?, ?, ?)",
                (int(request_id), json.dumps(list(prompt)),
                 int(max_new_tokens), cache_eligible_tokens,
                 correlation_id, tenant, priority, float(deadline_wall),
                 trace_id, span_id, time.time()))
            if not existed:
                self._n += 1
            self._journaled += 1

    def checkpoint(self, request_id: int,
                   generated: Iterable[int]) -> None:
        """Record the tokens accepted so far for one request.
        ``generated`` is relative to the row's CURRENT prompt (the
        continuation after a supersede); the row's durable ``tokens``
        column is always relative to the ORIGINAL prompt."""
        self.checkpoint_many([(request_id, generated)])

    def checkpoint_many(
            self, pairs: Iterable[tuple[int, Iterable[int]]]) -> None:
        pairs = [(int(rid), list(gen)) for rid, gen in pairs]
        if not pairs:
            return
        with self._lock, self._db:
            for rid, gen in pairs:
                row = self._db.execute(
                    "SELECT resumed FROM requests WHERE rid = ?",
                    (rid,)).fetchone()
                if row is None:
                    continue
                resumed = json.loads(row[0])
                self._db.execute(
                    "UPDATE requests SET tokens = ? WHERE rid = ?",
                    (json.dumps(resumed + gen), rid))
                self._checkpoints += 1

    def record_retire(self, request_id: int) -> None:
        """The request completed and its output was harvested: the row
        leaves the journal (crash-after-this replays nothing)."""
        self._delete(request_id, retired=True)

    def record_abandon(self, request_id: int) -> None:
        """A terminal structured failure was DELIVERED to a live caller
        (EngineFailed / suspect / deadline): the caller owns the retry,
        so the row must not replay at the next restart."""
        self._delete(request_id, retired=False)

    def _delete(self, request_id: int, *, retired: bool) -> None:
        with self._lock, self._db:
            cur = self._db.execute(
                "DELETE FROM requests WHERE rid = ?",
                (int(request_id),))
            if cur.rowcount:
                self._n -= cur.rowcount
                if retired:
                    self._retired += cur.rowcount
                else:
                    self._abandoned += cur.rowcount

    # -- recovery --------------------------------------------------------

    def unfinished(self) -> list[JournalEntry]:
        """Every journaled request that never retired, oldest first —
        the warm-restart work list."""
        with self._lock:
            rows = self._db.execute(
                "SELECT rid, prompt, max_new_tokens, tokens, attempt, "
                "cache_eligible, correlation_id, tenant, priority, "
                "deadline_wall, trace_id, span_id, journaled_at "
                "FROM requests ORDER BY rid").fetchall()
        return [JournalEntry(
            request_id=r[0], prompt=json.loads(r[1]),
            max_new_tokens=r[2], tokens=json.loads(r[3]), attempt=r[4],
            cache_eligible_tokens=r[5], correlation_id=r[6],
            tenant=r[7], priority=r[8], deadline_wall=r[9],
            trace_id=r[10], span_id=r[11], journaled_wall=r[12])
            for r in rows]

    def supersede(self, old_rid: int, new_rid: int,
                  resumed_tokens: Iterable[int]) -> None:
        """ATOMICALLY re-key ``old_rid``'s row onto the continuation
        ``new_rid``, preserving the ORIGINAL identity (prompt, budget,
        correlation/trace ids) with ``resumed_tokens`` as the accepted
        prefix the continuation resumes from and attempt+1. One UPDATE
        in one transaction — at no instant does the journal hold two
        live rows for one request, so a crash anywhere around a
        resubmission replays exactly one of {original, continuation},
        never both. Callers therefore SUPPRESS the continuation's own
        ``record_submit`` (``GenerationEngine._journal_suppress``) and
        let this re-key be the row's only mutation. Future checkpoints
        of the continuation land as resumed+generated — a second crash
        recovers the original request, not the continuation."""
        tok = json.dumps(list(resumed_tokens))
        with self._lock, self._db:
            self._db.execute(
                "UPDATE requests SET rid = ?, resumed = ?, tokens = ?, "
                "attempt = attempt + 1 WHERE rid = ?",
                (int(new_rid), tok, tok, int(old_rid)))

    # -- introspection ---------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return self._n

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": self._n,
                "journaled": self._journaled,
                "retired": self._retired,
                "abandoned": self._abandoned,
                "checkpoints": self._checkpoints,
            }

    def close(self) -> None:
        # Terminal teardown: snapshot the handle under the lock, close
        # outside it (sqlite's own close is thread-safe; a concurrent
        # writer surfaces a ProgrammingError it already tolerates).
        with self._lock:
            db = self._db
        db.close()


def resolve_journal(journal: Any) -> EngineJournal | None:
    """``journal=`` argument semantics (the ``resolve_telemetry`` /
    ``resolve_supervisor`` pattern): None/False disables, a string is a
    database path, a dict is ``{"path": ..., "checkpoint_every": ...}``,
    an :class:`EngineJournal` instance is used as-is."""
    if journal is None or journal is False:
        return None
    if isinstance(journal, EngineJournal):
        return journal
    if isinstance(journal, str):
        return EngineJournal(journal)
    if isinstance(journal, dict):
        cfg = dict(journal)
        return EngineJournal(
            cfg.get("path", ":memory:"),
            checkpoint_every=int(cfg.get("checkpoint_every", 8)))
    raise ValueError(
        f"journal must be None/False, a path, a config dict or an "
        f"EngineJournal, got {type(journal).__name__}")
