"""Engine flight recorder: request-lifecycle tracing + step telemetry.

The serving engines keep rich internal ledgers (``prefix_stats()``,
``spec_stats()``) but, before this module, none of it reached
``obs/metrics.py`` — a TTFT regression was invisible outside a one-shot
``bench.py`` artifact. This is the observability layer SURVEY.md §5
assigns to the TPU build, three pieces:

* **Request-lifecycle spans** (``RequestTrace``): one span per request
  carrying the pipeline ``correlation_id`` end-to-end — enqueue →
  admit (queue wait, prefix-cache hit / seeded split) → first token →
  retire — with the derived serving latencies every production LLM
  stack treats as the control surface for continuous batching: TTFT
  (time to first token), ITL (mean inter-token latency), e2e latency,
  and queue wait.
* **Step telemetry** (``StepRecord`` + ``FlightRecorder``): a bounded,
  lock-cheap ring buffer with one record per device dispatch — wave
  kind (prefill / prefill_seeded / decode / verify / piggyback /
  embed), batch occupancy, padding-bucket waste, draft acceptance,
  host wall time, and a monotonically increasing step id that matches
  the ``jax.profiler`` ``StepTraceAnnotation`` around the dispatch
  (``obs/profile.py:step_annotation``), so Perfetto device traces
  correlate with host-side records. The ring doubles as a **flight
  recorder**: dumpable as JSON on demand and automatically on engine
  error for post-mortems (``record_error`` → ``dump``), naming the
  requests in flight by ``correlation_id``.
* **Prometheus export**: every observation lands in an
  ``obs/metrics.py`` collector (an ``InMemoryMetrics`` by default, so
  ``telemetry.metrics.render_prometheus()`` works out of the box;
  services pass their shared collector instead). The emitted series
  are declared in ``METRICS`` — the registry the observability-pack
  contract test checks ``infra/grafana`` + ``infra/prometheus``
  references against, so a dashboard panel or alert on a typo'd
  ``copilot_engine_*`` series fails CI instead of rotting silently.

Everything here is strictly host-side: timestamps via
``time.monotonic()`` around dispatches the engines already sync on,
zero device work, no extra ``block_until_ready`` — the jaxlint
``host-sync-in-jit`` lane stays clean and measured overhead stays
under the 1% budget (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import collections
import json
import pathlib
import threading
import time
import weakref
from dataclasses import asdict, dataclass
from typing import Any

from copilot_for_consensus_tpu.obs.metrics import (
    InMemoryMetrics,
    MetricsCollector,
    check_registry_labels,
)

# ---------------------------------------------------------------------------
# Metric registry — the single source of truth for what the telemetry
# layer emits. Names are collector-namespaced at render time
# ("copilot_" by default), so the full series name is e.g.
# ``copilot_engine_ttft_seconds``. The observability-pack contract test
# (tests/test_observability_pack.py) asserts every ``copilot_engine_*``
# series a dashboard or alert references exists here WITH the right
# type for the PromQL function applied to it (rate() needs a counter or
# histogram, deriv() needs a gauge — the PR-1 alert-bug class).
# ---------------------------------------------------------------------------

#: metric name (sans namespace) → (type, label names, help)
METRICS: dict[str, tuple[str, tuple[str, ...], str]] = {
    "engine_requests_total": (
        "counter", ("engine", "finish_reason"),
        "Requests retired, by finish reason "
        "(eos|length|deadline|error|handoff)."),
    "engine_tokens_total": (
        "counter", ("engine", "kind"),
        "Tokens through the engine: kind=prompt (prefilled), "
        "kind=prompt_cached (skipped via prefix reuse), "
        "kind=generated."),
    "engine_errors_total": (
        "counter", ("engine",),
        "Engine dispatch failures (each one also dumps the flight "
        "recorder)."),
    "engine_queue_wait_seconds": (
        "histogram", ("engine",),
        "Submit → admission-wave start."),
    "engine_ttft_seconds": (
        "histogram", ("engine",),
        "Submit → first token (the admission wave samples it)."),
    "engine_itl_seconds": (
        "histogram", ("engine",),
        "Mean inter-token latency per retired request: decode time / "
        "(generated tokens - 1)."),
    "engine_e2e_seconds": (
        "histogram", ("engine",),
        "Submit → retire."),
    "engine_step_seconds": (
        "histogram", ("engine", "kind"),
        "Host wall time per device dispatch, by wave kind (prefill|"
        "prefill_seeded|decode|verify|piggyback|embed)."),
    "engine_queue_depth": (
        "gauge", ("engine",),
        "Requests waiting for a slot (queued + piggyback-prefilling)."),
    "engine_slot_occupancy": (
        "gauge", ("engine",),
        "Active slots / total slots at the last step."),
    "engine_padding_waste_ratio": (
        "gauge", ("engine",),
        "Padded-but-dead fraction of the last dispatch's token grid "
        "(bucket/pow2 padding the program computes and drops)."),
    "engine_prefix_hit_rate": (
        "gauge", ("engine",),
        "Prefix-cache hit rate over admission lookups "
        "(GenerationEngine.prefix_stats)."),
    "engine_spec_acceptance_rate": (
        "gauge", ("engine",),
        "Accepted / drafted speculative tokens "
        "(GenerationEngine.spec_stats)."),
    "engine_spec_draft_hit_rate": (
        "gauge", ("engine",),
        "Draft-index probes that produced a draft."),
    "engine_tokens_per_weight_pass": (
        "gauge", ("engine",),
        "Per-stream decode ledger across plain and verify paths; 1.0 "
        "is the vanilla decode wall."),
    # ---- SLO-aware scheduler (engine/scheduler.py) ----
    "engine_sched_tenant_queue_depth": (
        "gauge", ("engine", "tenant"),
        "Requests queued in the scheduler, per tenant."),
    "engine_sched_deficit": (
        "gauge", ("engine", "tenant"),
        "Weighted-DRR deficit (prompt tokens the tenant may release), "
        "per tenant."),
    "engine_sched_shed_total": (
        "counter", ("engine", "tenant", "priority"),
        "Requests shed with a structured EngineOverloaded rejection "
        "(surfaced as HTTP 429 + Retry-After at the edge)."),
    "engine_sched_prefill_chunks_total": (
        "counter", ("engine",),
        "Chunked-prefill continuation rows dispatched (long prompts "
        "split across decode steps to bound ITL)."),
    # ---- resilience: fault plane + supervisor (engine/faults.py,
    # engine/supervisor.py; docs/RESILIENCE.md) ----
    "engine_fault_injected_total": (
        "counter", ("engine", "kind", "mode"),
        "Faults fired by the injection plane (chaos harness; any "
        "nonzero value in production means a fault plan leaked in)."),
    "engine_fault_watchdog_trips_total": (
        "counter", ("engine", "kind"),
        "Dispatches that overran their per-kind watchdog deadline — "
        "the engine was marked suspect and in-flight handles failed "
        "structured instead of wedging their callers."),
    "engine_fault_breaker_state": (
        "gauge", ("engine", "breaker"),
        "Circuit-breaker state per degraded mode (0 closed, 0.5 "
        "half-open probe, 1 open): spec_verify open = spec decode "
        "disabled; resource open = occupancy cap lowered."),
    "engine_recovery_replays_total": (
        "counter", ("engine",),
        "In-flight requests resubmitted as prompt+generated "
        "continuations after an engine failure (request replay)."),
    "engine_recovery_failed_total": (
        "counter", ("engine",),
        "Requests terminally failed with structured EngineFailed "
        "after their replay budget was spent."),
    "engine_recovery_quarantined_slots": (
        "gauge", ("engine",),
        "Slots quarantined by the post-failure invariant audit "
        "(irreconcilable state; capacity reduced until restart)."),
    "engine_recovery_released_pins_total": (
        "counter", ("engine",),
        "Leaked prefix-cache pins released by the post-failure audit "
        "(a leaked pin would hold its pool blocks forever)."),
    "engine_recovery_deadline_expired_total": (
        "counter", ("engine",),
        "Requests dropped (not computed) because their per-request "
        "deadline_s expired before completion."),
    # ---- paged KV block pool (engine/kv_pool.py +
    # GenerationEngine(kv_pool_blocks=...); docs/ENGINE_PREFIX_CACHE.md
    # "Paged KV") ----
    "engine_kv_pool_free_blocks": (
        "gauge", ("engine",),
        "Free blocks in the paged KV pool (allocator free list; the "
        "EngineKVPoolExhausted alert watches this against a standing "
        "queue)."),
    "engine_kv_pool_pinned_blocks": (
        "gauge", ("engine",),
        "Pool blocks with outstanding pins — published prefix blocks "
        "the trie (and any admission reading them) holds."),
    "engine_kv_pool_fragmentation_ratio": (
        "gauge", ("engine",),
        "Internal fragmentation of allocated blocks: reserved-but-"
        "dead fraction (tail slack of partially filled blocks)."),
    "engine_kv_pool_zero_copy_admits_total": (
        "counter", ("engine",),
        "Seeded admissions that appended matched block ids to the "
        "slot's table instead of gathering a pool→slot copy "
        "(pointer-only prefix admission)."),
    "engine_kv_route": (
        "gauge", ("engine", "route"),
        "1 for the paged-attention dispatch route this engine "
        "resolved (route label: 'kernel' = Pallas in-place block "
        "reads, 'reference' = XLA working-set gather); dashboards "
        "join it against throughput to attribute route deltas."),
    # ---- disaggregated prefill/decode roles (engine/roles.py +
    # GenerationEngine(role=...); docs/PERF.md#multi-chip-serving) ----
    "engine_role_occupancy": (
        "gauge", ("engine", "engine_role"),
        "Occupied slots / total slots per role instance (active + "
        "chunking + handoff-parked) — the prefill/decode split's "
        "saturation view. Label is engine_role (not role): role is "
        "reserved for the cross-process aggregator's stamp."),
    "engine_role_handoff_blocks_total": (
        "counter", ("engine",),
        "KV pool blocks moved through the prefill→decode handoff "
        "(block-granular device-to-device transfers)."),
    "engine_role_handoff_wait_seconds": (
        "histogram", ("engine",),
        "Prefill-ready → decode-admitted wait per handed-off request "
        "(the disaggregation tax; the EngineKVHandoffStalled alert "
        "watches its p99 against a standing handoff backlog)."),
    # ---- durable request journal (engine/journal.py;
    # docs/RESILIENCE.md#process-lifecycle) ----
    "engine_journal_depth": (
        "gauge", ("engine",),
        "Unfinished requests in the durable engine journal (queued + "
        "in-flight); a depth that never drains while the engine is "
        "idle means rows leaked (EngineJournalBacklog alert)."),
    "engine_journal_replayed_total": (
        "counter", ("engine",),
        "Journaled requests resubmitted as prompt+generated "
        "continuations at warm restart (restart costs latency, not "
        "work)."),
    "engine_journal_checkpoint_lag": (
        "gauge", ("engine",),
        "Largest per-request accepted-token count not yet "
        "checkpointed to the journal — the tokens a crash right now "
        "would recompute."),
}

# Registration-time contract: reserved proc/role labels collide here,
# loudly, not at scrape time when the aggregator stamps them.
check_registry_labels(METRICS, owner="ENGINE_METRICS")

#: step-record kinds the engines emit (doc + test anchor)
STEP_KINDS = ("prefill", "prefill_seeded", "prefill_chunk", "decode",
              "verify", "piggyback", "embed")


def prometheus_series(namespace: str = "copilot") -> dict[str, str]:
    """Full series name → type, for contract tests and docs."""
    return {f"{namespace}_{name}": typ
            for name, (typ, _labels, _help) in METRICS.items()}


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------


@dataclass
class RequestTrace:
    """One request's lifecycle span. Timestamps are ``time.monotonic()``
    (latency math); ``enqueued_wall`` anchors the span to wall-clock for
    dump correlation with logs."""

    request_id: int
    correlation_id: str = ""
    prompt_len: int = 0
    enqueued_at: float = 0.0
    enqueued_wall: float = 0.0
    admitted_at: float = 0.0        # admission-wave start
    first_token_at: float = 0.0     # admission-wave end (first sample)
    finished_at: float = 0.0
    admit_kind: str = ""            # wave | seeded | piggyback | longctx
    prefix_hit_tokens: int = 0      # prompt tokens seeded from the pool
    new_tokens: int = 0
    finish_reason: str = ""
    # derived at retire (kept on the record so dumps are self-contained)
    queue_wait_s: float = 0.0
    ttft_s: float = 0.0
    itl_s: float = 0.0
    e2e_s: float = 0.0

    def as_dict(self) -> dict:
        return asdict(self)


@dataclass
class StepRecord:
    """One device dispatch as seen from the host. ``seq`` matches the
    ``StepTraceAnnotation`` step id around the dispatch, so a Perfetto
    device trace row and this record name the same step."""

    seq: int
    kind: str                 # one of STEP_KINDS
    t_wall: float             # time.time() at record (dump correlation)
    duration_s: float         # host wall time incl. the harvest sync
    rows: int = 0             # real rows (requests / active slots)
    batch: int = 0            # program batch width (incl. padding)
    tokens: int = 0           # real tokens processed or emitted
    padded_tokens: int = 0    # batch × bucket the program computed
    draft_tokens: int = 0     # verify waves: drafted
    accepted_tokens: int = 0  # verify waves: accepted
    route: str = ""           # paged dispatch route: kernel |
    #                           reference ("" = contiguous layout)

    @property
    def occupancy(self) -> float:
        return self.rows / self.batch if self.batch else 0.0

    @property
    def padding_waste(self) -> float:
        if self.padded_tokens <= 0:
            return 0.0
        dead = max(0, self.padded_tokens - self.tokens)
        return dead / self.padded_tokens

    def as_dict(self) -> dict:
        d = asdict(self)
        d["occupancy"] = round(self.occupancy, 4)
        d["padding_waste"] = round(self.padding_waste, 4)
        return d


class FlightRecorder:
    """Bounded ring of ``StepRecord``s. Append is one deque op under
    the GIL (the deque's maxlen does the eviction) — cheap enough to
    stay on by default in the serving loop."""

    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        self._ring: "collections.deque[StepRecord]" = collections.deque(
            maxlen=capacity)
        self._seq = 0
        self._lock = threading.Lock()

    def next_seq(self) -> int:
        """Allocate the next step id (also the StepTraceAnnotation
        step_num) BEFORE the dispatch, so the annotation and the record
        agree even if the dispatch raises."""
        with self._lock:
            self._seq += 1
            return self._seq

    def record(self, rec: StepRecord) -> StepRecord:
        self._ring.append(rec)
        return rec

    def records(self) -> list[StepRecord]:
        return list(self._ring)

    def as_dicts(self) -> list[dict]:
        return [r.as_dict() for r in self.records()]


# ---------------------------------------------------------------------------
# default dump dir — set by the test harness / service bootstrap via
# this setter (runtime environment access stays in the config layer;
# tests/conftest.py plumbs COPILOT_FLIGHT_RECORD_DIR through here for
# the CI failure artifact).
# ---------------------------------------------------------------------------

_default_dump_dir: str | None = None
#: live telemetry instances, so a test-failure hook can dump every
#: engine that existed when the failure happened
_live: "weakref.WeakSet[EngineTelemetry]" = weakref.WeakSet()


def set_default_dump_dir(path: str | None) -> None:
    global _default_dump_dir
    _default_dump_dir = path


def get_default_dump_dir() -> str | None:
    return _default_dump_dir


def dump_all(directory: str | None = None, tag: str = "flight") -> list[str]:
    """Dump every live telemetry instance to ``directory`` (default:
    the configured dump dir). Returns written paths; never raises —
    this runs from failure hooks where a second error would mask the
    first."""
    directory = directory or _default_dump_dir
    if not directory:
        return []
    out = []
    for i, tele in enumerate(list(_live)):
        try:
            out.append(tele.dump_to_file(directory=directory,
                                         tag=f"{tag}-{i}"))
        except Exception:
            pass
    return out


# ---------------------------------------------------------------------------
# the telemetry front-end engines talk to
# ---------------------------------------------------------------------------


class EngineTelemetry:
    """Flight recorder + span tracker + metrics exporter for one engine.

    All methods are cheap host work (dict ops, a few float subtractions,
    one metrics observation each) and are called from the engine's own
    serving thread around dispatches it already syncs on. The metrics
    collector is thread-safe, so a shared collector across engines is
    fine.
    """

    def __init__(self, *, engine: str = "generation",
                 num_slots: int = 0,
                 metrics: MetricsCollector | None = None,
                 recorder_capacity: int = 512,
                 completed_capacity: int = 4096,
                 dump_dir: str | None = None):
        self.engine_label = engine
        self.num_slots = num_slots
        self.metrics = metrics if metrics is not None else \
            InMemoryMetrics(namespace="copilot")
        self.recorder = FlightRecorder(recorder_capacity)
        self.dump_dir = dump_dir
        self._labels = {"engine": engine}
        self._traces: dict[int, RequestTrace] = {}      # in flight
        self.completed: "collections.deque[RequestTrace]" = \
            collections.deque(maxlen=completed_capacity)
        self.created_wall = time.time()
        self.errors = 0
        self._dump_seq = 0
        _live.add(self)

    # -- lifecycle ------------------------------------------------------

    def on_submit(self, request_id: int, prompt_len: int,
                  correlation_id: str = "") -> RequestTrace:
        tr = RequestTrace(
            request_id=request_id, correlation_id=correlation_id,
            prompt_len=prompt_len, enqueued_at=time.monotonic(),
            enqueued_wall=time.time())
        self._traces[request_id] = tr
        return tr

    def on_admit(self, request_id: int, *, wave_start: float,
                 admit_kind: str = "wave",
                 prefix_hit_tokens: int = 0) -> None:
        """Record admission for one request: the wave started at
        ``wave_start`` (monotonic) and its first token exists NOW (the
        admit program samples it; the caller invokes this right after
        the host fetch)."""
        tr = self._traces.get(request_id)
        if tr is None:
            return
        now = time.monotonic()
        tr.admitted_at = wave_start
        tr.first_token_at = now
        tr.admit_kind = admit_kind
        tr.prefix_hit_tokens = prefix_hit_tokens
        tr.queue_wait_s = max(0.0, wave_start - tr.enqueued_at)
        tr.ttft_s = now - tr.enqueued_at
        m, lb = self.metrics, self._labels
        m.observe("engine_queue_wait_seconds", tr.queue_wait_s, lb)
        m.observe("engine_ttft_seconds", tr.ttft_s, lb)

    def on_retire(self, request_id: int, *, new_tokens: int,
                  finish_reason: str) -> RequestTrace | None:
        tr = self._traces.pop(request_id, None)
        if tr is None:
            return None
        now = time.monotonic()
        tr.finished_at = now
        tr.new_tokens = new_tokens
        tr.finish_reason = finish_reason
        tr.e2e_s = now - tr.enqueued_at
        decode_s = now - (tr.first_token_at or now)
        tr.itl_s = decode_s / (new_tokens - 1) if new_tokens > 1 else 0.0
        self.completed.append(tr)
        m, lb = self.metrics, self._labels
        m.observe("engine_e2e_seconds", tr.e2e_s, lb)
        if new_tokens > 1:
            m.observe("engine_itl_seconds", tr.itl_s, lb)
        m.increment("engine_requests_total", 1.0,
                    {**lb, "finish_reason": finish_reason})
        m.increment("engine_tokens_total", float(new_tokens),
                    {**lb, "kind": "generated"})
        m.increment("engine_tokens_total",
                    float(tr.prompt_len - tr.prefix_hit_tokens),
                    {**lb, "kind": "prompt"})
        if tr.prefix_hit_tokens:
            m.increment("engine_tokens_total",
                        float(tr.prefix_hit_tokens),
                        {**lb, "kind": "prompt_cached"})
        return tr

    # -- steps ----------------------------------------------------------

    def next_step(self) -> int:
        return self.recorder.next_seq()

    def record_step(self, kind: str, duration_s: float, *,
                    seq: int | None = None, rows: int = 0,
                    batch: int = 0, tokens: int = 0,
                    padded_tokens: int = 0, draft_tokens: int = 0,
                    accepted_tokens: int = 0,
                    route: str = "") -> StepRecord:
        rec = StepRecord(
            seq=self.recorder.next_seq() if seq is None else seq,
            kind=kind, t_wall=time.time(), duration_s=duration_s,
            rows=rows, batch=batch, tokens=tokens,
            padded_tokens=padded_tokens, draft_tokens=draft_tokens,
            accepted_tokens=accepted_tokens, route=route)
        self.recorder.record(rec)
        m, lb = self.metrics, self._labels
        m.observe("engine_step_seconds", duration_s,
                  {**lb, "kind": kind})
        if batch:
            m.gauge("engine_slot_occupancy", rec.occupancy, lb)
        if padded_tokens:
            m.gauge("engine_padding_waste_ratio", rec.padding_waste, lb)
        return rec

    def gauge_queue(self, queue_depth: int, active: int | None = None
                    ) -> None:
        m, lb = self.metrics, self._labels
        m.gauge("engine_queue_depth", float(queue_depth), lb)
        if active is not None and self.num_slots:
            m.gauge("engine_slot_occupancy",
                    active / self.num_slots, lb)

    # -- scheduler (engine/scheduler.py) --------------------------------

    def sched_gauges(self, tenant_depths: dict[str, int],
                     deficits: dict[str, float] | None = None) -> None:
        """Per-tenant scheduler state → gauges. Tenant label defaults
        to "default" for the anonymous tenant so the series is always
        well-formed."""
        m, lb = self.metrics, self._labels
        for tenant, depth in tenant_depths.items():
            m.gauge("engine_sched_tenant_queue_depth", float(depth),
                    {**lb, "tenant": tenant or "default"})
        for tenant, d in (deficits or {}).items():
            m.gauge("engine_sched_deficit", float(d),
                    {**lb, "tenant": tenant or "default"})

    def on_shed(self, tenant: str, priority: str) -> None:
        self.metrics.increment(
            "engine_sched_shed_total", 1.0,
            {**self._labels, "tenant": tenant or "default",
             "priority": priority or "batch"})

    def on_prefill_chunks(self, rows: int = 1) -> None:
        self.metrics.increment("engine_sched_prefill_chunks_total",
                               float(rows), self._labels)

    # -- resilience (engine/faults.py, engine/supervisor.py) ------------

    def on_fault_injected(self, kind: str, mode: str) -> None:
        self.metrics.increment(
            "engine_fault_injected_total", 1.0,
            {**self._labels, "kind": kind, "mode": mode})

    def on_watchdog_trip(self, kind: str) -> None:
        self.metrics.increment("engine_fault_watchdog_trips_total", 1.0,
                               {**self._labels, "kind": kind})

    def breaker_gauge(self, breaker: str, state: float) -> None:
        """0 closed | 0.5 half-open | 1 open (CircuitBreaker.GAUGE)."""
        self.metrics.gauge("engine_fault_breaker_state", float(state),
                           {**self._labels, "breaker": breaker})

    def on_replay(self, n: int = 1) -> None:
        self.metrics.increment("engine_recovery_replays_total",
                               float(n), self._labels)

    def on_replay_failed(self, n: int = 1) -> None:
        self.metrics.increment("engine_recovery_failed_total",
                               float(n), self._labels)

    def gauge_quarantined(self, n: int) -> None:
        self.metrics.gauge("engine_recovery_quarantined_slots",
                           float(n), self._labels)

    def on_released_pins(self, n: int = 1) -> None:
        self.metrics.increment("engine_recovery_released_pins_total",
                               float(n), self._labels)

    def on_deadline_expired(self, n: int = 1) -> None:
        self.metrics.increment(
            "engine_recovery_deadline_expired_total", float(n),
            self._labels)

    # -- paged KV block pool (engine/kv_pool.py) ------------------------

    def gauge_kv_pool(self, free_blocks: int, pinned_blocks: int,
                      fragmentation_ratio: float) -> None:
        m, lb = self.metrics, self._labels
        m.gauge("engine_kv_pool_free_blocks", float(free_blocks), lb)
        m.gauge("engine_kv_pool_pinned_blocks", float(pinned_blocks),
                lb)
        m.gauge("engine_kv_pool_fragmentation_ratio",
                float(fragmentation_ratio), lb)

    def on_zero_copy_admits(self, n: int = 1) -> None:
        self.metrics.increment("engine_kv_pool_zero_copy_admits_total",
                               float(n), self._labels)

    def gauge_kv_route(self, route: str) -> None:
        """Resolved paged dispatch route ('kernel' | 'reference'),
        emitted once at engine build — a label-dimensioned constant
        gauge, the Prometheus idiom for build info."""
        self.metrics.gauge("engine_kv_route", 1.0,
                           {**self._labels, "route": route})

    # -- disaggregated roles (engine/roles.py) --------------------------

    def gauge_role_occupancy(self, role: str, occupancy: float) -> None:
        # engine_role, not role: the bare label is reserved for the
        # cross-process aggregator's proc/role stamp (obs/ship.py).
        self.metrics.gauge("engine_role_occupancy", float(occupancy),
                           {**self._labels, "engine_role": role or "both"})

    def on_handoff(self, blocks: int, wait_s: float) -> None:
        """One prefill→decode KV handoff completed: ``blocks`` pool
        blocks moved, ``wait_s`` between prefill-ready and
        decode-admit (the DisaggregatedEngine wrapper drives this)."""
        m, lb = self.metrics, self._labels
        m.increment("engine_role_handoff_blocks_total", float(blocks),
                    lb)
        m.observe("engine_role_handoff_wait_seconds", float(wait_s),
                  lb)

    # -- durable request journal (engine/journal.py) --------------------

    def gauge_journal(self, depth: int, checkpoint_lag: int) -> None:
        m, lb = self.metrics, self._labels
        m.gauge("engine_journal_depth", float(depth), lb)
        m.gauge("engine_journal_checkpoint_lag", float(checkpoint_lag),
                lb)

    def on_journal_replayed(self, n: int = 1) -> None:
        self.metrics.increment("engine_journal_replayed_total",
                               float(n), self._labels)

    def update_ledgers(self, prefix_stats: dict | None = None,
                       spec_stats: dict | None = None) -> None:
        """Export the engine's existing ledgers (prefix_stats /
        spec_stats) as gauges. Called at retire cadence — the ledgers
        are cumulative, so per-step export buys nothing."""
        m, lb = self.metrics, self._labels
        if prefix_stats and prefix_stats.get("enabled"):
            m.gauge("engine_prefix_hit_rate",
                    float(prefix_stats.get("hit_rate", 0.0)), lb)
        if spec_stats and spec_stats.get("enabled"):
            m.gauge("engine_spec_acceptance_rate",
                    float(spec_stats.get("acceptance_rate", 0.0)), lb)
            m.gauge("engine_spec_draft_hit_rate",
                    float(spec_stats.get("draft_hit_rate", 0.0)), lb)
            m.gauge("engine_tokens_per_weight_pass",
                    float(spec_stats.get("tokens_per_weight_pass",
                                         0.0)), lb)

    # -- summaries ------------------------------------------------------

    def in_flight(self) -> list[RequestTrace]:
        return list(self._traces.values())

    def correlation_ids(self) -> list[str]:
        """Correlation ids of the requests in flight (error reports)."""
        return [t.correlation_id for t in self._traces.values()
                if t.correlation_id]

    def latency_summary(self, last_n: int | None = None) -> dict:
        """Percentile summary over the last ``last_n`` completed
        requests (None = all retained) plus mean occupancy over the
        recorded decode-path steps — the bench's telemetry columns."""
        traces = list(self.completed)
        if last_n is not None:
            traces = traces[-last_n:]
        ttfts = sorted(t.ttft_s for t in traces)
        itls = sorted(t.itl_s for t in traces if t.new_tokens > 1)

        def pct(sorted_vals: list[float], q: float) -> float:
            if not sorted_vals:
                return 0.0
            i = min(len(sorted_vals) - 1,
                    max(0, round(q * (len(sorted_vals) - 1))))
            return sorted_vals[i]

        decode_steps = [r for r in self.recorder.records()
                        if r.kind in ("decode", "verify", "piggyback")
                        and r.batch]
        if last_n is not None and traces:
            # occupancy must describe the same window the percentiles
            # do: drop steps older than the oldest counted request
            # (warmup dispatches would otherwise depress the mean)
            cutoff = min(t.enqueued_wall for t in traces)
            decode_steps = [r for r in decode_steps
                            if r.t_wall >= cutoff]
        occ = (sum(r.occupancy for r in decode_steps) / len(decode_steps)
               if decode_steps else 0.0)
        return {
            "requests": len(traces),
            "ttft_p50_s": round(pct(ttfts, 0.50), 6),
            "ttft_p95_s": round(pct(ttfts, 0.95), 6),
            "ttft_p99_s": round(pct(ttfts, 0.99), 6),
            "itl_mean_s": round(sum(itls) / len(itls), 6) if itls
            else 0.0,
            "itl_p95_s": round(pct(itls, 0.95), 6),
            "mean_occupancy": round(occ, 4),
        }

    # -- flight-recorder dump -------------------------------------------

    def dump(self, *, error: BaseException | None = None,
             extra: dict | None = None) -> dict:
        """The post-mortem record: ring buffer + spans, JSON-ready."""
        out = {
            "engine": self.engine_label,
            "created_wall": self.created_wall,
            "dumped_wall": time.time(),
            "num_slots": self.num_slots,
            "errors": self.errors,
            "in_flight": [t.as_dict() for t in self.in_flight()],
            "correlation_ids": self.correlation_ids(),
            "completed_tail": [t.as_dict()
                               for t in list(self.completed)[-64:]],
            "steps": self.recorder.as_dicts(),
            "summary": self.latency_summary(),
        }
        if error is not None:
            out["error"] = {"type": type(error).__name__,
                            "message": str(error)}
        if extra:
            out.update(extra)
        return out

    def abandon_in_flight(self, finish_reason: str = "error"
                          ) -> list[RequestTrace]:
        """Close every in-flight span: a failed dispatch killed those
        requests, and a long-lived engine that keeps serving after the
        error (the async runner's containment) must not accumulate
        dead spans in ``_traces`` forever — nor should the NEXT
        post-mortem list them as "in flight". Counted in
        ``engine_requests_total{finish_reason="error"}`` but kept OUT
        of the latency histograms (an aborted request has no honest
        e2e latency)."""
        now = time.monotonic()
        out = []
        for rid in list(self._traces):
            tr = self._traces.pop(rid)
            tr.finished_at = now
            tr.finish_reason = finish_reason
            tr.e2e_s = now - tr.enqueued_at
            self.completed.append(tr)
            self.metrics.increment(
                "engine_requests_total", 1.0,
                {**self._labels, "finish_reason": finish_reason})
            out.append(tr)
        return out

    def dump_to_file(self, directory: str | None = None,
                     tag: str = "flight",
                     error: BaseException | None = None,
                     data: dict | None = None) -> str:
        """Write ``data`` (or a fresh ``dump(error=...)``) as JSON.
        The filename counter is local — burning flight-recorder step
        ids on filenames would leave holes in the Perfetto step-id
        sequence."""
        directory = directory or self.dump_dir or _default_dump_dir
        if not directory:
            raise ValueError("no flight-record dump directory configured")
        path = pathlib.Path(directory)
        path.mkdir(parents=True, exist_ok=True)
        self._dump_seq += 1
        fname = (f"{tag}-{self.engine_label}-"
                 f"{int(time.time())}-{self._dump_seq}.json")
        target = path / fname
        if data is None:
            data = self.dump(error=error)
        target.write_text(json.dumps(data, indent=2, default=str))
        return str(target)

    def record_error(self, exc: BaseException,
                     context: dict[str, Any] | None = None
                     ) -> dict:
        """Engine dispatch failed: count it and auto-dump the flight
        recorder (to the configured dir when one is set — a post-mortem
        must not depend on someone remembering to ask). The in-flight
        spans are named in the dump, then closed with
        finish_reason="error" (see ``abandon_in_flight``). Returns the
        dump dict with ``dump_path`` when a file was written, so error
        reporters can attach it."""
        self.errors += 1
        self.metrics.increment("engine_errors_total", 1.0, self._labels)
        dump = self.dump(error=exc, extra=dict(context or {}))
        directory = self.dump_dir or _default_dump_dir
        if directory:
            try:
                dump["dump_path"] = self.dump_to_file(
                    directory=directory, tag="error", data=dump)
            except Exception:
                pass   # the dump must never mask the engine error
        self.abandon_in_flight()
        return dump


def attach_service_collector(holder: Any, metrics,
                             attrs: tuple[str, ...] = ("engine",
                                                       "long_engine",
                                                       "_engine")
                             ) -> int:
    """Production wiring: re-point every engine telemetry hanging off
    ``holder`` (a summarizer / embedding provider) at the SERVICE's
    shared collector — the one the gateway's ``/metrics`` serves.
    Without this the engines' default per-engine collectors render
    beautifully in tests and never reach a scrape in production, which
    is precisely the references-a-series-nobody-emits rot the contract
    tests exist to prevent.

    Only re-points onto an ``InMemoryMetrics``-family collector
    (Pushgateway included): swapping in a Noop would silently discard
    the engines' own renderable copy. Returns how many telemetries
    were re-pointed."""
    if not isinstance(metrics, InMemoryMetrics):
        return 0
    n = 0
    for attr in attrs:
        eng = getattr(holder, attr, None)
        tele = getattr(eng, "telemetry", None)
        if isinstance(tele, EngineTelemetry) and tele.metrics is not \
                metrics:
            tele.metrics = metrics
            n += 1
    return n


def resolve_telemetry(telemetry, *, engine: str, num_slots: int = 0
                      ) -> EngineTelemetry | None:
    """One place for the engines' ``telemetry=`` argument semantics:
    True (the default) builds a fresh recorder, False/None disables,
    an ``EngineTelemetry`` instance is used as-is (shared collector),
    a ``MetricsCollector`` builds a recorder exporting into it."""
    if telemetry is True:
        return EngineTelemetry(engine=engine, num_slots=num_slots)
    if not telemetry:
        return None
    if isinstance(telemetry, EngineTelemetry):
        return telemetry
    if isinstance(telemetry, MetricsCollector):
        return EngineTelemetry(engine=engine, num_slots=num_slots,
                               metrics=telemetry)
    raise ValueError(
        f"telemetry must be bool, EngineTelemetry or MetricsCollector, "
        f"got {type(telemetry).__name__}")
