"""Tokenizers for the serving engines.

Three drivers behind one interface:

* ``ByteTokenizer`` — raw UTF-8 bytes + specials; zero-dependency, works
  with any vocab ≥ 259. Default for self-contained runs and tests.
* ``HashWordTokenizer`` — deterministic word-hash ids; the encoder-side
  stand-in when no trained vocabulary is shipped (embeddings only need a
  stable text→id map to be meaningful relative to each other).
* ``HFTokenizer`` — loads a real trained BPE/WordPiece ``tokenizer.json``
  via the ``tokenizers`` library for production checkpoints.

The reference delegates tokenization to its external engines entirely and
budgets with a ~1.3 tokens/word estimator
(``orchestrator/app/context_selectors.py:17``); here the real ids are
first-party.
"""

from __future__ import annotations

import abc
import hashlib

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
N_SPECIALS = 3


def stable_block_hash(prev: bytes, tokens) -> bytes:
    """Chained hash of one token block for the prefix KV cache.

    ``prev`` is the parent block's digest (``b""`` for the first block),
    so a digest commits to the ENTIRE token prefix, not just its own
    block — two prompts share a radix-trie node iff every token from
    position zero matches. Uses blake2b over the explicit little-endian
    token bytes, NOT Python's ``hash()``: the digest keys cross-request
    (and potentially cross-process / on-disk) reuse, so it must not
    change between interpreter runs (PYTHONHASHSEED) or platforms."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(b"".join(int(t).to_bytes(8, "little", signed=True)
                      for t in tokens))
    return h.digest()


class NgramDraftIndex:
    """Prompt-lookup draft index for speculative decoding (one stream).

    The n-gram analogue of the radix hashing above, but WITHIN one
    stream instead of across requests: the last ``min_ngram``–``ngram``
    tokens of the stream's context key a map to the position where the
    same n-gram last occurred WITH a continuation, and the tokens that
    followed it become the draft (Saxena, *Prompt Lookup Decoding*,
    2023). Summaries and RAG answers copy long prompt spans verbatim
    (quotes, names, draft identifiers, header fields), so drafts come
    from the stream's own context with zero extra model and zero extra
    HBM — the drafting side of ``GenerationEngine``'s ``_verify``
    dispatch.

    Unlike the prefix cache this index never leaves the host or the
    request: plain tuple keys are correct (no cross-process stability
    requirement), longest-n wins (a 3-gram match is a stronger copy
    signal than a 2-gram one), and the EARLIEST occurrence wins within
    an n — the PLD scan order, and the one that maximizes the
    available continuation: a tail-adjacent match can only draft as
    far as the repetition period, while a prompt-side match drafts the
    whole remembered span. An n-gram is only indexed once at least one
    token follows it, so the context's own tail can never match itself
    into an empty draft.

    Cost: O(ngram - min_ngram + 1) dict inserts per appended token —
    the only per-token host cost speculation adds to the decode path,
    mirroring how ``prompt_digests`` is the only one on admission.
    """

    def __init__(self, tokens=(), *, ngram: int = 3, min_ngram: int = 2):
        if min_ngram < 1 or ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= ngram, got {min_ngram}..{ngram}")
        self.ngram = int(ngram)
        self.min_ngram = int(min_ngram)
        self._tokens: list[int] = []
        self._maps: dict[int, dict[tuple, int]] = {
            n: {} for n in range(self.min_ngram, self.ngram + 1)}
        self._next_end = 0   # first n-gram end position not yet indexed
        if tokens:
            self.extend(tokens)

    def __len__(self) -> int:
        return len(self._tokens)

    def extend(self, tokens) -> None:
        """Append accepted tokens and index every n-gram that now has a
        continuation (the n-gram ending at the new tail stays
        unindexed until the NEXT extend gives it a continuation)."""
        self._tokens.extend(int(t) for t in tokens)
        t = self._tokens
        for end in range(self._next_end, len(t)):
            # ``end`` is the exclusive end of the n-gram and t[end] its
            # continuation — the n-gram ending AT len(t) has none yet
            # and waits for the next extend
            for n, m in self._maps.items():
                if end >= n:
                    m.setdefault(tuple(t[end - n:end]), end)
        self._next_end = len(t)

    def draft(self, max_tokens: int) -> list[int]:
        """Up to ``max_tokens`` drafted continuations of the current
        tail, or ``[]`` when no indexed n-gram matches. Longest n
        first; the returned span is a verbatim copy of the context
        after the matched occurrence."""
        if max_tokens <= 0:
            return []
        t = self._tokens
        for n in range(self.ngram, self.min_ngram - 1, -1):
            if len(t) <= n:
                continue
            end = self._maps[n].get(tuple(t[-n:]))
            if end is not None:
                return t[end:end + max_tokens]
        return []


class Tokenizer(abc.ABC):
    pad_id = PAD_ID
    bos_id = BOS_ID
    eos_id = EOS_ID

    @property
    @abc.abstractmethod
    def vocab_size(self) -> int: ...

    @abc.abstractmethod
    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> list[int]: ...

    @abc.abstractmethod
    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer(Tokenizer):
    """UTF-8 bytes shifted past the special ids."""

    def __init__(self, vocab_size: int = 259):
        if vocab_size < 256 + N_SPECIALS:
            raise ValueError("ByteTokenizer needs vocab_size >= 259")
        self._vocab = vocab_size

    @property
    def vocab_size(self) -> int:
        return self._vocab

    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> list[int]:
        ids = [b + N_SPECIALS for b in text.encode("utf-8")]
        if add_bos:
            ids.insert(0, BOS_ID)
        if add_eos:
            ids.append(EOS_ID)
        return ids

    def decode(self, ids: list[int]) -> str:
        data = bytes(i - N_SPECIALS for i in ids
                     if N_SPECIALS <= i < 256 + N_SPECIALS)
        return data.decode("utf-8", errors="replace")


class HashWordTokenizer(Tokenizer):
    """Stable word→id hashing (sha1 mod vocab). Not invertible — decode
    returns placeholders — so only suitable for the encoder path."""

    def __init__(self, vocab_size: int = 30522):
        if vocab_size <= N_SPECIALS + 1:
            raise ValueError("vocab too small")
        self._vocab = vocab_size

    @property
    def vocab_size(self) -> int:
        return self._vocab

    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> list[int]:
        span = self._vocab - N_SPECIALS
        ids = [
            N_SPECIALS + int.from_bytes(
                hashlib.sha1(w.lower().encode()).digest()[:4], "big") % span
            for w in text.split()
        ]
        if add_bos:
            ids.insert(0, BOS_ID)
        if add_eos:
            ids.append(EOS_ID)
        return ids

    def decode(self, ids: list[int]) -> str:
        return " ".join(f"<{i}>" for i in ids)


class HFTokenizer(Tokenizer):
    """A trained ``tokenizer.json`` via the HuggingFace tokenizers lib.

    ``bos_id``/``eos_id`` default to the Llama/Mistral-family convention
    (1/2) but can be overridden from checkpoint metadata. ``eos_id`` may
    be a list (Llama-3.1-style multi-EOS configs); the first id is used
    when appending, all are stripped on decode. ``pad_id`` is only
    filtered when explicitly given — id 0 is a real vocab token in some
    families."""

    def __init__(self, path: str, *, bos_id: int = BOS_ID,
                 eos_id=EOS_ID, pad_id: int | None = None):
        from tokenizers import Tokenizer as _HFTok  # lazy: optional dep
        self._tok = _HFTok.from_file(path)
        self.bos_id = int(bos_id)
        eos_list = list(eos_id) if isinstance(eos_id, (list, tuple)) \
            else [int(eos_id)]
        self.eos_id = int(eos_list[0])
        self.eos_ids = tuple(int(e) for e in eos_list)
        self.pad_id = pad_id if pad_id is not None else -1

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = False) -> list[int]:
        ids = list(self._tok.encode(text).ids)
        # Real Mistral/Llama tokenizer.json files carry a post-processor
        # that already emits BOS; don't double it.
        if add_bos and (not ids or ids[0] != self.bos_id):
            ids.insert(0, self.bos_id)
        if add_eos and (not ids or ids[-1] not in self.eos_ids):
            ids.append(self.eos_id)
        return ids

    def decode(self, ids: list[int]) -> str:
        specials = {self.pad_id, self.bos_id, *self.eos_ids}
        return self._tok.decode([i for i in ids if i not in specials])


def create_tokenizer(driver: str = "byte", *, vocab_size: int = 259,
                     path: str | None = None, bos_id: int = BOS_ID,
                     eos_id: int = EOS_ID) -> Tokenizer:
    if driver == "byte":
        return ByteTokenizer(vocab_size)
    if driver == "hash_word":
        return HashWordTokenizer(vocab_size)
    if driver == "hf":
        if not path:
            raise ValueError("hf tokenizer needs a path")
        return HFTokenizer(path, bos_id=bos_id, eos_id=eos_id)
    raise ValueError(f"unknown tokenizer driver {driver!r}")
