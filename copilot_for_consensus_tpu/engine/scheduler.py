"""SLO-aware scheduler: admission, fairness, and load shedding.

The engines admit FIFO; under adversarial mixed traffic that FIFO is
the whole problem — a burst of long prompts monopolizes admission waves
and starves decode ITL, one chatty tenant starves everyone else, and
the only backpressure is the queue growing until the
``EngineQueueBacklogGrowing`` alert fires. This module owns the traffic
policy the serving literature converged on, as pure host-side state the
engines consult around their existing dispatch loop:

* **Chunked prefill** (Sarathi-style): the scheduler releases at most
  ``prefill_wave_tokens`` of prompt work per engine step, and prompts
  longer than ``chunk_tokens`` are split into fixed-size chunks
  dispatched BETWEEN decode windows (``GenerationEngine._chunk_step``
  — a seeded prefill over the slot's own partially-filled cache, the
  PR-1 ``prefill_attention_seeded`` machinery generalized into a
  continuation), so a 16k-token prompt costs many small ITL bumps
  instead of one multi-second stall.
* **Per-tenant fairness**: requests carry a ``tenant`` key and a
  priority lane (``interactive`` > ``batch``). Each lane runs weighted
  deficit-round-robin over tenant queues — every round a tenant's
  deficit grows by ``quantum_tokens x weight`` and it may release that
  many prompt tokens, so a tenant submitting 100x more work gets its
  weighted share, not the whole engine. Per-tenant token quotas cap
  queued backlog per tenant with an honest rejection instead of
  unbounded queueing.
* **SLO-aware load shedding**: a closed loop over the engine's own
  telemetry (``engine/telemetry.py`` spans: queue-wait p95, TTFT p99,
  occupancy) sheds the lowest-priority work FIRST — and everything at
  the hard cap — with a structured :class:`EngineOverloaded` carrying
  an honest ``retry_after_s``, surfaced as HTTP 429 + ``Retry-After``
  by the service layer (``services/http.py``) *before* the queue ever
  reaches the ``EngineQueueBacklogGrowing`` alert threshold.
* **Prefix-cache-aware placement**: at release time, requests sharing
  a radix-cache block prefix (same first-block digest) are pulled into
  the same admission wave, so template-sharing requests ride one
  seeded dispatch and the pool gather amortizes.

Everything in this module is import-light host code (no jax): the
service layer imports :class:`EngineOverloaded` for its 429 mapping
without touching the device stack, and the scheduler itself is unit-
testable without a device. The device-side mechanism (the chunked
prefill dispatch) lives in ``engine/generation.py``; the policy lives
here. Design notes: ``docs/SCHEDULER.md``.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

#: priority lanes, highest first — the shed order is the reverse
PRIORITIES = ("interactive", "batch")


class EngineOverloaded(Exception):
    """Structured admission rejection: the engine is shedding load.

    Carries everything the edge needs for an honest 429: how long the
    caller should back off (``retry_after_s``, from the scheduler's
    drain estimate, not a constant), which tenant/priority was shed,
    why, and the pipeline ``correlation_id`` so the rejection joins the
    request's trace in logs and error events."""

    def __init__(self, message: str, *, retry_after_s: float = 1.0,
                 tenant: str = "", priority: str = "",
                 reason: str = "overloaded", correlation_id: str = ""):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.tenant = tenant
        self.priority = priority
        self.reason = reason
        self.correlation_id = correlation_id

    def as_event_fields(self) -> dict:
        """The structured error-event payload (HTTP body / bus failure
        event tags)."""
        return {
            "error": str(self),
            "reason": self.reason,
            "retry_after_s": round(self.retry_after_s, 3),
            "tenant": self.tenant,
            "priority": self.priority,
            "correlation_id": self.correlation_id,
        }


def jain_index(values: Iterable[float]) -> float:
    """Jain's fairness index over per-tenant allocations: 1.0 is
    perfectly fair, 1/n is one tenant taking everything. The bench's
    ``fairness_jain_index`` column and the DRR property tests both use
    this definition, so they can never drift apart."""
    xs = [float(v) for v in values]
    if not xs:
        return 1.0
    s = sum(xs)
    sq = sum(x * x for x in xs)
    if sq <= 0.0:
        return 1.0
    return (s * s) / (len(xs) * sq)


@dataclass(frozen=True)
class SchedulerConfig:
    """Policy knobs. Defaults are sized so the shed thresholds sit
    BELOW the ``EngineQueueBacklogGrowing`` alert's ``> 64`` queue
    depth — shedding is supposed to fire first (the alert firing means
    the scheduler failed), and the contract test in
    ``tests/test_engine_scheduler.py`` pins that ordering."""

    #: per-request prefill tokens per chunk dispatch: prompts longer
    #: than this split into chunks co-scheduled with decode windows
    chunk_tokens: int = 256
    #: total prompt tokens the scheduler releases into admission per
    #: engine step — the ITL bound: one step's prefill work can never
    #: exceed this
    prefill_wave_tokens: int = 2048
    #: DRR quantum: deficit granted per tenant per scheduling round
    quantum_tokens: int = 512
    #: tenant → DRR weight (share of admission tokens under contention)
    tenant_weights: dict[str, float] = field(default_factory=dict)
    default_weight: float = 1.0
    #: tenant → max QUEUED prompt tokens (0 = unlimited); beyond it the
    #: tenant's own submits shed while others keep flowing
    tenant_quota_tokens: dict[str, int] = field(default_factory=dict)
    default_quota_tokens: int = 0
    #: hard cap: total queued requests at which EVERYTHING sheds.
    #: Strictly below the EngineQueueBacklogGrowing threshold (64).
    max_queue_depth: int = 48
    #: batch-lane shed point: beyond this queued depth only interactive
    #: work admits (shed lowest priority first)
    batch_shed_depth: int = 32
    #: SLO bounds the closed loop sheds against (matching the alert
    #: pack: EngineTTFTP99High fires at 30s)
    ttft_p99_slo_s: float = 30.0
    queue_wait_p95_slo_s: float = 20.0
    #: completed-trace window the closed loop computes percentiles over
    signal_window: int = 64
    #: Retry-After clamp for the honest 429
    min_retry_after_s: float = 1.0
    max_retry_after_s: float = 60.0
    #: embedding-engine wave sizing: rows per encode dispatch (0 = the
    #: engine's own batch_size); halved under overload so an embed
    #: burst yields the host loop between tiles
    embed_wave_rows: int = 0
    #: embedding burst hard cap per call (0 = unlimited): a burst
    #: larger than this sheds instead of monopolizing the device
    embed_max_burst_texts: int = 0
    #: paged-KV (kv_pool_blocks) free-block shed thresholds: fractions
    #: of the pool below which the batch lane sheds / everything sheds.
    #: The ratios apply to the engine's HEADROOM (free + evictable
    #: minus admitted worst-case claims), so pressure shows before the
    #: allocator actually runs dry.
    kv_low_ratio: float = 0.10
    kv_critical_ratio: float = 0.02
    #: disaggregated-role shed thresholds (engine ``role="prefill"``):
    #: finished prefills awaiting handoff (slot-parked + exported-but-
    #: unadmitted) beyond this depth shed the batch lane (decode chips
    #: are the bottleneck — prefilling further ahead only pins pool
    #: blocks behind the handoff); at 2x everything sheds. The engine
    #: additionally stops RELEASING waves at its ``handoff_high`` mark
    #: (default num_slots/2), so the shed levels here are the
    #: door-side mirror of that hold — decode ITL stays flat while
    #: prefill chips saturate on work decode can actually take. Size
    #: this to the role PAIR: the backlog signal is bounded by
    #: prefill slots + the wrapper's capacity-capped pending queue,
    #: so a threshold above that sum can never fire.
    handoff_shed_depth: int = 16


@dataclass
class _TenantState:
    deficit: float = 0.0
    queued_tokens: int = 0
    admitted_tokens: int = 0          # fairness ledger (jain_index)
    shed: int = 0


class Scheduler:
    """Admission owner for one engine (or shared across engines).

    The engine calls, per step:

    * :meth:`observe` — feed the closed loop (queue depth, occupancy,
      telemetry spans); recomputes the overload level and the
      Retry-After drain estimate.
    * :meth:`select` — pop the next wave's requests in DRR order
      (interactive lane first), bounded by a token budget and the free
      slot count, with prefix-placement grouping.

    Callers (services / async runner / the engine's ``submit``) call
    :meth:`check_admission` first; it raises :class:`EngineOverloaded`
    when the request should shed. All methods are cheap dict/deque work
    under the GIL — safe to call from a caller thread while the
    dispatcher owns the engine, same discipline as the telemetry
    counters."""

    def __init__(self, cfg: SchedulerConfig | None = None, *,
                 telemetry: Any = None):
        self.cfg = cfg or SchedulerConfig()
        #: engine telemetry (EngineTelemetry | None) — the sched_*
        #: gauges/counters export through it when present
        self.telemetry = telemetry
        self._queues: dict[tuple[str, str],
                           "collections.deque"] = {}
        self._tenants: dict[str, _TenantState] = {}
        self._rotation: list[str] = []     # DRR visit order
        #: closed-loop state (observe())
        self.overload_level = 0            # 0 ok | 1 shed batch | 2 all
        #: external pressure floor on the overload level, set by the
        #: engine supervisor's resource breaker (engine/supervisor.py):
        #: repeated device resource exhaustion lowers the engine's
        #: occupancy cap AND raises this, so the shed loop starts
        #: rejecting batch work at the edge instead of re-OOMing.
        #: Cleared by the supervisor when capacity is restored.
        self.pressure = 0
        self.retry_after_s = self.cfg.min_retry_after_s
        self.last_signals: dict[str, float] = {}
        #: requests staged inside the engine (queue/prefilling/chunking)
        #: as of the last observe() — check_admission counts them toward
        #: the depth caps so a burst between steps cannot blow past them
        self._engine_staged = 0
        #: counters (bench/tests read these; metrics mirror them)
        self.shed_total = 0
        self.submitted_total = 0

    # -- queue state ----------------------------------------------------

    @property
    def queued(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def queued_for(self, tenant: str) -> int:
        return sum(len(q) for (t, _lane), q in self._queues.items()
                   if t == tenant)

    def tenant_depths(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for (t, _lane), q in self._queues.items():
            out[t] = out.get(t, 0) + len(q)
        return out

    def fairness_snapshot(self) -> dict[str, float]:
        """Per-tenant admitted prompt tokens normalized by weight — the
        quantity DRR equalizes under contention; feed it to
        :func:`jain_index` for the bench column."""
        return {t: st.admitted_tokens / self._weight(t)
                for t, st in self._tenants.items()
                if st.admitted_tokens}

    #: hard cap on tracked tenant states: beyond it, NEW tenant names
    #: fold into one overflow bucket — an adversarial spray of unique
    #: tenant strings must not grow host memory, the DRR rotation, or
    #: the per-tenant Prometheus series without bound
    MAX_TENANTS = 256

    def _weight(self, tenant: str) -> float:
        w = self.cfg.tenant_weights.get(tenant, self.cfg.default_weight)
        return max(1e-6, float(w))

    def _tenant_key(self, tenant: str) -> str:
        if tenant in self._tenants \
                or len(self._tenants) < self.MAX_TENANTS:
            return tenant
        return "__overflow__"

    def _state(self, tenant: str) -> _TenantState:
        tenant = self._tenant_key(tenant)
        st = self._tenants.get(tenant)
        if st is None:
            st = self._tenants[tenant] = _TenantState()
            self._rotation.append(tenant)
        return st

    # -- admission gate -------------------------------------------------

    def check_admission(self, *, tenant: str = "",
                        priority: str = "interactive",
                        prompt_tokens: int = 0,
                        correlation_id: str = "") -> None:
        """Raise :class:`EngineOverloaded` when this request should be
        shed; return normally when it may enqueue. Shed order: tenant
        quota first (that tenant's own backlog), then the batch lane at
        ``batch_shed_depth`` / overload level 1, then everything at
        ``max_queue_depth`` / level 2."""
        if priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {priority!r}; one of {PRIORITIES}")
        depth = self.queued + self._engine_staged
        quota = self.cfg.tenant_quota_tokens.get(
            tenant, self.cfg.default_quota_tokens)
        if quota and self._state(tenant).queued_tokens + prompt_tokens \
                > quota:
            self._shed(tenant, priority, "tenant-quota", correlation_id,
                       f"tenant {tenant!r} over its {quota}-token "
                       f"queued quota")
        if depth >= self.cfg.max_queue_depth or self.overload_level >= 2:
            self._shed(tenant, priority, "queue-full", correlation_id,
                       f"admission queue at {depth} (cap "
                       f"{self.cfg.max_queue_depth})")
        if priority != "interactive" and (
                depth >= self.cfg.batch_shed_depth
                or self.overload_level >= 1):
            self._shed(tenant, priority, "slo-pressure", correlation_id,
                       "batch lane shed under SLO pressure "
                       f"(queue {depth}, level {self.overload_level})")

    def _shed(self, tenant: str, priority: str, reason: str,
              correlation_id: str, message: str) -> None:
        self.shed_total += 1
        self._state(tenant).shed += 1
        if self.telemetry is not None:
            # folded key: the shed counter's tenant label must obey the
            # same cardinality cap as the gauges
            self.telemetry.on_shed(self._tenant_key(tenant), priority)
        raise EngineOverloaded(
            f"engine overloaded: {message}; retry after "
            f"{self.retry_after_s:.1f}s",
            retry_after_s=self.retry_after_s, tenant=tenant,
            priority=priority, reason=reason,
            correlation_id=correlation_id)

    def enqueue(self, req: Any) -> None:
        """Queue an admitted request (engine ``submit`` calls this after
        ``check_admission`` passed). ``req`` needs ``tenant``,
        ``priority`` and ``prompt`` attributes (``generation.Request``)."""
        tenant = self._tenant_key(getattr(req, "tenant", "") or "")
        lane = getattr(req, "priority", "") or "interactive"
        self.submitted_total += 1
        self._state(tenant).queued_tokens += len(req.prompt)
        self._queues.setdefault((tenant, lane),
                                collections.deque()).append(req)
        self._export_gauges()

    # -- closed loop ----------------------------------------------------

    def observe(self, *, queued: int, active: int, num_slots: int,
                telemetry: Any = None, now: float | None = None,
                free_blocks: int | None = None,
                total_blocks: int | None = None,
                handoff_backlog: int | None = None) -> dict:
        """Recompute the overload level and Retry-After estimate from
        the engine's own signals. Called once per engine step (and from
        tests with synthetic traces).

        Shed policy: level 1 (batch lane sheds) when the latency SLOs
        are violated while the slots are actually saturated — high TTFT
        with idle slots is admission hysteresis, not overload — or when
        the queue passes ``batch_shed_depth``; level 2 (everything
        sheds) at ``max_queue_depth``. The queue-depth terms mean the
        loop degrades gracefully when telemetry is disabled.

        Paged engines (``kv_pool_blocks``) report FREE-BLOCK headroom
        (``free_blocks``: free + evictable minus admitted work's
        worst-case remaining claims, out of ``total_blocks``) — the
        load-shedding signal moves from free-slot counting to
        free-block accounting: under ``kv_low_ratio`` of the pool the
        batch lane sheds, under ``kv_critical_ratio`` everything does,
        whatever the queue depth says."""
        now = time.monotonic() if now is None else now
        self._engine_staged = max(0, queued - self.queued)
        tele = telemetry if telemetry is not None else self.telemetry
        qwait_p95 = ttft_p99 = 0.0
        rate = 0.0
        traces = []
        if tele is not None and getattr(tele, "completed", None):
            traces = list(tele.completed)[-self.cfg.signal_window:]
        if traces:
            qwaits = sorted(t.queue_wait_s for t in traces)
            ttfts = sorted(t.ttft_s for t in traces)
            qwait_p95 = qwaits[min(len(qwaits) - 1,
                                   int(0.95 * (len(qwaits) - 1)))]
            ttft_p99 = ttfts[min(len(ttfts) - 1,
                                 int(0.99 * (len(ttfts) - 1)))]
            span = max(1e-3, now - min(t.finished_at for t in traces))
            rate = len(traces) / span
        occupancy = active / num_slots if num_slots else 0.0
        slo_violated = (qwait_p95 > self.cfg.queue_wait_p95_slo_s
                        or ttft_p99 > self.cfg.ttft_p99_slo_s)
        level = 0
        if (slo_violated and occupancy >= 0.75) \
                or queued >= self.cfg.batch_shed_depth:
            level = 1
        if queued >= self.cfg.max_queue_depth:
            level = 2
        kv_ratio = None
        if free_blocks is not None and total_blocks:
            kv_ratio = max(0.0, free_blocks) / total_blocks
            if kv_ratio < self.cfg.kv_critical_ratio:
                level = 2
            elif kv_ratio < self.cfg.kv_low_ratio:
                level = max(level, 1)
        if handoff_backlog is not None and self.cfg.handoff_shed_depth:
            # prefill-role engines: parked handoffs mean the DECODE
            # side is the bottleneck — shed at the door instead of
            # prefilling work nothing can decode yet
            if handoff_backlog >= 2 * self.cfg.handoff_shed_depth:
                level = 2
            elif handoff_backlog >= self.cfg.handoff_shed_depth:
                level = max(level, 1)
        level = max(level, min(2, self.pressure))
        self.overload_level = level
        # Honest Retry-After: time to drain the current backlog at the
        # recently observed completion rate, clamped. No observed rate
        # with a standing backlog means the drain time is UNKNOWN —
        # advertise the max backoff rather than an optimistic guess.
        if rate > 0:
            est = queued / rate
        else:
            est = self.cfg.max_retry_after_s if queued else 0.0
        self.retry_after_s = min(
            self.cfg.max_retry_after_s,
            max(self.cfg.min_retry_after_s, est))
        self.last_signals = {
            "queue_wait_p95_s": round(qwait_p95, 6),
            "ttft_p99_s": round(ttft_p99, 6),
            "occupancy": round(occupancy, 4),
            "completion_rate": round(rate, 4),
            "queued": queued,
            "overload_level": level,
            "retry_after_s": round(self.retry_after_s, 3),
        }
        if kv_ratio is not None:
            self.last_signals["kv_headroom_ratio"] = round(kv_ratio, 4)
        if handoff_backlog is not None:
            self.last_signals["handoff_backlog"] = int(handoff_backlog)
        self._export_gauges()
        return self.last_signals

    # -- per-request deadlines (engine/supervisor.py policy) ------------

    def drop_expired(self, now: float | None = None) -> list:
        """Remove queued requests whose ``deadline_at`` has passed and
        return them (the engine retires each with
        ``finish_reason="deadline"`` — expired work is DROPPED, never
        computed). The per-tenant queued-token ledgers are repaid so
        quota accounting stays honest."""
        now = time.monotonic() if now is None else now
        out: list = []
        for (tenant, _lane), q in self._queues.items():
            if not q:
                continue
            keep = [r for r in q
                    if getattr(r, "deadline_at", float("inf")) > now]
            if len(keep) == len(q):
                continue
            st = self._tenants[tenant]
            for r in q:
                if getattr(r, "deadline_at", float("inf")) <= now:
                    out.append(r)
                    st.queued_tokens = max(
                        0, st.queued_tokens - len(r.prompt))
            q.clear()
            q.extend(keep)
        if out:
            self._export_gauges()
        return out

    def purge(self) -> list:
        """Drain EVERY tenant queue, repaying the queued-token ledgers
        and re-exporting the gauges; returns the dropped requests. The
        engine supervisor uses this after a suspect event (the dropped
        requests' handles were already failed — computing them would
        serve nobody)."""
        out: list = []
        for (tenant, _lane), q in self._queues.items():
            if not q:
                continue
            st = self._tenants[tenant]
            for r in q:
                out.append(r)
                st.queued_tokens = max(
                    0, st.queued_tokens - len(r.prompt))
            q.clear()
        if out:
            self._export_gauges()
        return out

    def recount_queued_tokens(self) -> dict[str, tuple[int, int]]:
        """Recompute every tenant's queued-token ledger from the
        actual queues; returns ``{tenant: (recorded, actual)}`` for
        the ones that drifted (already repaired). The supervisor's
        post-failure invariant audit calls this — ledger drift would
        silently skew quota enforcement forever."""
        actual: dict[str, int] = {}
        for (tenant, _lane), q in self._queues.items():
            actual[tenant] = actual.get(tenant, 0) + sum(
                len(r.prompt) for r in q)
        drift: dict[str, tuple[int, int]] = {}
        for tenant, st in self._tenants.items():
            want = actual.get(tenant, 0)
            if st.queued_tokens != want:
                drift[tenant] = (st.queued_tokens, want)
                st.queued_tokens = want
        if drift:
            self._export_gauges()
        return drift

    # -- wave composition (DRR + prefix placement) ----------------------

    def select(self, *, max_requests: int,
               token_budget: int | None = None,
               cost_fn: Callable[[Any], int] | None = None,
               placement_key: Callable[[Any], Any] | None = None
               ) -> list:
        """Pop the next admission wave in weighted-DRR order.

        Interactive lane drains before the batch lane ever runs (strict
        priority; the fairness guarantee is *within* a lane). Each
        visited tenant's deficit grows by ``quantum x weight`` per
        round and shrinks by the cost of every request it releases;
        ``cost_fn`` defaults to prompt length — the engine passes the
        prefix-cache SUFFIX length so cached prompts cost what they
        actually prefill. ``placement_key`` groups requests sharing a
        radix-cache prefix into the same wave (the pulled request's own
        tenant still pays the deficit, so fairness accounting stays
        honest). A request larger than the whole budget is released
        alone rather than starved forever."""
        cost_fn = cost_fn or (lambda r: len(r.prompt))
        budget = (token_budget if token_budget is not None
                  else self.cfg.prefill_wave_tokens)
        out: list = []
        for lane in PRIORITIES:
            if len(out) >= max_requests or budget <= 0:
                break
            picked, spent = self._select_lane(
                lane, max_requests - len(out), budget, cost_fn,
                placement_key, wave_empty=not out)
            budget -= spent
            out.extend(picked)
        if out:
            self._export_gauges()
        return out

    def _select_lane(self, lane: str, max_requests: int, budget: int,
                     cost_fn, placement_key, *, wave_empty: bool
                     ) -> tuple[list, int]:
        """Returns (released requests, tokens spent). ``wave_empty``
        gates the oversized-release escape: a request bigger than the
        whole budget may only go out when the WAVE (across lanes) has
        released nothing — otherwise one step could blow far past
        ``prefill_wave_tokens``, the bound chunked prefill exists to
        keep."""
        out: list = []
        spent = 0
        # bounded rounds: every round grants each queued tenant one
        # quantum; no progress in a full round means nothing affordable
        while len(out) < max_requests and budget > spent:
            progress = False
            for tenant in list(self._rotation):
                q = self._queues.get((tenant, lane))
                if not q:
                    # classic DRR: an idle queue's deficit resets so a
                    # silent tenant cannot bank an unbounded burst
                    if not self.queued_for(tenant):
                        self._tenants[tenant].deficit = 0.0
                    continue
                st = self._tenants[tenant]
                st.deficit += self.cfg.quantum_tokens \
                    * self._weight(tenant)
                while q and len(out) < max_requests:
                    cost = cost_fn(q[0])
                    if cost > st.deficit:
                        break
                    if cost > budget - spent and (out or not wave_empty):
                        return out, spent
                    req = q.popleft()
                    self._charge(st, req, cost)
                    spent += cost
                    out.append(req)
                    progress = True
                    if placement_key is not None:
                        got = self._pull_same_prefix(
                            lane, placement_key(req), placement_key,
                            cost_fn, max_requests - len(out),
                            budget - spent)
                        for r2, c2 in got:
                            spent += c2
                            out.append(r2)
            if not progress:
                break
        return out, spent

    def _pull_same_prefix(self, lane, key, placement_key, cost_fn,
                          room: int, budget: int) -> list:
        """Prefix-cache-aware placement: pull requests whose placement
        key (first radix block digest) matches ``key`` into this wave,
        from ANY tenant's queue in the lane — each pull still charges
        its own tenant's deficit, so the pull only reorders a tenant's
        near-term share, never grows it."""
        if key is None or room <= 0:
            return []
        got = []
        for tenant in list(self._rotation):
            q = self._queues.get((tenant, lane))
            if not q:
                continue
            st = self._tenants[tenant]
            keep = []
            while q and len(got) < room:
                r = q.popleft()
                c = cost_fn(r)
                # Deficit DEBT model: the pull charges the tenant even
                # past zero — its own DRR releases then stall until the
                # debt is repaid by later quanta, so riding a shared
                # prefix reorders a tenant's near-term share without
                # ever growing it.
                if placement_key(r) == key and c <= budget:
                    self._charge(st, r, c)
                    budget -= c
                    got.append((r, c))
                else:
                    keep.append(r)
            # mutate in place: the caller's DRR loop holds this deque
            keep.extend(q)
            q.clear()
            q.extend(keep)
            if len(got) >= room:
                break
        return got

    def _charge(self, st: _TenantState, req: Any, cost: int) -> None:
        st.deficit -= cost
        st.admitted_tokens += cost
        st.queued_tokens = max(0, st.queued_tokens - len(req.prompt))

    # -- embedding-engine wave sizing ------------------------------------

    def embed_admit(self, n_texts: int, *, tenant: str = "",
                    batch_size: int = 64,
                    correlation_id: str = "") -> int:
        """Admission + batch sizing for one ``embed_batch`` call.
        Returns the rows-per-dispatch cap: ``embed_wave_rows`` (or the
        engine's batch size), halved under overload so a burst yields
        between tiles. Sheds oversized bursts (``embed_max_burst_texts``)
        and everything at overload level 2 — embed work is batch-lane
        by definition."""
        if self.cfg.embed_max_burst_texts \
                and n_texts > self.cfg.embed_max_burst_texts:
            self._shed(tenant, "batch", "embed-burst", correlation_id,
                       f"embed burst of {n_texts} texts over the "
                       f"{self.cfg.embed_max_burst_texts} cap")
        if self.overload_level >= 2:
            self._shed(tenant, "batch", "queue-full", correlation_id,
                       "embed burst shed at overload level 2")
        # NOT credited to the DRR fairness ledger: admitted_tokens is
        # denominated in prompt tokens and feeds jain_index — mixing
        # text counts in would skew the fairness column's units.
        rows = self.cfg.embed_wave_rows or batch_size
        if self.overload_level >= 1:
            rows = max(1, rows // 2)
        return min(rows, batch_size)

    # -- metrics export --------------------------------------------------

    def _export_gauges(self) -> None:
        if self.telemetry is None:
            return
        self.telemetry.sched_gauges(
            self.tenant_depths(),
            {t: st.deficit for t, st in self._tenants.items()})


def resolve_scheduler(scheduler, *, telemetry: Any = None
                      ) -> Scheduler | None:
    """Engine-side ``scheduler=`` argument semantics (mirrors
    ``telemetry.resolve_telemetry``): None/False disables, True builds
    one with defaults, a :class:`SchedulerConfig` builds from it, a
    :class:`Scheduler` instance is shared as-is (multi-engine closed
    loop: the embedding engine seeing the generation engine's overload
    level is exactly how embed bursts stop starving chat traffic)."""
    if scheduler is None or scheduler is False:
        return None
    if scheduler is True:
        return Scheduler(telemetry=telemetry)
    if isinstance(scheduler, SchedulerConfig):
        return Scheduler(scheduler, telemetry=telemetry)
    if isinstance(scheduler, Scheduler):
        if scheduler.telemetry is None:
            scheduler.telemetry = telemetry
        return scheduler
    raise ValueError(
        f"scheduler must be None/bool, SchedulerConfig or Scheduler, "
        f"got {type(scheduler).__name__}")


# ---------------------------------------------------------------------------
# shardcheck contract (analysis/shardcheck.py)
# ---------------------------------------------------------------------------

from copilot_for_consensus_tpu.analysis.contracts import (  # noqa: E402
    ContractCase,
    checkable,
)


@checkable("scheduler-chunked-prefill")
def _shardcheck_scheduler():
    """The chunked-prefill continuation dispatch must honor the same
    contracts as every other program that touches the slot cache:

    * its donated cache input aliases a shape/dtype-matching output
      (``engine.generation-kv`` group membership means the layout it
      reads/writes is THE layout admit/decode/verify agree on — a
      chunk continuation that drifted would corrupt live timelines);
    * its token-width bucket table covers the configured chunk size
      (``chunk_tokens``), bounding retrace count exactly like the
      verify dispatch's draft-length buckets.

    The tiny config matches the generation contract's so the shared
    kv-layout group compares identical (L, Hkv, Dh, dtype) signatures.
    """
    import jax
    import jax.numpy as jnp
    import functools

    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine,
    )
    from copilot_for_consensus_tpu.models.configs import DecoderConfig

    cfg = DecoderConfig(name="shardcheck-tiny", vocab_size=64,
                        d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
                        d_ff=64, max_seq_len=128)
    eng = GenerationEngine(cfg, num_slots=4, max_len=64,
                           prefill_buckets=(16, 32), decode_window=4,
                           windows_per_dispatch=1,
                           scheduler=SchedulerConfig(chunk_tokens=16))

    def aval(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)

    S = jax.ShapeDtypeStruct
    i32 = jnp.int32
    cache = aval(eng._cache)
    key = jax.random.PRNGKey(0)
    b = eng.num_slots
    width = eng._chunk_buckets[-1]
    return [
        ContractCase(
            label="prefill-chunk",
            fn=functools.partial(eng._chunk_fn, kv_len=eng.max_len),
            args=(eng.params, S((b, width), i32), S((b,), i32),
                  S((b,), i32), cache, key),
            donate_argnums=(4,), kv_group="engine.generation-kv",
            kv_caches=(("slot-cache", cache),),
            buckets=eng._chunk_buckets,
            bucket_covers=(min(eng._sched.cfg.chunk_tokens,
                               eng.prompt_limit),)),
    ]
