"""Bounded device KV block pool — the paged engine's native layout.

One pool ``[L, num_blocks, Hkv, block, Dh]`` backs EVERY KV byte the
paged engine serves from: active slots map logical positions onto pool
blocks through per-slot block tables (``[slots, max_blocks]`` int32,
position ``p`` of a slot lives at ``table[p // block]`` offset
``p % block``), and the radix prefix cache's published nodes reference
the same blocks by id — so a prefix hit is a pointer handoff (append the
matched ids to the slot's table, pin them) and publish-on-retire is a
refcount handoff (the trie adopts the slot's own blocks), neither of
which moves a byte of KV. This is the vLLM PagedAttention block-pool
design (Kwon et al., SOSP 2023) adapted to this engine's host-side
single-owner discipline; the device-side indirection lives in
``ops/paged_attention.py``.

The allocator here is pure host Python (one owner thread — the engine's
dispatcher; see docs/RESILIENCE.md), but its invariants are
load-bearing enough to be machine-checked twice: property tests drive
random alloc/free/pin/release sequences (tests/test_engine_paged.py)
and ``supervisor.audit()`` cross-checks block ownership against the
engine's live tables after a failure.

Invariants (violations raise — a silent double-assign would let two
requests share one KV timeline, the exact corruption the contiguous
engine's slot free-list repair exists to prevent):

* a block id is in exactly one place: the free list, or assigned;
* ``free`` refuses ids that are already free (double-free) and ids
  with a nonzero pin count (a pinned block is visible to a reader —
  freeing it would let the allocator hand it to a writer);
* pins are counted, never boolean: the trie pins each published block
  once for itself, and lookups pin matched nodes per active request.
"""

from __future__ import annotations

import numpy as np

try:                      # import-light for host-only tooling/tests
    import jax
    import jax.numpy as jnp
except Exception:  # pragma: no cover - jax is a hard dep in serving
    jax = None
    jnp = None

#: dtype of every block table the paged dispatches consume — declared
#: once so the host arrays, the shardcheck contract declarations, and
#: the Pallas kernel's scalar-prefetch spec cannot drift apart
#: (analysis: engine.generation-kv-table layout group).
BLOCK_TABLE_DTYPE = np.int32

#: TPU lane width the pool's block axis packs against: block_size must
#: divide it so a block never straddles a lane boundary — the layout
#: commitment the Pallas kernel route compiles its BlockSpecs against
#: (ops.paged_attention.KERNEL_BLOCK_PACK is the kernel-side twin;
#: analysis: engine.generation-kv-pack layout group trips on drift).
POOL_BLOCK_PACK = 128


class KVPoolExhausted(RuntimeError):
    """The pool has no free block for a write the dispatch needs.

    Admission gating (free-block accounting in the engine + scheduler)
    exists to make this unreachable on the serving path; reaching it
    anyway is classified as resource exhaustion by the supervisor
    (``is_resource_exhaustion``), which lowers the admission cap and
    contains the step."""

    def __init__(self, message: str, *, needed: int = 0, free: int = 0):
        super().__init__(message)
        self.needed = needed
        self.free = free
        #: supervisor classification hook (engine/supervisor.py)
        self.resource_exhausted = True


class BlockPool:
    """Device KV blocks + the host allocator that owns them.

    ``k``/``v``: ``[L, num_blocks, Hkv, block, Dh]`` in the serving
    cache dtype. ``num_blocks`` doubles as the OOB sentinel id: gathers
    clamp (masked downstream), scatters drop — the same padding
    discipline as the contiguous engine's OOB slot ids.
    """

    def __init__(self, cfg, *, num_blocks: int, block_size: int,
                 kv_dtype=None, mesh=None):
        if num_blocks < 1:
            raise ValueError("kv pool needs num_blocks >= 1")
        if block_size < 1:
            raise ValueError("kv pool needs block_size >= 1")
        self.cfg = cfg
        self.block = int(block_size)
        self.num_blocks = int(num_blocks)
        self.kv_dtype = kv_dtype if kv_dtype is not None else jnp.bfloat16
        self.mesh = mesh
        #: dp shards the BLOCK axis: shard s owns the contiguous global
        #: id range [s*blocks_per_shard, (s+1)*blocks_per_shard). Host
        #: code speaks GLOBAL ids throughout; the engine localizes them
        #: (id - shard base) only when building dispatch index arrays,
        #: because inside the shard_map body each shard sees only its
        #: own pool slice.
        self.num_shards = int(mesh.shape["dp"]) if mesh is not None else 1
        if num_blocks % self.num_shards:
            raise ValueError(
                f"kv pool num_blocks {num_blocks} must divide evenly "
                f"over dp={self.num_shards} shards")
        self.blocks_per_shard = num_blocks // self.num_shards
        shape = (cfg.n_layers, num_blocks, cfg.n_kv_heads, block_size,
                 cfg.head_dim)
        if mesh is None:
            self.spec = None
            self.k = jnp.zeros(shape, self.kv_dtype)
            self.v = jnp.zeros(shape, self.kv_dtype)
        else:
            # tp splits the kv-head axis per the engine's cache rules;
            # replicate when tp doesn't divide it (standard GQA
            # serving — same fallback as the contiguous cache).
            from jax.sharding import NamedSharding, PartitionSpec

            kv_tp = "tp" if cfg.n_kv_heads % mesh.shape["tp"] == 0 \
                else None
            self.spec = PartitionSpec(None, "dp", kv_tp, None, None)
            sharding = NamedSharding(mesh, self.spec)
            # allocate sharded directly — a transient full-pool array
            # on device 0 would be the largest allocation of the build
            zeros = jax.jit(
                lambda: jnp.zeros(shape, self.kv_dtype),
                out_shardings=sharding)
            self.k = zeros()
            self.v = zeros()
        #: per-shard free lists over disjoint global-id ranges — the
        #: "per-shard host allocators" of the multi-chip design: a
        #: slot's blocks must all live in the slot's dp shard, so
        #: alloc() takes the shard and never crosses ranges.
        self._free_by_shard: list[list[int]] = [
            list(range(s * self.blocks_per_shard,
                       (s + 1) * self.blocks_per_shard))
            for s in range(self.num_shards)]
        self._is_free = np.ones(num_blocks, dtype=bool)
        self._pins = np.zeros(num_blocks, dtype=np.int64)
        #: lifetime accounting (telemetry + benches)
        self.allocs_total = 0
        self.frees_total = 0

    # -- introspection --------------------------------------------------

    def shard_of(self, bid: int) -> int:
        return int(bid) // self.blocks_per_shard

    def local_id(self, bid: int) -> int:
        """Shard-local block id (what the dispatch index arrays carry
        under dp sharding — each shard_map body indexes its own slice)."""
        return int(bid) % self.blocks_per_shard

    @property
    def free_blocks(self) -> int:
        return sum(len(f) for f in self._free_by_shard)

    def free_blocks_shard(self, shard: int) -> int:
        return len(self._free_by_shard[shard])

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def pinned_blocks(self) -> int:
        """Blocks with at least one outstanding pin (shared/published
        blocks a reader may be attending over)."""
        return int(np.count_nonzero(self._pins))

    def pins(self, bid: int) -> int:
        return int(self._pins[bid])

    def is_free(self, bid: int) -> bool:
        return bool(self._is_free[bid])

    # -- allocation -----------------------------------------------------

    def alloc(self, n: int = 1, *, shard: int = 0) -> list[int]:
        """Take ``n`` blocks off ``shard``'s free list. All-or-nothing:
        a partial grant would leave the caller's table covering less of
        the timeline than its positions claim. Allocation never crosses
        shard ranges — a slot's timeline must stay inside its own dp
        shard's pool slice."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if not 0 <= shard < self.num_shards:
            raise ValueError(f"alloc on unknown shard {shard}")
        free = self._free_by_shard[shard]
        if n > len(free):
            raise KVPoolExhausted(
                f"kv pool exhausted: need {n} blocks, {len(free)} "
                f"free of {self.blocks_per_shard} on shard {shard}",
                needed=n, free=len(free))
        out = [free.pop() for _ in range(n)]
        for bid in out:
            self._is_free[bid] = False
        self.allocs_total += n
        return out

    def free(self, bids) -> None:
        """Return blocks to the free list. Double-free and
        free-while-pinned raise: both mean two owners believed they
        held the block, and handing it out again would alias two KV
        timelines."""
        for bid in bids:
            bid = int(bid)
            if not 0 <= bid < self.num_blocks:
                raise ValueError(f"free of out-of-range block {bid}")
            if self._is_free[bid]:
                raise ValueError(f"double free of block {bid}")
            if self._pins[bid]:
                raise ValueError(
                    f"free of pinned block {bid} "
                    f"({int(self._pins[bid])} pins outstanding)")
            self._is_free[bid] = True
            self._free_by_shard[self.shard_of(bid)].append(bid)
            self.frees_total += 1

    def pin(self, bids) -> None:
        """Count a reader/owner reference on assigned blocks. Pinning a
        free block raises — nothing should hold a reference the
        allocator could hand to a writer."""
        for bid in bids:
            bid = int(bid)
            if self._is_free[bid]:
                raise ValueError(f"pin of free block {bid}")
            self._pins[bid] += 1

    def release(self, bids) -> None:
        for bid in bids:
            bid = int(bid)
            if self._pins[bid] <= 0:
                raise ValueError(f"release underflow on block {bid}")
            self._pins[bid] -= 1

    # -- repair (supervisor.audit) --------------------------------------

    def rebuild_free_list(self, owned: set[int]) -> list[int]:
        """Recompute the free list as ``all - owned`` (audit repair
        after a failure left the allocator and the engine's tables
        disagreeing). Pins on blocks nobody owns are cleared — the
        owner that held them is gone. Returns the ids whose free/used
        state changed."""
        changed = []
        for bid in range(self.num_blocks):
            want_free = bid not in owned
            if want_free and not self._is_free[bid]:
                self._pins[bid] = 0
                changed.append(bid)
            elif not want_free and self._is_free[bid]:
                changed.append(bid)
            self._is_free[bid] = want_free
        self._free_by_shard = [
            [b for b in range(s * self.blocks_per_shard,
                              (s + 1) * self.blocks_per_shard)
             if self._is_free[b]]
            for s in range(self.num_shards)]
        return changed

    # -- geometry helpers ------------------------------------------------

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` positions."""
        return -(-max(0, int(n_tokens)) // self.block)

    def fragmentation(self, used_tokens: int) -> float:
        """Internal fragmentation of the allocated blocks: the fraction
        of reserved-but-dead positions (tail slack of partially filled
        blocks). 0.0 when nothing is allocated."""
        cap = self.blocks_in_use * self.block
        if cap <= 0:
            return 0.0
        return max(0.0, 1.0 - used_tokens / cap)
