"""Bounded device KV block pool — the paged engine's native layout.

One pool ``[L, num_blocks, Hkv, block, Dh]`` backs EVERY KV byte the
paged engine serves from: active slots map logical positions onto pool
blocks through per-slot block tables (``[slots, max_blocks]`` int32,
position ``p`` of a slot lives at ``table[p // block]`` offset
``p % block``), and the radix prefix cache's published nodes reference
the same blocks by id — so a prefix hit is a pointer handoff (append the
matched ids to the slot's table, pin them) and publish-on-retire is a
refcount handoff (the trie adopts the slot's own blocks), neither of
which moves a byte of KV. This is the vLLM PagedAttention block-pool
design (Kwon et al., SOSP 2023) adapted to this engine's host-side
single-owner discipline; the device-side indirection lives in
``ops/paged_attention.py``.

The allocator here is pure host Python (one owner thread — the engine's
dispatcher; see docs/RESILIENCE.md), but its invariants are
load-bearing enough to be machine-checked twice: property tests drive
random alloc/free/pin/release sequences (tests/test_engine_paged.py)
and ``supervisor.audit()`` cross-checks block ownership against the
engine's live tables after a failure.

Invariants (violations raise — a silent double-assign would let two
requests share one KV timeline, the exact corruption the contiguous
engine's slot free-list repair exists to prevent):

* a block id is in exactly one place: the free list, or assigned;
* ``free`` refuses ids that are already free (double-free) and ids
  with a nonzero pin count (a pinned block is visible to a reader —
  freeing it would let the allocator hand it to a writer);
* pins are counted, never boolean: the trie pins each published block
  once for itself, and lookups pin matched nodes per active request.
"""

from __future__ import annotations

import numpy as np

try:                      # import-light for host-only tooling/tests
    import jax.numpy as jnp
except Exception:  # pragma: no cover - jax is a hard dep in serving
    jnp = None

#: dtype of every block table the paged dispatches consume — declared
#: once so the host arrays, the shardcheck contract declarations, and
#: the Pallas kernel's scalar-prefetch spec cannot drift apart
#: (analysis: engine.generation-kv-table layout group).
BLOCK_TABLE_DTYPE = np.int32


class KVPoolExhausted(RuntimeError):
    """The pool has no free block for a write the dispatch needs.

    Admission gating (free-block accounting in the engine + scheduler)
    exists to make this unreachable on the serving path; reaching it
    anyway is classified as resource exhaustion by the supervisor
    (``is_resource_exhaustion``), which lowers the admission cap and
    contains the step."""

    def __init__(self, message: str, *, needed: int = 0, free: int = 0):
        super().__init__(message)
        self.needed = needed
        self.free = free
        #: supervisor classification hook (engine/supervisor.py)
        self.resource_exhausted = True


class BlockPool:
    """Device KV blocks + the host allocator that owns them.

    ``k``/``v``: ``[L, num_blocks, Hkv, block, Dh]`` in the serving
    cache dtype. ``num_blocks`` doubles as the OOB sentinel id: gathers
    clamp (masked downstream), scatters drop — the same padding
    discipline as the contiguous engine's OOB slot ids.
    """

    def __init__(self, cfg, *, num_blocks: int, block_size: int,
                 kv_dtype=None):
        if num_blocks < 1:
            raise ValueError("kv pool needs num_blocks >= 1")
        if block_size < 1:
            raise ValueError("kv pool needs block_size >= 1")
        self.cfg = cfg
        self.block = int(block_size)
        self.num_blocks = int(num_blocks)
        self.kv_dtype = kv_dtype if kv_dtype is not None else jnp.bfloat16
        shape = (cfg.n_layers, num_blocks, cfg.n_kv_heads, block_size,
                 cfg.head_dim)
        self.k = jnp.zeros(shape, self.kv_dtype)
        self.v = jnp.zeros(shape, self.kv_dtype)
        self._free: list[int] = list(range(num_blocks))
        self._is_free = np.ones(num_blocks, dtype=bool)
        self._pins = np.zeros(num_blocks, dtype=np.int64)
        #: lifetime accounting (telemetry + benches)
        self.allocs_total = 0
        self.frees_total = 0

    # -- introspection --------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def pinned_blocks(self) -> int:
        """Blocks with at least one outstanding pin (shared/published
        blocks a reader may be attending over)."""
        return int(np.count_nonzero(self._pins))

    def pins(self, bid: int) -> int:
        return int(self._pins[bid])

    def is_free(self, bid: int) -> bool:
        return bool(self._is_free[bid])

    # -- allocation -----------------------------------------------------

    def alloc(self, n: int = 1) -> list[int]:
        """Take ``n`` blocks off the free list. All-or-nothing: a
        partial grant would leave the caller's table covering less of
        the timeline than its positions claim."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise KVPoolExhausted(
                f"kv pool exhausted: need {n} blocks, {len(self._free)} "
                f"free of {self.num_blocks}",
                needed=n, free=len(self._free))
        out = [self._free.pop() for _ in range(n)]
        for bid in out:
            self._is_free[bid] = False
        self.allocs_total += n
        return out

    def free(self, bids) -> None:
        """Return blocks to the free list. Double-free and
        free-while-pinned raise: both mean two owners believed they
        held the block, and handing it out again would alias two KV
        timelines."""
        for bid in bids:
            bid = int(bid)
            if not 0 <= bid < self.num_blocks:
                raise ValueError(f"free of out-of-range block {bid}")
            if self._is_free[bid]:
                raise ValueError(f"double free of block {bid}")
            if self._pins[bid]:
                raise ValueError(
                    f"free of pinned block {bid} "
                    f"({int(self._pins[bid])} pins outstanding)")
            self._is_free[bid] = True
            self._free.append(bid)
            self.frees_total += 1

    def pin(self, bids) -> None:
        """Count a reader/owner reference on assigned blocks. Pinning a
        free block raises — nothing should hold a reference the
        allocator could hand to a writer."""
        for bid in bids:
            bid = int(bid)
            if self._is_free[bid]:
                raise ValueError(f"pin of free block {bid}")
            self._pins[bid] += 1

    def release(self, bids) -> None:
        for bid in bids:
            bid = int(bid)
            if self._pins[bid] <= 0:
                raise ValueError(f"release underflow on block {bid}")
            self._pins[bid] -= 1

    # -- repair (supervisor.audit) --------------------------------------

    def rebuild_free_list(self, owned: set[int]) -> list[int]:
        """Recompute the free list as ``all - owned`` (audit repair
        after a failure left the allocator and the engine's tables
        disagreeing). Pins on blocks nobody owns are cleared — the
        owner that held them is gone. Returns the ids whose free/used
        state changed."""
        changed = []
        for bid in range(self.num_blocks):
            want_free = bid not in owned
            if want_free and not self._is_free[bid]:
                self._pins[bid] = 0
                changed.append(bid)
            elif not want_free and self._is_free[bid]:
                changed.append(bid)
            self._is_free[bid] = want_free
        self._free = [b for b in range(self.num_blocks)
                      if self._is_free[b]]
        return changed

    # -- geometry helpers ------------------------------------------------

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` positions."""
        return -(-max(0, int(n_tokens)) // self.block)

    def fragmentation(self, used_tokens: int) -> float:
        """Internal fragmentation of the allocated blocks: the fraction
        of reserved-but-dead positions (tail slack of partially filled
        blocks). 0.0 when nothing is allocated."""
        cap = self.blocks_in_use * self.block
        if cap <= 0:
            return 0.0
        return max(0.0, 1.0 - used_tokens / cap)
