"""Rule group ``blocking-call``: bus/service handler-thread hygiene.

The bus consumer threads (``BrokerSubscriber.start_consuming``, the
service runner threads) and the engine's single-owner consumer thread
are the system's availability surface: a bare ``time.sleep`` there is
(a) uninterruptible — shutdown waits out the sleep — and (b) dead time
the thread could spend draining its queue. The audited pattern is an
``Event.wait(timeout)`` (stop-aware) or the exponential-backoff retry
helpers the checker allowlists below.

Two checks:

* ``time.sleep(...)`` anywhere in the package outside the audited retry
  helpers. CLI parking loops and deliberate backoffs carry an inline
  ``# jaxlint: disable=blocking-call`` with the justification.
* a ``publish``-family call made while holding a lock (``with <lock>:``
  around ``*.publish*(...)``): publish is a network round trip with
  broker confirms — holding a lock across it serializes every producer
  behind one slow confirm.
"""

from __future__ import annotations

import ast
import re

from copilot_for_consensus_tpu.analysis.base import (
    Finding,
    LockModel,
    Module,
    dotted_name,
)

#: (path suffix, function name) pairs of the audited retry helpers —
#: exponential-backoff loops whose sleeps are the documented contract
#: (transient-error retry with backoff; see docs/STATIC_ANALYSIS.md).
AUDITED_RETRY_HELPERS = (
    ("bus/azure_servicebus.py", "request"),
    ("security/keyvault_signer.py", "_request"),
    ("core/retry.py", "run"),
)

_LOCKISH = ("lock", "mutex")


def _enclosing_function_name(mod: Module, node: ast.AST) -> str:
    cur = mod.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur.name
        cur = mod.parent(cur)
    return ""


def _is_audited(mod: Module, node: ast.AST) -> bool:
    fname = _enclosing_function_name(mod, node)
    return any(mod.relpath.endswith(suffix) and fname == func
               for suffix, func in AUDITED_RETRY_HELPERS)


def lockish_with(item: ast.withitem, locks: LockModel) -> bool:
    """Is this with-item a lock acquisition? Provenance first (the
    shared ``LockModel``: anything bound from ``threading.Lock`` /
    ``RLock`` / ``Condition`` / ``Semaphore``, through aliases — so
    Condition-typed members like ``async_runner._work`` count); the
    old name-token heuristic survives only as a fallback for names
    whose construction the model cannot see (parameters, fields set by
    another module)."""
    expr = item.context_expr
    if isinstance(expr, ast.Call):
        expr = expr.func
    info = locks.resolve(expr, item.context_expr)
    if info is not None:
        return info.role == "lock"
    name = dotted_name(expr).lower()
    # token match, not substring: `blockchain`/`clock` are not locks
    tokens = set(re.split(r"[^a-z0-9]+", name))
    return bool(tokens & set(_LOCKISH))


def check(mod: Module) -> list[Finding]:
    if mod.tree is None:
        return []
    locks = LockModel(mod)
    out: list[Finding] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and dotted_name(
                node.func) == "time.sleep":
            if _is_audited(mod, node):
                continue
            f = mod.finding(
                "blocking-call", node,
                "`time.sleep` blocks the thread uninterruptibly — use a "
                "stop Event's `.wait(timeout)` (shutdown-aware) or route "
                "backoff through the audited retry helpers")
            if f is not None:
                out.append(f)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            if not any(lockish_with(i, locks) for i in node.items):
                continue
            # stop at nested function boundaries: a callback DEFINED
            # under the lock does not publish under the lock
            stack: list[ast.AST] = list(node.body)
            while stack:
                sub = stack.pop()
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                stack.extend(ast.iter_child_nodes(sub))
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr.startswith("publish")):
                    f = mod.finding(
                        "blocking-call", sub,
                        f"`.{sub.func.attr}()` (a broker round trip with "
                        "confirms) is called while holding a lock — "
                        "every producer serializes behind one slow "
                        "confirm; publish outside the critical section")
                    if f is not None:
                        out.append(f)
    return out
