"""Rule group ``dura``: the crash-safety / exactly-once contracts.

docs/RESILIENCE.md states the durability contracts as prose; every
rule here machine-checks one of them, and every rule is grounded in a
bug class this repo actually shipped and later fixed:

- **dura-commit-publish-window** — a handler commits a store write and
  then publishes only the *freshly inserted* rows, so a crash between
  commit and publish strands the committed rows forever (redelivery
  filters them out as duplicates and nothing republishes their
  events). Parsing shipped exactly this; the fix publishes
  already-stored-but-unfinished rows too (``stored_unchunked``).
- **dura-raw-publish** — ``publish_envelope`` / raw broker ``pub`` ops
  outside the bus package bypass the typed-event discipline (schema
  validation, identity stamping, the outbox/publish_window path).
- **dura-ack-swallow** — handler code that catches ``RetryableError``
  or broad ``Exception`` and falls through normally converts a
  transient failure into a silent ack: the envelope is gone and the
  work never happened. Handlers must re-raise, return the exception
  for classification, or publish a ``*Failed`` event.
- **dura-journal-order** — engine submit paths must
  ``record_submit`` *before* any queue/scheduler insertion (a crash in
  the window otherwise loses admitted work), and ``record_retire``
  only *after* the harvested result is used (retire-at-harvest).
- **dura-idempotent-write** — inserts reachable from an at-least-once
  dispatch context must tolerate redelivery: ``ignore_duplicates=True``
  or an existence-read dedup guard in the same handler.
- **dura-sqlite-ledger** — first-party sqlite ledgers (journal,
  outbox, broker queue store, DLQ) must open WAL, scope multi-row
  write loops in one transaction, and have an owner-joined ``close``.

All receiver reasoning goes through :class:`base.EffectModel`
provenance (what a name was *bound from*), not name tokens — plus one
narrow convention fallback: inside a handler class, ``self.store`` /
``self.publisher`` are trusted as store/publisher even when the
binding ``__init__`` lives in a base class another module owns.
"""

from __future__ import annotations

import ast
import re

from copilot_for_consensus_tpu.analysis.base import (
    EffectModel, Finding, Module, dotted_name, kw,
)

RULES = (
    "dura-commit-publish-window",
    "dura-raw-publish",
    "dura-ack-swallow",
    "dura-journal-order",
    "dura-idempotent-write",
    "dura-sqlite-ledger",
)

#: DocumentStore surface, split by effect
STORE_READS = {
    "get_document", "get_documents", "query_documents", "count_documents",
}
STORE_INSERTS = {"insert_document", "insert_many"}
STORE_WRITES = STORE_INSERTS | {
    "upsert_document", "update_document", "update_documents",
    "replace_document", "delete_document", "delete_documents",
}
PUBLISH_METHODS = {"publish", "publish_envelope"}

#: self-attribute methods that insert work into a queue/scheduler
#: ("add"/"push" are deliberately excluded — too many unrelated uses)
QUEUE_INSERTS = {"enqueue", "append", "appendleft", "put", "put_nowait"}

#: exception names whose catch is "broad" for ack purposes: catching
#: one of these around handler work can eat a transient failure
BROAD_CATCHES = {"Exception", "BaseException",
                 "RetryableError", "RetryExhaustedError"}

#: method names that make a class a dispatch-context handler
HANDLER_NAMES = {"handle_envelope", "handle_envelopes"}

#: bus event handlers are named after the CamelCase event type
#: (``on_JSONParsed`` / ``on_wave_ChunksPrepared``); lowercase ``on_*``
#: are engine/telemetry callbacks, which are NOT dispatch contexts
_EVENT_HANDLER_RE = re.compile(r"on_(wave_)?[A-Z]")


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _methods(cls: ast.ClassDef) -> list[ast.FunctionDef]:
    return [n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _handler_classes(mod: Module) -> list[ast.ClassDef]:
    """Classes whose methods run under at-least-once dispatch: they
    define an ``on_*`` wave/event handler or the dispatch entrypoints
    themselves (``handle_envelope``/``handle_envelopes``)."""
    assert mod.tree is not None
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        names = {m.name for m in _methods(node)}
        if names & HANDLER_NAMES or any(_EVENT_HANDLER_RE.match(n)
                                        for n in names):
            out.append(node)
    return out


def _receiver_tag(effects: EffectModel, call: ast.Call,
                  handler_scope: bool = False) -> str | None:
    """Effect tag of ``call``'s receiver (None for plain functions or
    untagged receivers)."""
    if not isinstance(call.func, ast.Attribute):
        return None
    recv = call.func.value
    info = effects.resolve(recv, call)
    if info is not None:
        return info.tag
    if handler_scope:
        d = dotted_name(recv)
        if d == "self.store":
            return "store"
        if d == "self.publisher":
            return "publisher"
    return None


def _own_nodes(fn: ast.AST) -> list[ast.AST]:
    """ast.walk(fn) minus the bodies of nested function defs (a
    nested finisher is its own ordering domain)."""
    out: list[ast.AST] = [fn]
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _base_name(expr: ast.AST) -> str | None:
    """Root Name of a Name / attribute chain (``req.request_id`` →
    ``req``); None for anything else or ``self``."""
    cur = expr
    while isinstance(cur, ast.Attribute):
        cur = cur.value
    if isinstance(cur, ast.Name) and cur.id != "self":
        return cur.id
    return None


# ---------------------------------------------------------------------------
# dura-commit-publish-window
# ---------------------------------------------------------------------------

def _check_commit_publish_window(mod: Module, effects: EffectModel,
                                 cls: ast.ClassDef) -> list[Finding]:
    """The PR-11 crash-window shape, per handler method:

    1. an existence read (``existing = store.get_documents(...)``),
    2. a *fresh* filter — rows NOT in the existence read
       (``d["id"] not in existing``),
    3. a store insert commits in the same method, and
    4. the fresh-only collection flows to a publish (direct args, a
       publish-bearing ``for`` loop, or the method's return value —
       helper methods return to a caller that publishes),

    with NO companion *positive* use of the same existence read (the
    redelivery-republish half: already-stored-but-unfinished rows,
    e.g. parsing's ``stored_unchunked``). If redelivery republishes
    nothing for committed rows, a crash between commit and publish
    loses their downstream events forever.
    """
    out: list[Finding] = []
    for fn in _methods(cls):
        nodes = list(ast.walk(fn))
        # the commit half must exist at all
        has_insert = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr in STORE_INSERTS
            and _receiver_tag(effects, n, True) == "store"
            for n in nodes)
        if not has_insert:
            continue
        # pass 1: existence reads + taint propagation, in source order
        exist: set[str] = set()
        taints: dict[str, set[tuple[str, str]]] = {}
        first_site: dict[str, ast.AST] = {}

        def marks_of(rhs: ast.AST) -> set[tuple[str, str]]:
            marks: set[tuple[str, str]] = set()
            for n in ast.walk(rhs):
                if not (isinstance(n, ast.Name)
                        and isinstance(n.ctx, ast.Load)):
                    continue
                marks |= taints.get(n.id, set())
                if n.id in exist:
                    par = mod.parent(n)
                    if isinstance(par, ast.Compare) \
                            and n in par.comparators \
                            and all(isinstance(op, ast.NotIn)
                                    for op in par.ops):
                        marks.add(("fresh", n.id))
                    else:
                        marks.add(("pos", n.id))
            return marks

        def bind(name: str, marks: set[tuple[str, str]],
                 site: ast.AST) -> None:
            if marks:
                taints.setdefault(name, set()).update(marks)
                first_site.setdefault(name, site)

        assigns = [n for n in nodes
                   if isinstance(n, (ast.Assign, ast.AugAssign))
                   or (isinstance(n, ast.Call)
                       and isinstance(n.func, ast.Attribute)
                       and n.func.attr in ("extend", "append",
                                           "setdefault", "update"))]
        for n in sorted(assigns, key=lambda x: x.lineno):
            if isinstance(n, ast.Assign):
                if isinstance(n.value, ast.Call) and isinstance(
                        n.value.func, ast.Attribute) \
                        and n.value.func.attr in STORE_READS \
                        and _receiver_tag(effects, n.value, True) == "store":
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            exist.add(t.id)
                    continue
                marks = marks_of(n.value)
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        bind(t.id, marks, n)
                    elif isinstance(t, ast.Subscript):
                        base = _base_name(t.value)
                        if base:
                            bind(base, marks, n)
            elif isinstance(n, ast.AugAssign):
                if isinstance(n.target, ast.Name):
                    bind(n.target.id, marks_of(n.value), n)
            else:  # mutating container call: to_insert.extend(fresh)
                base = _base_name(n.func.value)
                if base:
                    marks = set()
                    for a in n.args:
                        marks |= marks_of(a)
                    bind(base, marks, n)
        if not taints:
            continue
        # pass 2: which names flow to a publish?
        published: set[str] = set()
        for n in nodes:
            if isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) \
                    and n.func.attr in PUBLISH_METHODS \
                    and _receiver_tag(effects, n, True) == "publisher":
                for a in list(n.args) + [k.value for k in n.keywords]:
                    for sub in ast.walk(a):
                        if isinstance(sub, ast.Name):
                            published.add(sub.id)
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                body_pub = any(
                    isinstance(s, ast.Call)
                    and isinstance(s.func, ast.Attribute)
                    and s.func.attr in PUBLISH_METHODS
                    and _receiver_tag(effects, s, True) == "publisher"
                    for b in n.body for s in ast.walk(b))
                if body_pub:
                    for sub in ast.walk(n.iter):
                        if isinstance(sub, ast.Name):
                            published.add(sub.id)
            elif isinstance(n, ast.Return) and n.value is not None:
                for sub in ast.walk(n.value):
                    if isinstance(sub, ast.Name):
                        published.add(sub.id)
        # a positive companion anywhere on the publish flow covers the
        # window: `for r in fresh + stored_unfinished:` is as good as
        # merging into one name first
        pos_covered: set[str] = set()
        for name in published:
            pos_covered |= {e for k, e in taints.get(name, set())
                            if k == "pos"}
        for name, marks in taints.items():
            if name not in published:
                continue
            fresh_es = {e for k, e in marks if k == "fresh"}
            for e in sorted(fresh_es - pos_covered):
                f = mod.finding(
                    "dura-commit-publish-window", first_site[name],
                    f"`{name}` publishes only rows absent from the "
                    f"existence read `{e}` while this handler also "
                    "commits a store insert — a crash between commit "
                    "and publish strands the committed rows (redelivery "
                    "filters them as duplicates and nothing republishes "
                    "their events); also publish the "
                    "already-stored-but-unfinished rows, the way "
                    "parsing republishes `stored_unchunked`")
                if f is not None:
                    out.append(f)
    return out


# ---------------------------------------------------------------------------
# dura-raw-publish
# ---------------------------------------------------------------------------

def _check_raw_publish(mod: Module, effects: EffectModel) -> list[Finding]:
    """``publish_envelope`` and raw broker ``pub`` ops belong to the
    bus package; everywhere else must publish typed events through
    ``.publish`` so the outbox/publish_window discipline applies."""
    if mod.relpath.startswith("copilot_for_consensus_tpu/bus/"):
        return []
    out: list[Finding] = []
    assert mod.tree is not None
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr == "publish_envelope":
            info = effects.resolve(node.func.value, node)
            d = dotted_name(node.func.value) or ""
            if (info is not None and info.tag == "publisher") \
                    or d.endswith("publisher"):
                f = mod.finding(
                    "dura-raw-publish", node,
                    "raw `publish_envelope` outside the bus package "
                    "bypasses the typed-event discipline (schema "
                    "validation, identity stamping, the "
                    "outbox/publish_window path) — publish a typed "
                    "Event via `.publish()`")
                if f is not None:
                    out.append(f)
        elif node.func.attr == "request" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Dict):
                for k, v in zip(arg.keys, arg.values):
                    if isinstance(k, ast.Constant) and k.value == "op" \
                            and isinstance(v, ast.Constant) \
                            and v.value in ("pub", "pub_batch"):
                        f = mod.finding(
                            "dura-raw-publish", node,
                            f"raw broker `{v.value}` op outside the bus "
                            "package bypasses the outbox — route "
                            "through an EventPublisher")
                        if f is not None:
                            out.append(f)
    return out


# ---------------------------------------------------------------------------
# dura-ack-swallow
# ---------------------------------------------------------------------------

def _caught_names(type_expr: ast.AST | None) -> set[str]:
    if type_expr is None:
        return {"<bare>"}
    names: set[str] = set()
    exprs = type_expr.elts if isinstance(type_expr, ast.Tuple) \
        else [type_expr]
    for e in exprs:
        d = dotted_name(e)
        if d:
            names.add(d.rsplit(".", 1)[-1])
    return names


def _classifies(handler: ast.ExceptHandler) -> bool:
    """Does this except body re-raise, hand the exception back for
    classification, or publish a ``*Failed`` event?"""
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, ast.Return) and n.value is not None \
                and handler.name is not None:
            if any(isinstance(s, ast.Name) and s.id == handler.name
                   for s in ast.walk(n.value)):
                return True
        if isinstance(n, ast.Call):
            d = dotted_name(n.func) or ""
            tail = d.rsplit(".", 1)[-1]
            if tail == "_publish_failure":
                return True
            if tail in PUBLISH_METHODS:
                for a in ast.walk(n):
                    if isinstance(a, ast.Call):
                        ad = dotted_name(a.func) or ""
                        if ad.rsplit(".", 1)[-1].endswith("Failed"):
                            return True
    return False


def _check_ack_swallow(mod: Module, cls: ast.ClassDef) -> list[Finding]:
    out: list[Finding] = []
    for node in ast.walk(cls):
        if not isinstance(node, ast.Try):
            continue
        for h in node.handlers:
            caught = _caught_names(h.type)
            if not caught & (BROAD_CATCHES | {"<bare>"}):
                continue
            if _classifies(h):
                continue
            shown = "bare except" if "<bare>" in caught else \
                "/".join(sorted(caught & BROAD_CATCHES))
            f = mod.finding(
                "dura-ack-swallow", h,
                f"handler code catches {shown} and falls through "
                "normally — under at-least-once dispatch this silently "
                "acks the envelope and the work never happened; "
                "re-raise, `return exc` for classification, or publish "
                "a *Failed event")
            if f is not None:
                out.append(f)
    return out


# ---------------------------------------------------------------------------
# dura-journal-order
# ---------------------------------------------------------------------------

def _check_journal_order(mod: Module, effects: EffectModel) -> list[Finding]:
    """Journal effects are recognized by provenance OR by the
    distinctive method names (``record_submit``/``record_retire`` —
    engine call sites often reach the journal via
    ``getattr(self.engine, "journal", None)``, which has no static
    provenance). ``record_abandon`` is exempt from the retire half:
    abandoning journals requests that were *never* harvested."""
    out: list[Finding] = []
    assert mod.tree is not None
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        nodes = _own_nodes(fn)
        calls = [n for n in nodes
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)]
        submits = [c for c in calls if c.func.attr == "record_submit"]
        if submits:
            first = min(c.lineno for c in submits)
            for c in calls:
                if c.func.attr in QUEUE_INSERTS and c.args \
                        and c.lineno < first \
                        and isinstance(c.func.value, ast.Attribute) \
                        and isinstance(c.func.value.value, ast.Name) \
                        and c.func.value.value.id == "self":
                    f = mod.finding(
                        "dura-journal-order", c,
                        f"`{dotted_name(c.func)}` inserts into a "
                        "queue/scheduler before `record_submit` "
                        f"(line {first}) — journal-before-admit: a "
                        "crash in that window loses admitted work "
                        "because restart replays only journaled "
                        "submits")
                    if f is not None:
                        out.append(f)
        for c in calls:
            if c.func.attr != "record_retire" or not c.args:
                continue
            base = _base_name(c.args[0])
            if base is None:
                continue
            used_before = any(
                isinstance(n, ast.Name) and n.id == base
                and isinstance(n.ctx, ast.Load)
                and getattr(n, "lineno", 0) < c.lineno
                for n in nodes)
            if not used_before:
                f = mod.finding(
                    "dura-journal-order", c,
                    f"`record_retire({base}...)` before the harvested "
                    "result is used — retire-at-harvest: deleting the "
                    "journal row before the completion is emitted "
                    "turns a crash into silent loss (use "
                    "`record_abandon` for never-harvested requests)")
                if f is not None:
                    out.append(f)
    return out


# ---------------------------------------------------------------------------
# dura-idempotent-write
# ---------------------------------------------------------------------------

def _check_idempotent_write(mod: Module, effects: EffectModel,
                            cls: ast.ClassDef) -> list[Finding]:
    out: list[Finding] = []
    for fn in _methods(cls):
        nodes = list(ast.walk(fn))
        reads = [n for n in nodes
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)
                 and n.func.attr in STORE_READS
                 and _receiver_tag(effects, n, True) == "store"]
        for n in nodes:
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in STORE_INSERTS
                    and _receiver_tag(effects, n, True) == "store"):
                continue
            dup = kw(n, "ignore_duplicates")
            if isinstance(dup, ast.Constant) and dup.value is True:
                continue
            if any(r.lineno < n.lineno for r in reads):
                continue  # existence-read dedup guard in this handler
            f = mod.finding(
                "dura-idempotent-write", n,
                f"`{n.func.attr}` reachable from an at-least-once "
                "dispatch context without dup tolerance — redelivery "
                "re-runs this handler and the second insert raises or "
                "duplicates; pass `ignore_duplicates=True` or guard "
                "with an existence read")
            if f is not None:
                out.append(f)
    return out


# ---------------------------------------------------------------------------
# dura-sqlite-ledger
# ---------------------------------------------------------------------------

_MUTATING_SQL = ("INSERT", "UPDATE", "DELETE", "REPLACE")


def _sql_is_mutating(call: ast.Call) -> bool:
    if not call.args:
        return False
    a = call.args[0]
    return isinstance(a, ast.Constant) and isinstance(a.value, str) \
        and a.value.lstrip().upper().startswith(_MUTATING_SQL)


def _check_sqlite_ledger(mod: Module, effects: EffectModel) -> list[Finding]:
    out: list[Finding] = []
    assert mod.tree is not None
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        # direct `self.X = sqlite3.connect(...)` bindings only —
        # attribute-of-attribute targets (per-thread `self._local.conn`)
        # follow a different discipline and stay out of scope
        conns: dict[str, ast.AST] = {}
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and dotted_name(node.value.func) == "sqlite3.connect":
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        conns.setdefault(t.attr, node)
        for fld, site in conns.items():
            info = effects.class_fields.get(cls.name, {}).get(fld)

            def is_conn(expr: ast.AST, use: ast.AST) -> bool:
                if dotted_name(expr) == f"self.{fld}":
                    return True
                got = effects.resolve(expr, use)
                return got is not None and got is info

            # (a) WAL
            wal = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "execute" and n.args
                and isinstance(n.args[0], ast.Constant)
                and isinstance(n.args[0].value, str)
                and "journal_mode" in n.args[0].value
                and is_conn(n.func.value, n)
                for n in ast.walk(cls))
            if not wal:
                f = mod.finding(
                    "dura-sqlite-ledger", site,
                    f"sqlite ledger `{cls.name}.{fld}` never sets "
                    "`PRAGMA journal_mode=WAL` — every first-party "
                    "ledger opens WAL so readers don't block the "
                    "writer and a crash can't corrupt the rollback "
                    "journal (docs/RESILIENCE.md)")
                if f is not None:
                    out.append(f)
            # (b) multi-row write loops inside one transaction
            for m in _methods(cls):
                out.extend(_txn_scan(mod, m, m.body, False, is_conn))
            # (c) owner-joined close
            closed = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "close"
                and is_conn(n.func.value, n)
                for n in ast.walk(cls))
            if not closed:
                f = mod.finding(
                    "dura-sqlite-ledger", site,
                    f"sqlite ledger `{cls.name}.{fld}` has no "
                    "owner-joined close — add a `close()` the owning "
                    "lifecycle calls on shutdown, or the WAL/SHM "
                    "sidecar files outlive the process and the last "
                    "checkpoint is skipped")
                if f is not None:
                    out.append(f)
    return out


def _txn_scan(mod: Module, fn: ast.AST, stmts: list[ast.stmt],
              in_txn: bool, is_conn) -> list[Finding]:
    out: list[Finding] = []
    for s in stmts:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(s, (ast.With, ast.AsyncWith)):
            entered = in_txn or any(
                is_conn(item.context_expr, s) for item in s.items)
            out.extend(_txn_scan(mod, fn, s.body, entered, is_conn))
        elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
            if not in_txn:
                for n in s.body:
                    for sub in ast.walk(n):
                        if isinstance(sub, ast.Call) \
                                and isinstance(sub.func, ast.Attribute) \
                                and sub.func.attr in ("execute",
                                                      "executemany") \
                                and is_conn(sub.func.value, sub) \
                                and _sql_is_mutating(sub):
                            f = mod.finding(
                                "dura-sqlite-ledger", sub,
                                "multi-row ledger write loop outside a "
                                "transaction — wrap the loop in "
                                "`with <conn>:` so a crash mid-loop "
                                "cannot commit a partial batch")
                            if f is not None:
                                out.append(f)
                            break
            # loop bodies can still open their own transactions
            out.extend(_txn_scan(mod, fn, list(s.body) + list(s.orelse),
                                 in_txn, is_conn))
        elif isinstance(s, ast.Try):
            for blk in (s.body, s.orelse, s.finalbody,
                        *[h.body for h in s.handlers]):
                out.extend(_txn_scan(mod, fn, blk, in_txn, is_conn))
        elif isinstance(s, ast.If):
            out.extend(_txn_scan(mod, fn, list(s.body) + list(s.orelse),
                                 in_txn, is_conn))
    return out


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def check(mod: Module) -> list[Finding]:
    if mod.tree is None:
        return []
    effects = EffectModel(mod)
    out: list[Finding] = []
    out.extend(_check_raw_publish(mod, effects))
    out.extend(_check_journal_order(mod, effects))
    out.extend(_check_sqlite_ledger(mod, effects))
    for cls in _handler_classes(mod):
        out.extend(_check_commit_publish_window(mod, effects, cls))
        out.extend(_check_ack_swallow(mod, cls))
        out.extend(_check_idempotent_write(mod, effects, cls))
    return out
