"""shardcheck — the semantic rule family of the jaxlint lane.

Where ``jax_rules.py`` parses, this module *traces*: it imports the
registered contract modules (``contracts.CONTRACT_MODULES``), runs each
``SHARDCHECK_CONTRACTS`` factory, and abstract-interprets the declared
jitted entrypoints with ``jax.eval_shape`` under the declared meshes —
all on CPU, with a virtual 8-device platform, before any TPU time is
spent. The bug class this catches is invisible to the syntactic pass:

* ``shard-rule-axis`` — a logical-axis rule (``parallel/sharding.py``
  style) whose target names a mesh axis the mesh doesn't have. The
  weight silently replicates: a memory blow-up, not an error.
* ``shard-divisibility`` — a spec'd dimension that doesn't divide
  evenly by its mesh axes (silent padding/replication per shard).
* ``shard-collective`` — a collective inside a traced program naming an
  axis that doesn't exist in the mesh it runs under (ring / ulysses /
  pipeline shard_map bodies). Surfaces as the trace failure it is.
* ``shard-donation`` — a ``donate_argnums`` entry with no shape/dtype-
  matching output: XLA drops the alias with only a warning and the
  buffer double-allocates (2x cache HBM on the decode path).
* ``shard-kv-layout`` — the engine programs that hand the KV cache to
  each other (admit / seeded admit / decode / piggyback / prefix-pool
  publish) disagreeing on the one cache layout
  ``(n_layers, n_kv_heads, head_dim, dtype)``.
* ``shard-bucket`` — a declared input length the padding-bucket table
  doesn't cover: an unbounded retrace (or silent truncation) hazard.
* ``shard-contract`` — the contract itself is broken (module doesn't
  import, factory raises, non-mesh trace failure): the registry must
  not rot silently.

Run it alone (``python -m copilot_for_consensus_tpu.analysis.shardcheck``)
or let the main CLI fold it in (``python -m
copilot_for_consensus_tpu.analysis`` runs both passes; the semantic one
is skipped under ``--fast`` and for explicit-path runs). In-process,
:func:`check_modules` is the API the tests drive fixtures and mutated
modules through. Findings flow through the same inline-suppression and
justified-baseline machinery as every other jaxlint rule.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import re
import subprocess
import sys
from collections import Counter

from copilot_for_consensus_tpu.analysis.base import (
    DEFAULT_BASELINE,
    Finding,
    ROOT,
    Suppressions,
    rel,
)
from copilot_for_consensus_tpu.analysis.contracts import (
    CONTRACT_MODULES,
    Contract,
    ContractCase,
    ContractSkip,
)

RULES = (
    "shard-rule-axis",
    "shard-divisibility",
    "shard-collective",
    "shard-donation",
    "shard-kv-layout",
    "shard-bucket",
    "shard-contract",
)

#: virtual CPU device count the semantic pass runs under — enough for a
#: dp2×tp4 / sp4×tp2 / pp2×tp2 mesh, matching tests/conftest.py.
DEVICE_COUNT = 8


# ---------------------------------------------------------------------------
# contract collection
# ---------------------------------------------------------------------------


def load_contract_module(spec: str):
    """Import a contract module by dotted name, or by ``.py`` path (the
    fixture / mutated-module route: the file is executed under a
    synthetic module name so its absolute package imports still work)."""
    if spec.endswith(".py") or "/" in spec or "\\" in spec:
        import importlib.util

        path = pathlib.Path(spec).resolve()
        name = f"_shardcheck_mod_{path.stem}"
        mspec = importlib.util.spec_from_file_location(name, path)
        if mspec is None or mspec.loader is None:
            raise ImportError(f"cannot load {spec}")
        mod = importlib.util.module_from_spec(mspec)
        sys.modules[name] = mod       # before exec: @checkable needs it
        mspec.loader.exec_module(mod)
        return mod
    import importlib

    return importlib.import_module(spec)


def _spec_path(spec: str) -> str:
    """Repo-relative file path for a module spec, so findings for a
    module that fails to IMPORT still anchor to its source file (the
    baseline/stale/--format=github machinery all assume file paths).
    Falls back to the spec string when nothing resolves."""
    try:
        if spec.endswith(".py") or "/" in spec or "\\" in spec:
            return rel(pathlib.Path(spec))
        import importlib.util

        mspec = importlib.util.find_spec(spec)
        if mspec is not None and mspec.origin:
            return rel(pathlib.Path(mspec.origin))
    except Exception:
        pass
    return spec


def collect(modules=None):
    """Import the contract modules and read their tables.

    Returns ``(entries, findings)`` where entries are
    ``(Contract, module_path)`` pairs and findings cover modules that
    fail to import or declare no contracts (both mean the registry —
    the thing CI trusts to cover the engine — has silently rotted)."""
    specs = CONTRACT_MODULES if modules is None else modules
    entries: list[tuple[Contract, pathlib.Path]] = []
    findings: list[Finding] = []
    for spec in specs:
        try:
            mod = load_contract_module(str(spec))
        except Exception as exc:
            findings.append(Finding(
                "shard-contract", _spec_path(str(spec)), 1,
                f"contract module failed to import: "
                f"{type(exc).__name__}: {_oneline(exc)}"))
            continue
        path = pathlib.Path(mod.__file__)
        table = getattr(mod, "SHARDCHECK_CONTRACTS", None)
        if not table:
            findings.append(Finding(
                "shard-contract", rel(path), 1,
                "module declares no SHARDCHECK_CONTRACTS — the semantic "
                "pass no longer covers it"))
            continue
        entries.extend((c, path) for c in table)
    return entries, findings


# ---------------------------------------------------------------------------
# per-case checks
# ---------------------------------------------------------------------------


def _oneline(exc, limit: int = 300) -> str:
    msg = " ".join(str(exc).split())
    return msg[:limit] + ("..." if len(msg) > limit else "")


def _leaf_sig(leaf) -> tuple:
    return (tuple(leaf.shape), str(leaf.dtype))


def _check_rules_table(case: ContractCase) -> list[tuple[str, str]]:
    """Every rule target must name a real mesh axis."""
    if case.rules is None or case.mesh is None:
        return []
    axes = set(case.mesh.axis_names)
    shape = dict(case.mesh.shape)
    out = []
    for logical, target in sorted(case.rules.items()):
        targets = target if isinstance(target, tuple) else (target,)
        for t in targets:
            if t is not None and t not in axes:
                out.append((
                    "shard-rule-axis",
                    f"rule '{logical}' -> mesh axis '{t}', which mesh "
                    f"{shape} does not have — the array would silently "
                    f"replicate"))
    return out


def _check_logical(case: ContractCase) -> list[tuple[str, str]]:
    """Every spec'd dimension must divide evenly by its mesh axes."""
    if not case.logical or case.mesh is None:
        return []
    import jax

    from copilot_for_consensus_tpu.parallel import sharding as _sharding

    mesh_shape = dict(case.mesh.shape)
    axis_names = set(case.mesh.axis_names)
    out = []
    for label, avals, axes_tree in case.logical:
        try:
            specs = _sharding.spec_tree(axes_tree, case.rules)
        except KeyError as exc:
            out.append(("shard-rule-axis",
                        f"{label}: {_oneline(exc)}"))
            continue
        flat_avals = jax.tree_util.tree_flatten_with_path(avals)[0]
        flat_specs = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda s: isinstance(
                s, jax.sharding.PartitionSpec))
        if len(flat_avals) != len(flat_specs):
            out.append(("shard-contract",
                        f"{label}: aval tree and logical-axes tree "
                        f"disagree ({len(flat_avals)} vs "
                        f"{len(flat_specs)} leaves)"))
            continue
        for (path, aval), spec in zip(flat_avals, flat_specs):
            leaf = jax.tree_util.keystr(path)
            for dim, entry in enumerate(spec):
                if dim >= len(aval.shape):
                    # a spec longer than the leaf's rank means the
                    # logical-axes tuple drifted from the array shape
                    out.append((
                        "shard-contract",
                        f"{label}{leaf}: spec has {len(spec)} entries "
                        f"but the leaf is rank {len(aval.shape)} — "
                        f"logical axes drifted from the array shape"))
                    break
                if entry is None:
                    continue
                names = entry if isinstance(entry, tuple) else (entry,)
                unknown = [n for n in names if n not in axis_names]
                if unknown:
                    out.append((
                        "shard-rule-axis",
                        f"{label}{leaf}: dim {dim} spec'd over "
                        f"{unknown}, not axes of mesh {mesh_shape}"))
                    continue
                size = 1
                for n in names:
                    size *= mesh_shape[n]
                if size > 1 and aval.shape[dim] % size:
                    out.append((
                        "shard-divisibility",
                        f"{label}{leaf}: dim {dim} ({aval.shape[dim]}) "
                        f"not divisible by mesh axes "
                        f"{'x'.join(names)} (size {size}) — silent "
                        f"padding/replication per shard"))
    return out


def _check_trace(case: ContractCase):
    """eval_shape the program; returns (findings, out_avals | None)."""
    if case.fn is None:
        return [], None
    import jax

    try:
        out = jax.eval_shape(case.fn, *case.args, **dict(case.kwargs))
        return [], out
    except ContractSkip:
        raise
    except Exception as exc:
        msg = f"{type(exc).__name__}: {_oneline(exc)}"
        text = str(exc).lower()
        # Classify narrowly: axis-binding failures surface as jax's
        # "unbound axis name" / "axis name" errors, or as a bare
        # NameError/KeyError on the axis string when specs resolve
        # against a declared mesh. Anything else (TypeError from a
        # drifted signature, a stray "axis out of bounds") is the
        # CONTRACT rotting, and must say so — a collective label there
        # would invite baselining genuine registry rot away.
        if ("unbound axis" in text or "axis name" in text
                or (case.mesh is not None
                    and isinstance(exc, (NameError, KeyError)))):
            return [("shard-collective",
                     f"tracing under the declared mesh failed: {msg}")], \
                None
        return [("shard-contract", f"tracing failed: {msg}")], None


def _check_donation(case: ContractCase, out_avals) -> list[tuple[str, str]]:
    """Every donated input leaf needs a shape/dtype-matching output leaf
    or XLA drops the alias (the donated buffer double-allocates)."""
    if not case.donate_argnums or out_avals is None:
        return []
    import jax

    pool = Counter(_leaf_sig(leaf)
                   for leaf in jax.tree_util.tree_leaves(out_avals))
    out = []
    for argnum in case.donate_argnums:
        if argnum >= len(case.args):
            out.append(("shard-contract",
                        f"donate_argnums entry {argnum} out of range for "
                        f"{len(case.args)} declared args"))
            continue
        for leaf in jax.tree_util.tree_leaves(case.args[argnum]):
            sig = _leaf_sig(leaf)
            if pool[sig] > 0:
                pool[sig] -= 1
            else:
                shape, dtype = sig
                out.append((
                    "shard-donation",
                    f"donated arg {argnum} leaf {list(shape)}/{dtype} "
                    f"has no shape/dtype-matching output — XLA drops "
                    f"the alias and the buffer double-allocates"))
    return out


def _kv_signatures(tree) -> set[tuple]:
    """Layout signatures of a cache pytree under the engine-wide
    ``[L, batch/slots/blocks, Hkv, seq/block, Dh]`` convention."""
    import jax

    sigs = set()
    for leaf in jax.tree_util.tree_leaves(tree):
        if len(leaf.shape) != 5:
            sigs.add(("non-5d", tuple(leaf.shape), str(leaf.dtype)))
            continue
        sigs.add((leaf.shape[0], leaf.shape[2], leaf.shape[4],
                  str(leaf.dtype)))
    return sigs


def _check_buckets(case: ContractCase) -> list[tuple[str, str]]:
    if case.buckets is None:
        return []
    buckets = sorted(case.buckets)
    if not buckets:
        return [("shard-bucket", "empty padding-bucket table — every "
                 "shape compiles its own program")]
    out = []
    for need in case.bucket_covers:
        if need > buckets[-1]:
            out.append((
                "shard-bucket",
                f"declared input length {need} exceeds the largest "
                f"padding bucket ({buckets[-1]}; table {buckets}) — "
                f"unbounded retrace or silent truncation"))
    return out


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------


def check_modules(modules=None):
    """Collect and verify contracts. Returns
    ``(findings, checked_paths, skips)`` — findings already filtered
    through inline ``# jaxlint: disable=`` suppressions at the contract
    declaration line; ``skips`` are ``(context, reason)`` notes for
    ContractSkip factories (environment, not code, problems)."""
    entries, findings = collect(modules)
    checked: list[pathlib.Path] = []
    seen_paths: set[pathlib.Path] = set()
    skips: list[tuple[str, str]] = []
    suppressions: dict[pathlib.Path, Suppressions] = {}
    # kv groups accumulate across every contract in the run
    kv_groups: dict[str, list[tuple]] = {}

    def suppressed(path: pathlib.Path, rule: str, line: int) -> bool:
        if path not in suppressions:
            try:
                suppressions[path] = Suppressions(path.read_text())
            except OSError:
                suppressions[path] = Suppressions("")
        return suppressions[path].is_suppressed(rule, line)

    def emit(path, lineno, context, results):
        for rule, message in results:
            if not suppressed(path, rule, lineno):
                findings.append(Finding(rule, rel(path), lineno,
                                        message, context))

    for con, path in entries:
        if path not in seen_paths:
            seen_paths.add(path)
            checked.append(path)
        try:
            produced = con.factory()
        except ContractSkip as skip:
            skips.append((con.name, str(skip)))
            continue
        except Exception as exc:
            emit(path, con.lineno, con.name,
                 [("shard-contract",
                   f"contract factory raised {type(exc).__name__}: "
                   f"{_oneline(exc)}")])
            continue
        cases = produced if isinstance(produced, (list, tuple)) \
            else [produced]
        for case in cases:
            if not isinstance(case, ContractCase):
                emit(path, con.lineno, con.name,
                     [("shard-contract",
                       f"factory returned {type(case).__name__}, "
                       f"expected ContractCase")])
                continue
            context = f"{con.name}:{case.label}" if case.label \
                else con.name
            results = []
            results += _check_rules_table(case)
            results += _check_logical(case)
            results += _check_buckets(case)
            try:
                trace_findings, out_avals = _check_trace(case)
            except ContractSkip as skip:
                skips.append((context, str(skip)))
                emit(path, con.lineno, context, results)
                continue
            results += trace_findings
            results += _check_donation(case, out_avals)
            if case.kv_group:
                for label, tree in case.kv_caches:
                    kv_groups.setdefault(case.kv_group, []).append(
                        (path, con.lineno, context, label,
                         frozenset(_kv_signatures(tree))))
            emit(path, con.lineno, context, results)

    # kv-layout agreement: every member of a group must carry exactly
    # the reference signature (the group's first declaration wins the
    # role of reference; the message names both sides).
    for group, members in sorted(kv_groups.items()):
        ref_path, ref_line, ref_ctx, ref_label, ref_sig = members[0]
        if len(ref_sig) != 1:
            emit(ref_path, ref_line, ref_ctx,
                 [("shard-kv-layout",
                   f"kv group '{group}': '{ref_label}' mixes layouts "
                   f"{sorted(ref_sig)} within one cache")])
        for path, lineno, ctx, label, sig in members[1:]:
            if sig != ref_sig:
                emit(path, lineno, ctx,
                     [("shard-kv-layout",
                       f"kv group '{group}': '{label}' layout "
                       f"{sorted(sig)} != '{ref_label}' layout "
                       f"{sorted(ref_sig)} (declared in {ref_ctx}) — "
                       f"the programs do not share one KV-cache "
                       f"layout")])
    return findings, checked, skips


# ---------------------------------------------------------------------------
# subprocess runner (what the main CLI and bench preflight call)
# ---------------------------------------------------------------------------


_DEVICE_FLAG_RE = re.compile(
    r"--xla_force_host_platform_device_count=(\d+)")


def _force_cpu_env(env) -> None:
    """Force the CPU platform and AT LEAST the virtual device count the
    contracts need, in place. A pre-existing lower count (e.g. a shell
    that exports =4 for other tests) must be RAISED, not preserved —
    otherwise every require_devices(8) contract silently skips and the
    pass reports CLEAN with most of its coverage gone. A higher count
    is kept."""
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    m = _DEVICE_FLAG_RE.search(flags)
    if m and int(m.group(1)) >= DEVICE_COUNT:
        return
    if m:
        flags = _DEVICE_FLAG_RE.sub("", flags).strip()
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count"
                f"={DEVICE_COUNT}").strip()


def worker_env() -> dict:
    """Env for the semantic-pass subprocess: CPU platform, ≥8 virtual
    devices (same virtualization as tests/conftest.py)."""
    env = dict(os.environ)
    _force_cpu_env(env)
    return env


def spawn_worker(modules=None, baseline=None) -> subprocess.Popen:
    """Start the worker subprocess (jax must initialize with the CPU
    platform and the virtual device count BEFORE any backend touch —
    same reason the policy group's import smoke is a subprocess).
    Spawn early and :func:`finish_worker` late to overlap the ~10s
    trace pass with other work (the main CLI overlaps it with the ast
    groups + import smoke). ``baseline=None`` disables the worker's
    own baseline application — callers who apply the baseline
    themselves (the main CLI) must not have it applied twice."""
    cmd = [sys.executable, "-m",
           "copilot_for_consensus_tpu.analysis.shardcheck", "--json"]
    if modules:
        cmd += ["--modules", ",".join(str(m) for m in modules)]
    if baseline:
        cmd += ["--baseline", str(baseline)]
    else:
        cmd += ["--no-baseline"]
    return subprocess.Popen(cmd, cwd=ROOT, env=worker_env(),
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def finish_worker(proc: subprocess.Popen, timeout: float = 900.0):
    """Collect a spawned worker and parse its one JSON result line.
    Returns ``(data, detail)``: the worker's result dict or None, with
    ``detail`` the error summary when None."""
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        return None, f"semantic pass timed out after {timeout:.0f}s"
    for line in reversed((stdout or "").strip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line), ""
            except json.JSONDecodeError:
                continue
    tail = (stderr or stdout or "").strip().splitlines()
    detail = tail[-1] if tail else f"rc={proc.returncode}"
    return None, f"semantic pass produced no result: {detail[:300]}"


def run_worker(modules=None, baseline=None, timeout: float = 900.0):
    """spawn + finish in one call (the bench preflight route)."""
    return finish_worker(spawn_worker(modules, baseline), timeout)


def check_semantic(modules=None, timeout: float = 900.0, proc=None):
    """Run the semantic pass in a subprocess (or collect an
    already-spawned ``proc``). Returns ``(findings, checked_paths)``;
    an infra failure is itself a ``shard-contract`` finding, never a
    silent pass."""
    self_path = rel(pathlib.Path(__file__))
    if proc is None:
        proc = spawn_worker(modules)
    data, detail = finish_worker(proc, timeout)
    if data is None:
        return [Finding("shard-contract", self_path, 1, detail)], []
    for ctx, reason in data.get("skips", ()):
        print(f"jaxlint: shardcheck skipped {ctx}: {reason}",
              file=sys.stderr)
    findings = [Finding(d["rule"], d["path"], d["line"], d["message"],
                        d.get("context", ""))
                for d in data.get("findings", ())]
    checked = [ROOT / p for p in data.get("checked", ())]
    return findings, checked


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m copilot_for_consensus_tpu.analysis.shardcheck",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--modules",
                    help="comma list of dotted modules or .py paths "
                         "(default: the full contract registry)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON line")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="apply this jaxlint baseline file (entries "
                         "with shard-* rules) before reporting "
                         "(default: jaxlint_baseline.json at the repo "
                         "root — so the standalone run agrees with "
                         "the main CLI)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything); "
                         "the main CLI spawns the worker with this, "
                         "as it applies the baseline itself")
    args = ap.parse_args(argv)

    # Force the CPU platform even when a sitecustomize pre-imported jax
    # for a TPU plugin: this is a static-analysis pass, it must never
    # grab (or hang on) an accelerator. Setting the virtual device
    # count here works as long as the backend is still uninitialized
    # (XLA reads XLA_FLAGS at CPU-client creation, not at jax import);
    # spawning via spawn_worker()/worker_env() guarantees it.
    _force_cpu_env(os.environ)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception as exc:
        msg = f"jax unavailable: {type(exc).__name__}: {_oneline(exc)}"
        if args.json:
            print(json.dumps({"findings": [
                {"rule": "shard-contract", "path": "jax", "line": 1,
                 "message": msg, "context": ""}], "checked": [],
                "skips": []}))
        else:
            print(msg, file=sys.stderr)
        return 1

    modules = [m.strip() for m in args.modules.split(",")
               if m.strip()] if args.modules else None
    findings, checked, skips = check_modules(modules)
    if not args.no_baseline:
        from copilot_for_consensus_tpu.analysis.base import (
            apply_baseline,
            load_baseline,
        )

        entries, errors = load_baseline(pathlib.Path(args.baseline))
        for err in errors:
            print(f"shardcheck: {err}", file=sys.stderr)
        if not errors:
            entries = [e for e in entries
                       if str(e.get("rule", "")).startswith("shard-")]
            findings, _ = apply_baseline(findings, entries)

    if args.json:
        print(json.dumps({
            "findings": [{"rule": f.rule, "path": f.path, "line": f.line,
                          "message": f.message, "context": f.context}
                         for f in findings],
            "checked": [rel(p) for p in checked],
            "skips": list(skips),
        }))
    else:
        for ctx, reason in skips:
            print(f"shardcheck: skipped {ctx}: {reason}",
                  file=sys.stderr)
        for f in findings:
            print(f.render())
        verdict = "CLEAN" if not findings else f"{len(findings)} finding(s)"
        print(f"shardcheck: {len(checked)} contract module(s): {verdict}",
              file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
