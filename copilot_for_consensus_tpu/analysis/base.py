"""jaxlint plumbing: findings, inline suppressions, the baseline file.

The ast rule groups never import the code they check, so they run
identically on a TPU host, a CPU CI runner, or a laptop without jax
installed (the one exception is the policy group's import-smoke stage,
which imports every package module in a subprocess — ``--fast`` skips
it). Everything here is shared by the rule groups in ``jax_rules.py``
/ ``concurrency.py`` / ``policy.py``.

Suppression surfaces, in precedence order:

1. ``# jaxlint: disable=<rule>[,<rule>...]`` — inline, on the offending
   line or on a comment-only line directly above it. Use for findings
   that are deliberate AND local (put the justification in the same
   comment).
2. The committed baseline file (``jaxlint_baseline.json`` at the repo
   root) — for grandfathered findings. Every entry MUST carry a
   non-empty ``justification``; an unjustified entry fails the run, so
   the baseline cannot silently become a dumping ground. Entries match
   on (rule, path, context, message) — never on line numbers, which
   drift with every edit.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
from dataclasses import dataclass

#: repo root (the directory holding ``copilot_for_consensus_tpu/``)
ROOT = pathlib.Path(__file__).resolve().parent.parent.parent
PACKAGE = ROOT / "copilot_for_consensus_tpu"
DEFAULT_BASELINE = ROOT / "jaxlint_baseline.json"

_DISABLE_RE = re.compile(r"#\s*jaxlint:\s*disable=([\w\-, ]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative posix path (or absolute if outside)
    line: int
    message: str
    context: str = ""  # enclosing function/class qualname; "" = module

    def render(self) -> str:
        ctx = f" [in {self.context}]" if self.context else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{ctx}"

    def render_github(self) -> str:
        """GitHub Actions annotation line (``--format=github``): shows
        the finding inline on the PR diff. Properties/message need the
        runner's %-escapes for newlines."""
        ctx = f" [in {self.context}]" if self.context else ""
        msg = (self.message + ctx).replace("%", "%25") \
            .replace("\r", "").replace("\n", "%0A")
        return (f"::error file={self.path},line={self.line},"
                f"title=jaxlint {self.rule}::{msg}")

    def key(self) -> tuple[str, str, str, str]:
        """Line-number-free identity used for baseline matching."""
        return (self.rule, self.path, self.context, self.message)


def rel(path: pathlib.Path) -> str:
    """Stable path spelling for findings and baseline entries."""
    try:
        return path.resolve().relative_to(ROOT).as_posix()
    except ValueError:
        return path.resolve().as_posix()


class Suppressions:
    """Per-line ``# jaxlint: disable=...`` map for one source file.

    A trailing comment suppresses its own line; a comment-only line
    suppresses the next line (so multi-rule justifications fit)."""

    def __init__(self, source: str):
        self._by_line: dict[int, set[str]] = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = _DISABLE_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            self._by_line.setdefault(i, set()).update(rules)
            if text.lstrip().startswith("#"):     # comment-only line
                self._by_line.setdefault(i + 1, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        return rule in self._by_line.get(line, ())


class Module:
    """A parsed source file plus the lookups every checker needs."""

    def __init__(self, path: pathlib.Path, source: str | None = None):
        self.path = path
        self.relpath = rel(path)
        self.source = path.read_text() if source is None else source
        self.lines = self.source.splitlines()
        self.tree: ast.Module | None = None
        self.syntax_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(self.source, filename=str(path))
        except SyntaxError as exc:   # policy-syntax owns reporting this
            self.syntax_error = exc
            self.suppressions = Suppressions(self.source)
            return
        self.suppressions = Suppressions(self.source)
        self._parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def qualname(self, node: ast.AST) -> str:
        """Dotted name of the enclosing defs/classes (for context)."""
        names: list[str] = []
        cur: ast.AST | None = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            elif isinstance(cur, ast.Lambda):
                names.append("<lambda>")
            cur = self._parents.get(cur)
        return ".".join(reversed(names))

    def finding(self, rule: str, node: ast.AST, message: str,
                context: str | None = None) -> Finding | None:
        """Build a Finding unless an inline suppression covers it."""
        line = getattr(node, "lineno", 1)
        if self.suppressions.is_suppressed(rule, line):
            return None
        ctx = self.qualname(node) if context is None else context
        return Finding(rule, self.relpath, line, message, ctx)


# ---------------------------------------------------------------------------
# baseline file
# ---------------------------------------------------------------------------


def load_baseline(path: pathlib.Path) -> tuple[list[dict], list[str]]:
    """Returns (entries, errors). An unreadable file or an entry with a
    missing/empty justification is an error — the lane fails rather than
    silently accepting an unaccounted-for suppression."""
    if not path.exists():
        return [], []
    try:
        entries = json.loads(path.read_text())
    except (OSError, ValueError) as exc:
        return [], [f"baseline {path}: unreadable: {exc}"]
    if not isinstance(entries, list):
        return [], [f"baseline {path}: expected a JSON list"]
    errors = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or not all(
                isinstance(e.get(k), str)
                for k in ("rule", "path", "context", "message")):
            errors.append(f"baseline {path}: entry {i} malformed "
                          "(need rule/path/context/message strings)")
            continue
        if not str(e.get("justification", "")).strip():
            errors.append(
                f"baseline {path}: entry {i} ({e['rule']} in {e['path']}) "
                "has no justification — every grandfathered finding must "
                "say WHY it is deliberate")
    return entries, errors


def unjustified_entries(entries: list[dict]) -> list[dict]:
    """Entries whose justification is still the ``--write-baseline``
    placeholder (starts with TODO). A non-empty placeholder passes the
    load-time emptiness check, so without this the generated TODO text
    could sit in the baseline forever looking like an explanation;
    ``--strict`` (CI) turns these into failures (finding id
    ``baseline-unjustified``)."""
    return [e for e in entries
            if str(e.get("justification", "")).strip().lower()
            .startswith("todo")]


def apply_baseline(findings: list[Finding], entries: list[dict]
                   ) -> tuple[list[Finding], list[dict]]:
    """Returns (non-baselined findings, stale entries). Matching is by
    Finding.key(); one entry may cover several findings (e.g. the same
    message at two call sites of one function)."""
    keyed = {(e["rule"], e["path"], e["context"], e["message"]): e
             for e in entries}
    used: set[tuple] = set()
    out = []
    for f in findings:
        if f.key() in keyed:
            used.add(f.key())
        else:
            out.append(f)
    stale = [e for k, e in keyed.items() if k not in used]
    return out, stale


def baseline_entries_for(findings: list[Finding]) -> list[dict]:
    """Render findings as baseline entries (for ``--write-baseline``).
    Justifications are intentionally unusable until a human fills them."""
    seen: set[tuple] = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append({"rule": f.rule, "path": f.path, "context": f.context,
                    "message": f.message,
                    "justification": "TODO: explain why this is deliberate"})
    return out


# ---------------------------------------------------------------------------
# assignment-provenance lock model (shared by blocking-call and racecheck)
# ---------------------------------------------------------------------------
#
# The old heuristic ("does the with-item's name contain 'lock'?") missed
# every Condition-typed member (``async_runner._work``) and every alias
# whose name doesn't say lock. This model tracks *provenance* instead:
# a name is a lock because it was BOUND from ``threading.Lock()`` /
# ``RLock()`` / ``Condition()`` / ``Semaphore()`` (directly, via a
# dataclass ``field(default_factory=threading.Lock)``, or by aliasing —
# ``Condition(self._lock)`` shares the identity of ``self._lock``).
# Events and Threads ride the same machinery (racecheck's
# thread-lifecycle rule needs both).

#: threading factory name -> (role, reentrant). Condition() builds its
#: own RLock, so bare Condition is reentrant; Condition(lock) aliases
#: the wrapped lock and inherits ITS reentrancy. Semaphores are marked
#: reentrant (re-acquiring one is legal when the count allows) so they
#: never produce self-deadlock findings, only cross-lock cycles.
THREADING_FACTORIES = {
    "Lock": ("lock", False),
    "RLock": ("lock", True),
    "Condition": ("lock", True),
    "Semaphore": ("lock", True),
    "BoundedSemaphore": ("lock", True),
    "Event": ("event", False),
    "Thread": ("thread", False),
}


@dataclass
class LockInfo:
    """One threading primitive with a stable identity. Aliases (a
    Condition wrapping a Lock, a field assigned from another lock
    field) map to the SAME LockInfo object, so identity comparisons
    answer "is this the same lock?" regardless of spelling."""

    name: str          # canonical spelling, e.g. "Broker._stats_lock"
    kind: str          # factory of the original binding ("Lock", ...)
    role: str          # "lock" | "event" | "thread"
    reentrant: bool
    line: int


def threading_imports(tree: ast.Module) -> set[str]:
    """Bare names this module imported from ``threading`` (so a bare
    ``Thread(...)`` / ``Lock()`` is only treated as the primitive when
    it actually IS one — a domain class named Thread is not)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "threading":
            for alias in node.names:
                out.add(alias.asname or alias.name)
    return out


def _named_factory(head: str, bare_ok: set[str]) -> str | None:
    tail = head.rsplit(".", 1)[-1]
    if tail not in THREADING_FACTORIES:
        return None
    if head.startswith("threading."):
        return tail
    if "." not in head and head in bare_ok:
        return tail
    return None


def _factory_of(value: ast.AST, bare_ok: set[str]) -> str | None:
    """Factory name when ``value`` constructs a threading primitive:
    ``threading.Lock()``, bare ``Lock()`` (when from-imported from
    threading), or the dataclass idiom
    ``field(default_factory=threading.Lock)``."""
    if not isinstance(value, ast.Call):
        return None
    hit = _named_factory(dotted_name(value.func), bare_ok)
    if hit is not None:
        return hit
    if dotted_name(value.func).rsplit(".", 1)[-1] == "field":
        df = kw(value, "default_factory")
        if df is not None:
            return _named_factory(dotted_name(df), bare_ok)
    return None


class LockModel:
    """Where every threading primitive in one module is bound.

    Three scopes: module-level names, per-class instance/class fields
    (``self._x = threading.Lock()`` in any method, class-body
    assignments, dataclass ``field(default_factory=...)``), and
    function locals. ``resolve(expr, node)`` answers "which primitive
    does this expression denote at this use site?" using the enclosing
    class/function found through the parent map."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.module_vars: dict[str, LockInfo] = {}
        self.class_fields: dict[str, dict[str, LockInfo]] = {}
        self.fn_locals: dict[tuple[str, str], LockInfo] = {}
        if mod.tree is None:
            self.bare_names: set[str] = set()
            return
        self.bare_names = threading_imports(mod.tree)
        # Pass 1: direct factory bindings plus aliases whose source is
        # already known. Unresolvable aliases (``Condition(self._lock)``
        # textually BEFORE ``self._lock = threading.Lock()``) are
        # deferred, not bound fresh — a premature fresh binding would
        # stick (bindings never overwrite) and hide the alias identity.
        # Pass 2 (final) re-walks: deferred aliases now resolve against
        # the pass-1 bindings; a Condition whose wrapped lock is still
        # unknown (e.g. a parameter) binds as its own fresh lock.
        for final in (False, True):
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign):
                    self._bind(node.targets, node.value, node, final)
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    self._bind([node.target], node.value, node, final)

    # -- collection ----------------------------------------------------

    def _scope_of(self, node: ast.AST) -> tuple[str | None, str | None]:
        """(enclosing class name, enclosing function qualname)."""
        cls = fn = None
        cur = self.mod.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn is None:
                fn = self.mod.qualname(cur)
            elif isinstance(cur, ast.ClassDef) and cls is None:
                cls = cur.name
            cur = self.mod.parent(cur)
        return cls, fn

    def _bind(self, targets: list[ast.expr], value: ast.AST,
              site: ast.AST, final: bool = True) -> None:
        factory = _factory_of(value, self.bare_names)
        info: LockInfo | None = None
        cls, fn = self._scope_of(site)
        if factory is not None:
            role, reentrant = THREADING_FACTORIES[factory]
            # Condition(existing_lock) aliases the wrapped lock
            if factory == "Condition" and isinstance(value, ast.Call) \
                    and value.args:
                inner = self.resolve(value.args[0], site)
                if inner is not None and inner.role == "lock":
                    info = inner
                elif not final:
                    return    # wrapped lock not bound yet: defer
            if info is None:
                info = LockInfo("", factory, role, reentrant,
                                getattr(site, "lineno", 1))
        else:
            # plain alias: RHS is itself a known primitive
            if isinstance(value, (ast.Name, ast.Attribute)):
                info = self.resolve(value, site)
            if info is None:
                return
        for t in targets:
            if isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name) and t.value.id == "self" \
                    and cls is not None:
                fields = self.class_fields.setdefault(cls, {})
                if not info.name:
                    info.name = f"{cls}.{t.attr}"
                fields.setdefault(t.attr, info)
            elif isinstance(t, ast.Name):
                if fn is not None:
                    if not info.name:
                        info.name = t.id
                    self.fn_locals.setdefault((fn, t.id), info)
                elif cls is not None:
                    # class-body assignment: a class attribute
                    if not info.name:
                        info.name = f"{cls}.{t.id}"
                    self.class_fields.setdefault(cls, {}).setdefault(
                        t.id, info)
                else:
                    if not info.name:
                        info.name = t.id
                    self.module_vars.setdefault(t.id, info)

    # -- resolution ----------------------------------------------------

    def resolve(self, expr: ast.AST,
                use_site: ast.AST) -> LockInfo | None:
        """The primitive ``expr`` denotes at ``use_site``, or None.
        ``self.x`` looks in the enclosing class; a bare name looks in
        the enclosing function's locals, then the class attributes,
        then module scope."""
        cls, fn = self._scope_of(use_site)
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self":
            if cls is not None:
                return self.class_fields.get(cls, {}).get(expr.attr)
            return None
        if isinstance(expr, ast.Name):
            if fn is not None:
                hit = self.fn_locals.get((fn, expr.id))
                if hit is not None:
                    return hit
            if cls is not None:
                hit = self.class_fields.get(cls, {}).get(expr.id)
                if hit is not None:
                    return hit
            return self.module_vars.get(expr.id)
        return None

    def locks_of(self, cls: str) -> dict[str, LockInfo]:
        """Field name -> LockInfo for one class (role 'lock' only)."""
        return {f: i for f, i in self.class_fields.get(cls, {}).items()
                if i.role == "lock"}


# ---------------------------------------------------------------------------
# effect-provenance model (shared by duracheck)
# ---------------------------------------------------------------------------
#
# The durability rules reason about *orderings of effects along a
# path* — store writes, publishes, journal mutations — so they first
# need to know which expressions denote an effectful receiver at all.
# Name tokens ("does it contain 'publisher'?") would misfire on
# wrappers and miss renamed fields; this model tracks provenance the
# way LockModel does for threading primitives: a name is a publisher
# because it was BOUND from a publisher — a tagged constructor
# parameter (``def __init__(self, publisher, store, ...)``; the
# ``self.<param> = param`` service convention is trusted even when the
# assignment happens in a base class the per-module pass can't see),
# a tagged constructor call (``EngineJournal(...)``,
# ``sqlite3.connect(...)``), or an alias of either.

#: constructor-parameter name → effect tag (the BaseService wiring
#: convention every service follows)
EFFECT_PARAM_TAGS = {
    "publisher": "publisher",
    "store": "store",
    "document_store": "store",
    "journal": "journal",
}

#: annotation class name → effect tag (covers renamed parameters:
#: ``bus: EventPublisher`` is a publisher no matter its spelling)
EFFECT_ANNOTATION_TAGS = {
    "EventPublisher": "publisher",
    "BrokerPublisher": "publisher",
    "DocumentStore": "store",
    "EngineJournal": "journal",
}

#: RHS call → effect tag. ``sqlite3.connect`` must be spelled dotted
#: (every first-party ledger does); the journal factories match by
#: tail so relative imports work.
EFFECT_CTOR_TAGS = {
    "EngineJournal": "journal",
    "resolve_journal": "journal",
    "sqlite3.connect": "sqlite",
}


@dataclass
class EffectInfo:
    """One effectful receiver with a stable identity (aliases share
    the object, so ``db = self._db; db.close()`` closes THE ledger)."""

    tag: str           # "publisher" | "store" | "journal" | "sqlite"
    name: str          # canonical spelling, e.g. "EngineJournal._db"
    line: int


def _annotation_tag(ann: ast.AST | None) -> str | None:
    if ann is None:
        return None
    names: list[str] = []
    for n in ast.walk(ann):
        if isinstance(n, (ast.Name, ast.Attribute)):
            d = dotted_name(n)
            if d:
                names.append(d.rsplit(".", 1)[-1])
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            names.extend(re.findall(r"\w+", n.value))
    for nm in names:
        if nm in EFFECT_ANNOTATION_TAGS:
            return EFFECT_ANNOTATION_TAGS[nm]
    return None


def _param_tag(arg: ast.arg) -> str | None:
    hit = _annotation_tag(arg.annotation)
    if hit is not None:
        return hit
    return EFFECT_PARAM_TAGS.get(arg.arg)


class EffectModel:
    """Where every effectful receiver in one module is bound. Same
    three scopes and the same resolution order as :class:`LockModel`:
    module names, per-class fields, function locals."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.module_vars: dict[str, EffectInfo] = {}
        self.class_fields: dict[str, dict[str, EffectInfo]] = {}
        self.fn_locals: dict[tuple[str, str], EffectInfo] = {}
        if mod.tree is None:
            return
        self._collect_params()
        # Two passes, like LockModel: aliases whose source binds later
        # in the file resolve on the second walk.
        for final in (False, True):
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign):
                    self._bind(node.targets, node.value, node, final)
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    self._bind([node.target], node.value, node, final)

    def _scope_of(self, node: ast.AST) -> tuple[str | None, str | None]:
        cls = fn = None
        cur = self.mod.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn is None:
                fn = self.mod.qualname(cur)
            elif isinstance(cur, ast.ClassDef) and cls is None:
                cls = cur.name
            cur = self.mod.parent(cur)
        return cls, fn

    def _collect_params(self) -> None:
        """Tagged parameters bind as function locals; tagged ``__init__``
        parameters ALSO bind the same-named instance field — the
        ``self.store = store`` convention, which often executes in a
        base class another module owns."""
        assert self.mod.tree is not None
        for fn in ast.walk(self.mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            cls, _ = self._scope_of(fn)
            qn = self.mod.qualname(fn)
            a = fn.args
            for arg in a.posonlyargs + a.args + a.kwonlyargs:
                tag = _param_tag(arg)
                if tag is None or arg.arg == "self":
                    continue
                info = EffectInfo(tag, arg.arg,
                                  getattr(fn, "lineno", 1))
                self.fn_locals.setdefault((qn, arg.arg), info)
                if cls is not None and fn.name == "__init__":
                    self.class_fields.setdefault(cls, {}).setdefault(
                        arg.arg, EffectInfo(
                            tag, f"{cls}.{arg.arg}", fn.lineno))

    def _ctor_tag(self, value: ast.AST) -> str | None:
        if not isinstance(value, ast.Call):
            return None
        d = dotted_name(value.func)
        if d in EFFECT_CTOR_TAGS:
            return EFFECT_CTOR_TAGS[d]
        tail = d.rsplit(".", 1)[-1]
        if tail in ("EngineJournal", "resolve_journal"):
            return EFFECT_CTOR_TAGS[tail]
        return None

    def _bind(self, targets: list[ast.expr], value: ast.AST,
              site: ast.AST, final: bool) -> None:
        tag = self._ctor_tag(value)
        info: EffectInfo | None = None
        cls, fn = self._scope_of(site)
        if tag is not None:
            info = EffectInfo(tag, "", getattr(site, "lineno", 1))
        elif isinstance(value, (ast.Name, ast.Attribute)):
            info = self.resolve(value, site)
            if info is None:
                return
        else:
            return
        for t in targets:
            if isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name) and t.value.id == "self" \
                    and cls is not None:
                if not info.name:
                    info.name = f"{cls}.{t.attr}"
                self.class_fields.setdefault(cls, {}).setdefault(
                    t.attr, info)
            elif isinstance(t, ast.Name):
                if fn is not None:
                    if not info.name:
                        info.name = t.id
                    self.fn_locals.setdefault((fn, t.id), info)
                elif not info.name:
                    info.name = t.id
                if fn is None:
                    self.module_vars.setdefault(t.id, info)

    def resolve(self, expr: ast.AST,
                use_site: ast.AST) -> EffectInfo | None:
        """The effectful receiver ``expr`` denotes at ``use_site``."""
        cls, fn = self._scope_of(use_site)
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self":
            if cls is not None:
                return self.class_fields.get(cls, {}).get(expr.attr)
            return None
        if isinstance(expr, ast.Name):
            if fn is not None:
                hit = self.fn_locals.get((fn, expr.id))
                if hit is not None:
                    return hit
            if cls is not None:
                hit = self.class_fields.get(cls, {}).get(expr.id)
                if hit is not None:
                    return hit
            return self.module_vars.get(expr.id)
        return None


# ---------------------------------------------------------------------------
# small AST helpers shared by the rule groups
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """'jax.lax.psum' for Attribute/Name chains, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def str_constants(node: ast.AST) -> list[str]:
    """Every string literal anywhere under ``node``."""
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def int_constants(node: ast.AST) -> list[int]:
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, int)
            and not isinstance(n.value, bool)]


def kw(call: ast.Call, name: str) -> ast.expr | None:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None
