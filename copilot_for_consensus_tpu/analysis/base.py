"""jaxlint plumbing: findings, inline suppressions, the baseline file.

The ast rule groups never import the code they check, so they run
identically on a TPU host, a CPU CI runner, or a laptop without jax
installed (the one exception is the policy group's import-smoke stage,
which imports every package module in a subprocess — ``--fast`` skips
it). Everything here is shared by the rule groups in ``jax_rules.py``
/ ``concurrency.py`` / ``policy.py``.

Suppression surfaces, in precedence order:

1. ``# jaxlint: disable=<rule>[,<rule>...]`` — inline, on the offending
   line or on a comment-only line directly above it. Use for findings
   that are deliberate AND local (put the justification in the same
   comment).
2. The committed baseline file (``jaxlint_baseline.json`` at the repo
   root) — for grandfathered findings. Every entry MUST carry a
   non-empty ``justification``; an unjustified entry fails the run, so
   the baseline cannot silently become a dumping ground. Entries match
   on (rule, path, context, message) — never on line numbers, which
   drift with every edit.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
from dataclasses import dataclass

#: repo root (the directory holding ``copilot_for_consensus_tpu/``)
ROOT = pathlib.Path(__file__).resolve().parent.parent.parent
PACKAGE = ROOT / "copilot_for_consensus_tpu"
DEFAULT_BASELINE = ROOT / "jaxlint_baseline.json"

_DISABLE_RE = re.compile(r"#\s*jaxlint:\s*disable=([\w\-, ]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative posix path (or absolute if outside)
    line: int
    message: str
    context: str = ""  # enclosing function/class qualname; "" = module

    def render(self) -> str:
        ctx = f" [in {self.context}]" if self.context else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{ctx}"

    def render_github(self) -> str:
        """GitHub Actions annotation line (``--format=github``): shows
        the finding inline on the PR diff. Properties/message need the
        runner's %-escapes for newlines."""
        ctx = f" [in {self.context}]" if self.context else ""
        msg = (self.message + ctx).replace("%", "%25") \
            .replace("\r", "").replace("\n", "%0A")
        return (f"::error file={self.path},line={self.line},"
                f"title=jaxlint {self.rule}::{msg}")

    def key(self) -> tuple[str, str, str, str]:
        """Line-number-free identity used for baseline matching."""
        return (self.rule, self.path, self.context, self.message)


def rel(path: pathlib.Path) -> str:
    """Stable path spelling for findings and baseline entries."""
    try:
        return path.resolve().relative_to(ROOT).as_posix()
    except ValueError:
        return path.resolve().as_posix()


class Suppressions:
    """Per-line ``# jaxlint: disable=...`` map for one source file.

    A trailing comment suppresses its own line; a comment-only line
    suppresses the next line (so multi-rule justifications fit)."""

    def __init__(self, source: str):
        self._by_line: dict[int, set[str]] = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = _DISABLE_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            self._by_line.setdefault(i, set()).update(rules)
            if text.lstrip().startswith("#"):     # comment-only line
                self._by_line.setdefault(i + 1, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        return rule in self._by_line.get(line, ())


class Module:
    """A parsed source file plus the lookups every checker needs."""

    def __init__(self, path: pathlib.Path, source: str | None = None):
        self.path = path
        self.relpath = rel(path)
        self.source = path.read_text() if source is None else source
        self.lines = self.source.splitlines()
        self.tree: ast.Module | None = None
        self.syntax_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(self.source, filename=str(path))
        except SyntaxError as exc:   # policy-syntax owns reporting this
            self.syntax_error = exc
            self.suppressions = Suppressions(self.source)
            return
        self.suppressions = Suppressions(self.source)
        self._parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def qualname(self, node: ast.AST) -> str:
        """Dotted name of the enclosing defs/classes (for context)."""
        names: list[str] = []
        cur: ast.AST | None = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            elif isinstance(cur, ast.Lambda):
                names.append("<lambda>")
            cur = self._parents.get(cur)
        return ".".join(reversed(names))

    def finding(self, rule: str, node: ast.AST, message: str,
                context: str | None = None) -> Finding | None:
        """Build a Finding unless an inline suppression covers it."""
        line = getattr(node, "lineno", 1)
        if self.suppressions.is_suppressed(rule, line):
            return None
        ctx = self.qualname(node) if context is None else context
        return Finding(rule, self.relpath, line, message, ctx)


# ---------------------------------------------------------------------------
# baseline file
# ---------------------------------------------------------------------------


def load_baseline(path: pathlib.Path) -> tuple[list[dict], list[str]]:
    """Returns (entries, errors). An unreadable file or an entry with a
    missing/empty justification is an error — the lane fails rather than
    silently accepting an unaccounted-for suppression."""
    if not path.exists():
        return [], []
    try:
        entries = json.loads(path.read_text())
    except (OSError, ValueError) as exc:
        return [], [f"baseline {path}: unreadable: {exc}"]
    if not isinstance(entries, list):
        return [], [f"baseline {path}: expected a JSON list"]
    errors = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or not all(
                isinstance(e.get(k), str)
                for k in ("rule", "path", "context", "message")):
            errors.append(f"baseline {path}: entry {i} malformed "
                          "(need rule/path/context/message strings)")
            continue
        if not str(e.get("justification", "")).strip():
            errors.append(
                f"baseline {path}: entry {i} ({e['rule']} in {e['path']}) "
                "has no justification — every grandfathered finding must "
                "say WHY it is deliberate")
    return entries, errors


def unjustified_entries(entries: list[dict]) -> list[dict]:
    """Entries whose justification is still the ``--write-baseline``
    placeholder (starts with TODO). A non-empty placeholder passes the
    load-time emptiness check, so without this the generated TODO text
    could sit in the baseline forever looking like an explanation;
    ``--strict`` (CI) turns these into failures (finding id
    ``baseline-unjustified``)."""
    return [e for e in entries
            if str(e.get("justification", "")).strip().lower()
            .startswith("todo")]


def apply_baseline(findings: list[Finding], entries: list[dict]
                   ) -> tuple[list[Finding], list[dict]]:
    """Returns (non-baselined findings, stale entries). Matching is by
    Finding.key(); one entry may cover several findings (e.g. the same
    message at two call sites of one function)."""
    keyed = {(e["rule"], e["path"], e["context"], e["message"]): e
             for e in entries}
    used: set[tuple] = set()
    out = []
    for f in findings:
        if f.key() in keyed:
            used.add(f.key())
        else:
            out.append(f)
    stale = [e for k, e in keyed.items() if k not in used]
    return out, stale


def baseline_entries_for(findings: list[Finding]) -> list[dict]:
    """Render findings as baseline entries (for ``--write-baseline``).
    Justifications are intentionally unusable until a human fills them."""
    seen: set[tuple] = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append({"rule": f.rule, "path": f.path, "context": f.context,
                    "message": f.message,
                    "justification": "TODO: explain why this is deliberate"})
    return out


# ---------------------------------------------------------------------------
# small AST helpers shared by the rule groups
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """'jax.lax.psum' for Attribute/Name chains, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def str_constants(node: ast.AST) -> list[str]:
    """Every string literal anywhere under ``node``."""
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def int_constants(node: ast.AST) -> list[int]:
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, int)
            and not isinstance(n.value, bool)]


def kw(call: ast.Call, name: str) -> ast.expr | None:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None
