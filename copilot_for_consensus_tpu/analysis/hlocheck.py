"""hlocheck — post-lowering verification of compiled-artifact contracts.

Every other analysis family (jaxlint ast rules, shardcheck, racecheck,
duracheck) verifies contracts BEFORE XLA lowers the program, and the
repo has paid twice for what that misses: the tp-within-head_dim RoPE
miscompile hid for 15 PRs because trace-level checks cannot see what
GSPMD actually emitted, and the kernel route's gather-elimination
guarantee was pinned only by a one-off trace spy. This module closes
the gap: it lowers the engine's REAL jitted dispatches via
``fn.lower(...)`` / ``.compile()`` under the same virtual 8-device CPU
platform shardcheck uses, and verifies the declared
:class:`~.contracts.HloSpec` budgets against the artifact itself:

* ``hlo-donation-alias`` — every ``donate_argnums`` leaf must survive
  as a compiled ``input_output_alias`` entry. shard-donation can only
  shape-match the trace; XLA still drops aliases (pruned params,
  layout mismatches) with a warning nobody reads, silently turning
  zero-copy pool updates into full-HBM copies per dispatch.
* ``hlo-materialize`` — per-contract forbidden-op fingerprints on the
  lowered StableHLO: the kernel route's paged dispatches must contain
  no pool-working-set ``gather`` at/above the declared element
  threshold. The pre-optimization lowering is checked on purpose —
  XLA fusion can hide the op, and the algebraic simplifier could fold
  a sentinel away; the lowering cannot lie about what was traced.
* ``hlo-collective-budget`` — the compiled program's
  all-reduce / all-gather / reduce-scatter / collective-permute /
  all-to-all counts must match the declared budget exactly (ops absent
  from the budget must be absent from the program). This is the
  RoPE-miscompile-class tripwire: GSPMD reshard insertion shows up as
  a changed collective count long before a TPU run shows it as a
  wrong answer or a 2x step time.
* ``hlo-peak-memory`` — ``compiled.memory_analysis()`` peak
  (argument + output + temp − aliased bytes) per dispatch, gated
  against the declared budget, so a paged_gather_kv-style working-set
  blowup fails CI instead of an HBM OOM on hardware. Measured peaks
  are snapshotted in docs/artifacts/HLO_BUDGETS.json (regenerate with
  ``--budgets``).
* ``hlo-program-cache`` — lowering every declared bucket-table variant
  (prefill buckets × draft widths × chunk) must yield exactly the
  declared number of distinct programs: a widened table that forgets
  its declaration is a retrace/program-cache explosion.
* ``hlo-contract`` — the contract itself is broken (module fails to
  import, declares no HLO specs, lowering/compilation raises): the
  registry must not rot silently.

Run it alone (``python -m copilot_for_consensus_tpu.analysis.hlocheck``)
or let the main CLI fold it in (``--group hlo``; skipped under
``--fast`` and for explicit-path runs — compiling is the expensive
half of the lane). In-process, :func:`check_modules` is the API tests
drive fixtures and mutated modules through; ``labels=`` /
``only_rules=`` narrow a tripwire run to one case and one artifact so
mutation tests stay cheap. Findings flow through the same inline
``# jaxlint: disable=`` suppression and justified-baseline machinery
as every other jaxlint rule.
"""

from __future__ import annotations

import argparse
import functools
import hashlib
import json
import os
import pathlib
import re
import sys
import warnings

from copilot_for_consensus_tpu.analysis.base import (
    DEFAULT_BASELINE,
    Finding,
    ROOT,
    Suppressions,
    rel,
)
from copilot_for_consensus_tpu.analysis.contracts import (
    HLO_CONTRACT_MODULES,
    ContractCase,
    ContractSkip,
)
from copilot_for_consensus_tpu.analysis.shardcheck import (
    _oneline,
    _spec_path,
    finish_worker,
    load_contract_module,
    worker_env,
)

RULES = (
    "hlo-donation-alias",
    "hlo-materialize",
    "hlo-collective-budget",
    "hlo-peak-memory",
    "hlo-program-cache",
    "hlo-contract",
)

#: the collective-op vocabulary of hlo-collective-budget: every op here
#: is counted in the compiled text and compared against the declared
#: budget (absent from the budget == must be absent from the program).
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)


# ---------------------------------------------------------------------------
# contract collection (hlo-bearing cases only)
# ---------------------------------------------------------------------------


def collect(modules=None):
    """Import the HLO contract modules and read their tables. Returns
    ``(entries, findings)`` like shardcheck.collect; a module that
    imports but declares no contracts is registry rot here too."""
    specs = HLO_CONTRACT_MODULES if modules is None else modules
    entries = []
    findings: list[Finding] = []
    for spec in specs:
        try:
            mod = load_contract_module(str(spec))
        except Exception as exc:
            findings.append(Finding(
                "hlo-contract", _spec_path(str(spec)), 1,
                f"contract module failed to import: "
                f"{type(exc).__name__}: {_oneline(exc)}"))
            continue
        path = pathlib.Path(mod.__file__)
        table = getattr(mod, "SHARDCHECK_CONTRACTS", None)
        if not table:
            findings.append(Finding(
                "hlo-contract", rel(path), 1,
                "module declares no SHARDCHECK_CONTRACTS — the "
                "post-lowering pass no longer covers it"))
            continue
        entries.extend((c, path) for c in table)
    return entries, findings


# ---------------------------------------------------------------------------
# lowering / compiling one case
# ---------------------------------------------------------------------------


def _resolve_lowerable(fn):
    """Split a case fn into ``(lowerable, bound_args, bound_kwargs)``.

    The engine declares either the jitted fn itself or a
    ``functools.partial`` binding its static args; both are lowered
    through the REAL jit wrapper so the artifact carries the real
    ``donate_argnums``. Wrapping a jitted fn in a second ``jax.jit``
    would instead verify the OUTER jit's (empty) donation — never do
    that. A plain callable (the fixture route) is wrapped once here;
    its donation promise must live on a jit of its own to be real.
    """
    import jax

    if isinstance(fn, functools.partial) and hasattr(fn.func, "lower"):
        return fn.func, fn.args, dict(fn.keywords)
    if hasattr(fn, "lower"):
        return fn, (), {}
    return jax.jit(fn), (), {}


def _lower(fn, args, kwargs):
    jfn, pre_args, pre_kwargs = _resolve_lowerable(fn)
    with warnings.catch_warnings():
        # donation-dropped warnings fire at lower time; the alias check
        # on the compiled artifact is the structured report of the same
        # fact, so the warning text itself is noise here
        warnings.simplefilter("ignore")
        return jfn.lower(*pre_args, *args,
                         **{**pre_kwargs, **dict(kwargs)})


def _compile(lowered):
    with warnings.catch_warnings():
        # donation-dropped warnings are exactly what hlo-donation-alias
        # reports as findings; the warning text itself is noise here
        warnings.simplefilter("ignore")
        return lowered.compile()


# ---------------------------------------------------------------------------
# per-artifact checks
# ---------------------------------------------------------------------------

_ALIAS_RE = re.compile(r"(?:may|must)-alias")
_RESULT_SHAPE_RE = re.compile(r"->\s*tensor<([0-9]+(?:x[0-9]+)*)x")
_LOC_RE = re.compile(r"loc\([^)]*\)")


def _check_donation_alias(case: ContractCase, compiled_text: str):
    """Count compiled input_output_alias entries against the donated
    input leaves. Count-based on purpose: under a mesh the header's
    entry_computation_layout prints per-device shapes, so shape
    matching against the declared (global) avals would misfire."""
    if not case.donate_argnums:
        return []
    import jax

    leaves = 0
    for argnum in case.donate_argnums:
        if argnum >= len(case.args):
            return [("hlo-contract",
                     f"donate_argnums entry {argnum} out of range for "
                     f"{len(case.args)} declared args")]
        leaves += len(jax.tree_util.tree_leaves(case.args[argnum]))
    header = compiled_text.splitlines()[0] if compiled_text else ""
    aliases = len(_ALIAS_RE.findall(header))
    if aliases < leaves:
        return [(
            "hlo-donation-alias",
            f"declared {leaves} donated input leaf(s) "
            f"(donate_argnums={tuple(case.donate_argnums)}) but the "
            f"compiled artifact carries {aliases} input_output_alias "
            f"entr{'y' if aliases == 1 else 'ies'} — XLA dropped the "
            f"alias and the donated buffer double-allocates on every "
            f"dispatch")]
    return []


def _shape_elements(dims: str) -> int:
    n = 1
    for d in dims.split("x"):
        n *= int(d)
    return n


def _check_materialize(case: ContractCase, lowered_text: str):
    """Scan the lowered StableHLO for forbidden ops at/above their
    element thresholds (result-tensor element count)."""
    out = []
    for op, min_elements in case.hlo.forbid_ops:
        needle = f"stablehlo.{op}"
        count = 0
        worst = None
        for line in lowered_text.splitlines():
            if needle not in line:
                continue
            m = _RESULT_SHAPE_RE.search(line)
            if not m:
                continue
            n = _shape_elements(m.group(1))
            if n >= min_elements:
                count += 1
                if worst is None or n > worst[1]:
                    worst = (m.group(1), n)
        if count:
            out.append((
                "hlo-materialize",
                f"lowered program contains {count} forbidden "
                f"'{op}' op(s) at/above {min_elements} elements "
                f"(largest tensor<{worst[0]}> = {worst[1]}) — the "
                f"working set materializes instead of being read in "
                f"place"))
    return out


def collective_counts(compiled_text: str) -> dict[str, int]:
    """Count collective ops in a compiled HLO text. ``-start`` forms
    count as the op; ``-done`` halves and operand references
    (``%all-reduce.5``) do not."""
    counts = {}
    for op in COLLECTIVE_OPS:
        pat = re.compile(r"(?<![-\w])" + re.escape(op)
                         + r"(?:-start)?\(")
        counts[op] = len(pat.findall(compiled_text))
    return counts


def _check_collectives(case: ContractCase, compiled_text: str):
    budget = case.hlo.collectives
    if budget is None:
        return []
    unknown = sorted(set(budget) - set(COLLECTIVE_OPS))
    if unknown:
        return [("hlo-contract",
                 f"collective budget names unknown op(s) {unknown}; "
                 f"known: {list(COLLECTIVE_OPS)}")]
    actual = collective_counts(compiled_text)
    out = []
    for op in COLLECTIVE_OPS:
        want = int(budget.get(op, 0))
        got = actual[op]
        if got != want:
            out.append((
                "hlo-collective-budget",
                f"compiled program has {got} '{op}' op(s), budget "
                f"declares {want} — GSPMD reshard insertion (or a "
                f"lost collective) changed the communication "
                f"pattern"))
    return out


def peak_stats(compiled) -> dict[str, int]:
    """argument/output/temp/alias bytes and the derived peak for one
    compiled artifact (the numbers HLO_BUDGETS.json snapshots)."""
    ma = compiled.memory_analysis()
    arg = int(ma.argument_size_in_bytes)
    out = int(ma.output_size_in_bytes)
    tmp = int(ma.temp_size_in_bytes)
    ali = int(ma.alias_size_in_bytes)
    return {"argument_bytes": arg, "output_bytes": out,
            "temp_bytes": tmp, "alias_bytes": ali,
            "peak_bytes": arg + out + tmp - ali}


def _check_peak(case: ContractCase, stats: dict[str, int] | None):
    budget = case.hlo.peak_bytes
    if budget is None or stats is None:
        return []
    peak = stats["peak_bytes"]
    if peak > budget:
        return [(
            "hlo-peak-memory",
            f"compiled peak {peak} bytes (argument "
            f"{stats['argument_bytes']} + output "
            f"{stats['output_bytes']} + temp {stats['temp_bytes']} − "
            f"aliased {stats['alias_bytes']}) exceeds the declared "
            f"budget of {budget} bytes — a working-set/materialization "
            f"regression that would OOM at production scale")]
    return []


def _program_digest(lowered_text: str) -> str:
    # strip MLIR location metadata so two variants differ only if the
    # program differs, not if a declaration moved by a line
    return hashlib.sha1(
        _LOC_RE.sub("", lowered_text).encode()).hexdigest()


def _check_program_cache(case: ContractCase):
    spec = case.hlo
    if spec.expected_programs is None:
        return []
    digests: dict[str, list[str]] = {}
    for variant in spec.variants:
        label, fn, vargs = variant[0], variant[1], variant[2]
        vkwargs = variant[3] if len(variant) > 3 else {}
        try:
            text = _lower(fn, vargs, vkwargs).as_text()
        except Exception as exc:
            return [("hlo-contract",
                     f"program-cache variant '{label}' failed to "
                     f"lower: {type(exc).__name__}: {_oneline(exc)}")]
        digests.setdefault(_program_digest(text), []).append(label)
    distinct = len(digests)
    if distinct != spec.expected_programs:
        shared = [labels for labels in digests.values()
                  if len(labels) > 1]
        detail = (f"; variants sharing a program: {shared}" if shared
                  else "")
        return [(
            "hlo-program-cache",
            f"{len(spec.variants)} declared bucket-table variant(s) "
            f"lower to {distinct} distinct program(s), contract "
            f"declares {spec.expected_programs} — the bucket "
            f"cross-product drifted from its declaration (program-"
            f"cache explosion or redundant bucket){detail}")]
    return []


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------


def check_modules(modules=None, labels=None, only_rules=None,
                  report=None):
    """Collect the hlo-bearing contract cases and verify their
    artifacts. Returns ``(findings, checked_paths, skips)`` with the
    same shapes and suppression semantics as shardcheck.check_modules.

    ``labels`` (set of case labels) and ``only_rules`` (set of rule
    names) narrow the run — a tripwire test that only needs one case's
    lowering should not pay for eighteen compiles. Artifacts are built
    lazily from the selection: a run that only needs ``hlo-materialize``
    never compiles, one that only needs ``hlo-program-cache`` only
    lowers the variants.

    ``report`` (a dict) collects per-case :func:`peak_stats` under the
    case context key — the ``--budgets`` snapshot route.
    """
    entries, findings = collect(modules)
    checked: list[pathlib.Path] = []
    seen_paths: set[pathlib.Path] = set()
    skips: list[tuple[str, str]] = []
    suppressions: dict[pathlib.Path, Suppressions] = {}
    # paths whose contracts produced at least one hlo-bearing case /
    # at least one skip — a module with neither has rotted out of the
    # pass and must say so rather than silently passing
    specced: set[pathlib.Path] = set()
    skipped_paths: set[pathlib.Path] = set()

    def selected(rule: str) -> bool:
        return only_rules is None or rule in only_rules

    def suppressed(path: pathlib.Path, rule: str, line: int) -> bool:
        if path not in suppressions:
            try:
                suppressions[path] = Suppressions(path.read_text())
            except OSError:
                suppressions[path] = Suppressions("")
        return suppressions[path].is_suppressed(rule, line)

    def emit(path, lineno, context, results):
        for rule, message in results:
            if not suppressed(path, rule, lineno):
                findings.append(Finding(rule, rel(path), lineno,
                                        message, context))

    for con, path in entries:
        if path not in seen_paths:
            seen_paths.add(path)
            checked.append(path)
        try:
            produced = con.factory()
        except ContractSkip as skip:
            skips.append((con.name, str(skip)))
            skipped_paths.add(path)
            continue
        except Exception as exc:
            emit(path, con.lineno, con.name,
                 [("hlo-contract",
                   f"contract factory raised {type(exc).__name__}: "
                   f"{_oneline(exc)}")])
            continue
        cases = produced if isinstance(produced, (list, tuple)) \
            else [produced]
        for case in cases:
            if not isinstance(case, ContractCase) or case.hlo is None:
                continue
            specced.add(path)
            if labels is not None and case.label not in labels:
                continue
            context = f"{con.name}:{case.label}" if case.label \
                else con.name
            spec = case.hlo
            results = []

            if selected("hlo-program-cache"):
                results += _check_program_cache(case)

            need_compile = case.fn is not None and (
                (bool(case.donate_argnums)
                 and selected("hlo-donation-alias"))
                or (spec.collectives is not None
                    and selected("hlo-collective-budget"))
                or (spec.peak_bytes is not None
                    and (selected("hlo-peak-memory")
                         or report is not None)))
            need_lower = need_compile or (
                case.fn is not None and bool(spec.forbid_ops)
                and selected("hlo-materialize"))

            lowered = compiled = None
            if need_lower:
                try:
                    lowered = _lower(case.fn, case.args, case.kwargs)
                except ContractSkip as skip:
                    skips.append((context, str(skip)))
                    skipped_paths.add(path)
                    emit(path, con.lineno, context, results)
                    continue
                except Exception as exc:
                    results.append((
                        "hlo-contract",
                        f"lowering failed: {type(exc).__name__}: "
                        f"{_oneline(exc)}"))
            if lowered is not None and spec.forbid_ops \
                    and selected("hlo-materialize"):
                results += _check_materialize(case, lowered.as_text())
            if lowered is not None and need_compile:
                try:
                    compiled = _compile(lowered)
                except Exception as exc:
                    results.append((
                        "hlo-contract",
                        f"compilation failed: {type(exc).__name__}: "
                        f"{_oneline(exc)}"))
            if compiled is not None:
                compiled_text = compiled.as_text()
                if selected("hlo-donation-alias"):
                    results += _check_donation_alias(case,
                                                     compiled_text)
                if selected("hlo-collective-budget"):
                    results += _check_collectives(case, compiled_text)
                stats = None
                try:
                    stats = peak_stats(compiled)
                except Exception as exc:
                    # memory_analysis is backend-dependent; its absence
                    # is an environment note, not a contract breach
                    skips.append((context,
                                  f"memory_analysis unavailable: "
                                  f"{_oneline(exc)}"))
                if stats is not None:
                    if selected("hlo-peak-memory"):
                        results += _check_peak(case, stats)
                    if report is not None:
                        report[context] = dict(
                            stats, budget_bytes=spec.peak_bytes)
            emit(path, con.lineno, context, results)

    if labels is None and only_rules is None:
        for path in sorted(seen_paths - specced - skipped_paths):
            findings.append(Finding(
                "hlo-contract", rel(path), 1,
                "module's contracts declare no HloSpec — the "
                "post-lowering pass no longer covers it"))
    return findings, checked, skips


# ---------------------------------------------------------------------------
# subprocess runner (what the main CLI and bench preflight call)
# ---------------------------------------------------------------------------


def spawn_worker(modules=None, baseline=None):
    """Start the hlocheck worker subprocess (same CPU-platform /
    8-virtual-device env contract as shardcheck.spawn_worker; spawn
    early, :func:`finish_worker` late — compiling is the slowest pass
    in the lane, so the main CLI overlaps it with everything else)."""
    import subprocess

    cmd = [sys.executable, "-m",
           "copilot_for_consensus_tpu.analysis.hlocheck", "--json"]
    if modules:
        cmd += ["--modules", ",".join(str(m) for m in modules)]
    if baseline:
        cmd += ["--baseline", str(baseline)]
    else:
        cmd += ["--no-baseline"]
    return subprocess.Popen(cmd, cwd=ROOT, env=worker_env(),
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def run_worker(modules=None, baseline=None, timeout: float = 900.0):
    """spawn + finish in one call (the bench preflight route)."""
    return finish_worker(spawn_worker(modules, baseline), timeout)


def check_semantic(modules=None, timeout: float = 900.0, proc=None):
    """Run the post-lowering pass in a subprocess (or collect an
    already-spawned ``proc``). Returns ``(findings, checked_paths)``;
    an infra failure is itself an ``hlo-contract`` finding, never a
    silent pass."""
    self_path = rel(pathlib.Path(__file__))
    if proc is None:
        proc = spawn_worker(modules)
    data, detail = finish_worker(proc, timeout)
    if data is None:
        return [Finding("hlo-contract", self_path, 1, detail)], []
    for ctx, reason in data.get("skips", ()):
        print(f"jaxlint: hlocheck skipped {ctx}: {reason}",
              file=sys.stderr)
    findings = [Finding(d["rule"], d["path"], d["line"], d["message"],
                        d.get("context", ""))
                for d in data.get("findings", ())]
    checked = [ROOT / p for p in data.get("checked", ())]
    return findings, checked


def write_budgets(report: dict, path: pathlib.Path) -> None:
    """Write the per-dispatch memory snapshot (the HLO_BUDGETS.json
    artifact future PRs diff the way BENCH_*.json diffs throughput)."""
    payload = {
        "generated_by": "python -m copilot_for_consensus_tpu.analysis"
                        ".hlocheck --budgets <path>",
        "device_count": 8,
        "platform": "cpu (virtual 8-device; bytes are per-device "
                    "logical buffer sizes from compiled"
                    ".memory_analysis())",
        "peak_definition": "argument_bytes + output_bytes + temp_bytes"
                           " - alias_bytes",
        "cases": {k: report[k] for k in sorted(report)},
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2, sort_keys=False)
                    + "\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m copilot_for_consensus_tpu.analysis.hlocheck",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--modules",
                    help="comma list of dotted modules or .py paths "
                         "(default: contracts.HLO_CONTRACT_MODULES)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON line")
    ap.add_argument("--budgets", metavar="PATH",
                    help="also write the per-dispatch memory snapshot "
                         "(docs/artifacts/HLO_BUDGETS.json) here")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="apply this jaxlint baseline file (entries "
                         "with hlo-* rules) before reporting")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything); "
                         "the main CLI spawns the worker with this, "
                         "as it applies the baseline itself")
    args = ap.parse_args(argv)

    from copilot_for_consensus_tpu.analysis.shardcheck import (
        _force_cpu_env,
    )

    _force_cpu_env(os.environ)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception as exc:
        msg = f"jax unavailable: {type(exc).__name__}: {_oneline(exc)}"
        if args.json:
            print(json.dumps({"findings": [
                {"rule": "hlo-contract", "path": "jax", "line": 1,
                 "message": msg, "context": ""}], "checked": [],
                "skips": []}))
        else:
            print(msg, file=sys.stderr)
        return 1

    modules = [m.strip() for m in args.modules.split(",")
               if m.strip()] if args.modules else None
    report: dict = {}
    findings, checked, skips = check_modules(modules, report=report)
    if args.budgets:
        write_budgets(report, pathlib.Path(args.budgets))
    if not args.no_baseline:
        from copilot_for_consensus_tpu.analysis.base import (
            apply_baseline,
            load_baseline,
        )

        entries, errors = load_baseline(pathlib.Path(args.baseline))
        for err in errors:
            print(f"hlocheck: {err}", file=sys.stderr)
        if not errors:
            entries = [e for e in entries
                       if str(e.get("rule", "")).startswith("hlo-")]
            findings, _ = apply_baseline(findings, entries)

    if args.json:
        print(json.dumps({
            "findings": [{"rule": f.rule, "path": f.path, "line": f.line,
                          "message": f.message, "context": f.context}
                         for f in findings],
            "checked": [rel(p) for p in checked],
            "skips": list(skips),
            "report": report,
        }))
    else:
        for ctx, reason in skips:
            print(f"hlocheck: skipped {ctx}: {reason}", file=sys.stderr)
        for f in findings:
            print(f.render())
        verdict = "CLEAN" if not findings \
            else f"{len(findings)} finding(s)"
        print(f"hlocheck: {len(checked)} contract module(s): {verdict}",
              file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
