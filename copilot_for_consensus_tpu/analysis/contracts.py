"""shardcheck contract registry: modules declare their jitted entrypoints.

The syntactic jaxlint rules (``jax_rules.py``) never import the code
they check; the semantic ``shard`` group (``shardcheck.py``) does the
opposite — it abstract-interprets the REAL jitted programs with
``jax.eval_shape`` under the real declared meshes, on CPU. The bridge
between the two worlds is this registry: each checked module keeps a
``SHARDCHECK_CONTRACTS`` table of *contract factories* declaring its
entrypoints with representative ``ShapeDtypeStruct`` inputs and mesh
configs.

Design constraints, in order:

* **Importing this module must stay free.** No jax at import time —
  engine/parallel modules import :func:`checkable` at module top, and
  the analysis CLI imports the registry to know what to check even on a
  machine without jax. All jax objects are built lazily inside factory
  bodies, which only run when shardcheck executes them.
* **Declaring must stay cheap.** A factory is registered, not called,
  at import time; a contract costs one decorated function per module.
* **The declaration is the contract.** ``donate_argnums``, the kv-cache
  group, the padding-bucket table are restated here ON PURPOSE: the
  declaration says what the module *promises* (this buffer aliases an
  output; these four programs share one KV layout; these buckets cover
  these shapes) and shardcheck verifies the traced program keeps the
  promise. Drift between promise and program is exactly the bug class
  the pass exists to catch.

Declaring a contract::

    from copilot_for_consensus_tpu.analysis.contracts import (
        ContractCase, checkable,
    )

    @checkable("my-program")
    def _shardcheck_my_program():
        import jax, jax.numpy as jnp
        S = jax.ShapeDtypeStruct
        return ContractCase(
            fn=my_jitted_fn,
            args=(S((4, 128), jnp.int32), ...),
            donate_argnums=(1,),
        )

A factory may return one :class:`ContractCase` or a list of them (use
``label`` to tell them apart), and may raise :class:`ContractSkip` when
the environment cannot host the check (e.g. too few virtual devices —
see :func:`require_devices`). Suppression: a ``# jaxlint:
disable=<rule>`` comment on (or directly above) the ``@checkable`` line
covers every finding the contract emits, and the committed baseline
matches on (rule, path, context=contract name, message) as usual.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Sequence

#: every module whose SHARDCHECK_CONTRACTS table the semantic pass runs
#: by default (``python -m copilot_for_consensus_tpu.analysis.shardcheck``).
#: Keep in sync with docs/STATIC_ANALYSIS.md.
CONTRACT_MODULES = (
    "copilot_for_consensus_tpu.parallel.mesh",
    "copilot_for_consensus_tpu.parallel.sharding",
    "copilot_for_consensus_tpu.parallel.ring",
    "copilot_for_consensus_tpu.parallel.ulysses",
    "copilot_for_consensus_tpu.parallel.pipeline",
    "copilot_for_consensus_tpu.engine.generation",
    "copilot_for_consensus_tpu.engine.prefix_cache",
    "copilot_for_consensus_tpu.engine.scheduler",
    "copilot_for_consensus_tpu.engine.longctx",
    "copilot_for_consensus_tpu.vectorstore.tpu",
)

#: modules whose contract cases (additionally) declare HLO lowering
#: specs — the registry the POST-lowering ``hlo`` group
#: (``analysis/hlocheck.py``) walks by default. A subset of the serving
#: plane on purpose: every case here is lowered AND compiled per run,
#: so membership is the compile-time budget of the pass. Keep in sync
#: with docs/STATIC_ANALYSIS.md.
HLO_CONTRACT_MODULES = (
    "copilot_for_consensus_tpu.engine.generation",
    "copilot_for_consensus_tpu.engine.prefix_cache",
    "copilot_for_consensus_tpu.engine.roles",
    "copilot_for_consensus_tpu.ops.paged_attention",
    "copilot_for_consensus_tpu.vectorstore.tpu",
)


class ContractSkip(Exception):
    """Raised by a factory when the environment cannot host the check
    (too few virtual devices, missing optional dep). The case is
    reported as skipped, never as a finding."""


def require_devices(n: int) -> None:
    """Factories that build real meshes call this first; the shardcheck
    worker always runs under ``--xla_force_host_platform_device_count=8``
    so skips only happen in ad-hoc in-process use."""
    import jax

    have = len(jax.devices())
    if have < n:
        raise ContractSkip(
            f"needs {n} devices, have {have} (run under XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n})")


@dataclass
class HloSpec:
    """Budgets a case declares against its LOWERED/COMPILED artifact —
    the post-lowering ``hlo`` rule family (``analysis/hlocheck.py``).
    Where shardcheck verifies the trace, hlocheck verifies what XLA
    actually emitted; each field feeds one rule:

    * ``forbid_ops`` (sequence of ``(stablehlo_op, min_elements)``) →
      the lowered StableHLO must contain no instance of the named op
      producing a result at/above ``min_elements`` elements
      (``hlo-materialize``). This is how the kernel route pins "no
      pool-working-set gather" as a contract instead of a trace-spy:
      the threshold sits above the largest legitimate small gather
      (embedding lookups) and below the working-set size. Checked on
      the pre-optimization lowering so XLA fusion cannot hide the op.
    * ``collectives`` (mapping op name → exact count) → the compiled
      program's all-reduce/all-gather/reduce-scatter/collective-permute
      /all-to-all counts must match exactly; ops absent from the
      mapping must be absent from the program
      (``hlo-collective-budget``). Catches GSPMD reshard insertion of
      the RoPE-miscompile class.
    * ``peak_bytes`` → ``compiled.memory_analysis()`` peak
      (argument + output + temp − aliased) must not exceed the budget
      (``hlo-peak-memory``). Budgets carry deliberate ~2× headroom
      over the measured tiny-config peak: they gate structural
      blowups (a materialized working set), not byte-level drift —
      byte-level drift is what docs/artifacts/HLO_BUDGETS.json diffs.
    * ``variants`` (sequence of ``(label, fn, args)`` or
      ``(label, fn, args, kwargs)``) + ``expected_programs`` → lowering
      every variant must yield exactly ``expected_programs`` distinct
      programs (``hlo-program-cache``). Declare the expected count as
      a literal cross-product so widening a bucket table without
      updating the declaration turns the lane red.

    ``donate_argnums`` needs no field here: any hlo-bearing case that
    declares ``donate_argnums`` is automatically compiled and its
    ``input_output_alias`` entries counted against the donated leaves
    (``hlo-donation-alias``).
    """

    forbid_ops: Sequence[tuple] = ()
    collectives: Mapping[str, int] | None = None
    peak_bytes: int | None = None
    variants: Sequence[tuple] = ()
    expected_programs: int | None = None


@dataclass
class ContractCase:
    """One verifiable claim about one program. Every field is optional;
    a case only exercises the rule families its fields feed:

    * ``fn``/``args``/``kwargs`` → the program is traced with
      ``jax.eval_shape`` (``shard-collective`` on an axis/mesh trace
      failure — an unbound collective axis name fails exactly here).
      Bind static jit args concretely with ``functools.partial``.
    * ``donate_argnums`` → every donated input leaf must have a
      shape/dtype-matching output leaf, or XLA silently drops the alias
      and the buffer double-allocates (``shard-donation``).
    * ``rules``+``mesh`` → every rule target must be a real mesh axis
      (``shard-rule-axis``).
    * ``logical`` (sequence of ``(label, aval_tree, logical_axes_tree)``)
      +``rules``+``mesh`` → every spec'd dimension must divide evenly by
      its mesh axes (``shard-divisibility``).
    * ``kv_group``+``kv_caches`` (sequence of ``(label, pytree)``) → all
      cases sharing a group must agree on one KV layout signature
      ``(n_layers, n_kv_heads, head_dim, dtype)`` extracted from the
      ``[L, *, Hkv, *, Dh]`` cache convention (``shard-kv-layout``).
    * ``buckets``+``bucket_covers`` → every declared input length must
      fit the padding-bucket table, bounding retrace count
      (``shard-bucket``). The table need not be prompt padding: the
      engine's verify contract declares its speculative draft-length
      set (token width per verify program) through the same fields.
    * ``hlo`` (an :class:`HloSpec`) → the case is additionally lowered
      and compiled by the post-lowering ``hlo`` group; see
      :class:`HloSpec` for the rule-by-rule mapping.
    """

    label: str = ""
    fn: Callable[..., Any] | None = None
    args: tuple = ()
    kwargs: Mapping[str, Any] = field(default_factory=dict)
    donate_argnums: Sequence[int] = ()
    mesh: Any = None
    rules: Mapping[str, Any] | None = None
    logical: Sequence[tuple] = ()
    kv_group: str = ""
    kv_caches: Sequence[tuple] = ()
    buckets: Sequence[int] | None = None
    bucket_covers: Sequence[int] = ()
    hlo: HloSpec | None = None


@dataclass(frozen=True)
class Contract:
    """A registered (but not yet materialized) contract declaration."""

    name: str
    factory: Callable[[], Any]
    lineno: int               # declaration line, for inline suppression
    module: str = ""          # dotted module of the declaring factory


def contract(name: str, factory: Callable[[], Any]) -> Contract:
    """Build a Contract entry for an explicit SHARDCHECK_CONTRACTS
    table (fixtures use this; package modules use ``@checkable``)."""
    code = getattr(factory, "__code__", None)
    return Contract(name, factory,
                    code.co_firstlineno if code is not None else 1,
                    getattr(factory, "__module__", "") or "")


def checkable(name: str | None = None):
    """Decorator: register a contract factory in the defining module's
    ``SHARDCHECK_CONTRACTS`` table (created on first use)."""

    def deco(fn: Callable[[], Any]) -> Callable[[], Any]:
        entry = contract(name or fn.__name__.lstrip("_"), fn)
        mod = sys.modules.get(fn.__module__)
        if mod is not None:
            table = getattr(mod, "SHARDCHECK_CONTRACTS", None)
            if table is None:
                table = []
                mod.SHARDCHECK_CONTRACTS = table
            table.append(entry)
        return fn

    return deco
