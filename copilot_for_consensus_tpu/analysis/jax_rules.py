"""JAX/TPU-aware rules: the invariants that keep the serving engine fast.

Five rules share one per-module model of "which functions are traced":

* ``host-sync-in-jit`` — ``.item()`` / ``np.asarray`` / ``jax.device_get``
  / ``.block_until_ready()`` reachable inside a jitted / shard_mapped /
  scan-body function. Each is a device→host round trip: inside a traced
  hot path it either breaks tracing outright or (worse) silently turns a
  fused dispatch into a per-step sync — the exact failure mode the
  engine's windowed-decode design exists to avoid (docs/PERF.md).
* ``retrace-hazard`` — Python ``if``/``while`` branching on a
  tracer-derived value (ConcretizationTypeError at best, a retrace per
  distinct value at worst), and unhashable static-arg defaults.
* ``donation`` — a jitted function taking a cache/pool device buffer
  without ``donate_argnums``. An undonated KV cache double-allocates on
  every dispatch (2x cache HBM) — the engine donates its cache in every
  decode/admit program (engine/generation.py).
* ``prng-reuse`` — one PRNG key consumed by two random ops without an
  intervening ``jax.random.split`` (correlated samples), or a key
  consumed again after being split.
* ``collective-axis`` — a collective (``psum``/``ppermute``/...) naming
  an axis, as a string literal, that no mesh/shard_map declaration in
  the module binds.

Tracing contexts are found statically: ``@jax.jit`` /
``@functools.partial(jax.jit, ...)`` decorators, ``jax.jit(fn, ...)`` /
``shard_map(fn, ...)`` call sites, loop-body functions handed to
``jax.lax.scan``/``fori_loop``/``while_loop``/``cond``/``switch``, every
function lexically nested in a context, and every same-module function a
context calls by name. Cross-module propagation is out of scope (v1):
the engine's programs and their same-file helpers are covered; shared
layers in ``models/`` are exercised through the engine's fixtures.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from copilot_for_consensus_tpu.analysis.base import (
    Finding,
    Module,
    dotted_name,
    int_constants,
    kw,
    str_constants,
)

JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
SHARD_NAMES = {"shard_map", "jax.experimental.shard_map.shard_map",
               "jax.shard_map"}
PARTIAL_NAMES = {"functools.partial", "partial"}
#: structured-control-flow combinators whose function args trace
LOOP_NAMES = {"jax.lax.scan", "lax.scan", "jax.lax.fori_loop",
              "lax.fori_loop", "jax.lax.while_loop", "lax.while_loop",
              "jax.lax.cond", "lax.cond", "jax.lax.switch", "lax.switch",
              "jax.lax.map", "lax.map", "jax.lax.associative_scan",
              "lax.associative_scan"}

#: positional-param name tokens that mark a large mutable device buffer
#: on the serving hot path (the KV slot cache, the prefix-cache block
#: pool). Token match on "_"-split names: ``cache``, ``kv_cache``,
#: ``cache_k``, ``pool_k`` hit; ``kv_len`` (a static int) does not.
BUFFER_TOKENS = {"cache", "pool"}

#: calls whose result is never a tracer regardless of arguments
UNTAINT_CALLS = {"len", "isinstance", "hasattr", "getattr", "type", "id",
                 "callable", "repr", "str.format"}
#: attribute reads that are static even on a tracer
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "itemsize"}

#: device→host sync surfaces (method names / dotted callables)
SYNC_METHODS = {"item", "tolist", "block_until_ready"}
SYNC_CALLS = {"jax.device_get", "jax.block_until_ready",
              "np.asarray", "np.array", "numpy.asarray", "numpy.array",
              "np.frombuffer", "numpy.frombuffer"}

_PRNG_PREFIXES = ("jax.random.", "random.", "jrandom.", "jr.")
_PRNG_NONCONSUMING = {"split", "fold_in", "PRNGKey", "key", "key_data",
                      "wrap_key_data", "key_impl", "clone"}
#: repo idiom: ``sample(logits, key, cfg)`` draws from the key
SAMPLE_LIKE = {"sample"}

COLLECTIVES = {"psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
               "ppermute": 1, "pshuffle": 1, "all_gather": 1,
               "all_to_all": 1, "psum_scatter": 1, "pcast": 1,
               "axis_index": 0, "pbroadcast": 1}
_COLLECTIVE_PREFIXES = ("jax.lax.", "lax.")


@dataclass
class _Reg:
    """One jit/shard_map registration of a function."""

    kind: str                       # "jit" | "shard_map" | "loop-body"
    line: int
    static_names: set[str] = field(default_factory=set)
    static_nums: set[int] = field(default_factory=set)
    donated_nums: set[int] = field(default_factory=set)
    donated_names: set[str] = field(default_factory=set)
    bound_names: set[str] = field(default_factory=set)  # partial kwargs


@dataclass
class _Fn:
    node: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda
    qualname: str
    regs: list[_Reg] = field(default_factory=list)
    in_context: bool = False

    @property
    def pos_params(self) -> list[str]:
        """FULL positional list, self/cls included — jax's own
        donate_argnums/static_argnums count self on methods, so indices
        must line up with the real signature."""
        a = self.node.args
        return [p.arg for p in (a.posonlyargs + a.args)]

    @property
    def all_params(self) -> list[str]:
        return self.pos_params + [p.arg for p in self.node.args.kwonlyargs]

    def static_params(self) -> set[str]:
        pos = self.pos_params
        out: set[str] = set()
        for r in self.regs:
            out |= r.static_names | r.bound_names
            out |= {pos[i] for i in r.static_nums if i < len(pos)}
        return out


def _reg_from_call(call: ast.Call, kind: str) -> _Reg:
    reg = _Reg(kind, call.lineno)
    for name, bucket in (("static_argnames", reg.static_names),
                         ("donate_argnames", reg.donated_names)):
        val = kw(call, name)
        if val is not None:
            bucket.update(str_constants(val))
    for name, bucket in (("static_argnums", reg.static_nums),
                         ("donate_argnums", reg.donated_nums)):
        val = kw(call, name)
        if val is not None:
            bucket.update(int_constants(val))
    return reg


class _ModuleModel:
    """Functions, jit registrations, and jit-reachable contexts."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.fns: dict[ast.AST, _Fn] = {}
        self.by_name: dict[str, list[_Fn]] = {}
        assert mod.tree is not None
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                fn = _Fn(node, mod.qualname(node))
                self.fns[node] = fn
                if not isinstance(node, ast.Lambda):
                    self.by_name.setdefault(node.name, []).append(fn)
        self._collect_decorators()
        self._collect_call_sites(mod.tree)
        self._propagate()

    # -- registration discovery ---------------------------------------

    def _collect_decorators(self) -> None:
        def kind_of(head: str) -> str:
            return "jit" if head in JIT_NAMES else "shard_map"

        for node, fn in self.fns.items():
            if isinstance(node, ast.Lambda):
                continue
            for deco in node.decorator_list:
                head = dotted_name(deco)
                if head in JIT_NAMES | SHARD_NAMES:
                    fn.regs.append(_Reg(kind_of(head), deco.lineno))
                elif isinstance(deco, ast.Call):
                    head = dotted_name(deco.func)
                    if head in JIT_NAMES | SHARD_NAMES:
                        fn.regs.append(
                            _reg_from_call(deco, kind_of(head)))
                    elif (head in PARTIAL_NAMES and deco.args):
                        inner = dotted_name(deco.args[0])
                        if inner in JIT_NAMES | SHARD_NAMES:
                            fn.regs.append(
                                _reg_from_call(deco, kind_of(inner)))

    def _resolve(self, node: ast.AST) -> tuple[_Fn | None, set[str]]:
        """A function argument at a jit/shard_map/loop call site: a bare
        Name, a lambda, or functools.partial(Name, **static)."""
        if isinstance(node, ast.Lambda):
            return self.fns.get(node), set()
        if isinstance(node, ast.Name):
            cands = self.by_name.get(node.id, [])
            return (cands[0] if cands else None), set()
        if (isinstance(node, ast.Call)
                and dotted_name(node.func) in PARTIAL_NAMES and node.args):
            fn, _ = self._resolve(node.args[0])
            bound = {k.arg for k in node.keywords if k.arg}
            return fn, bound
        return None, set()

    def _collect_call_sites(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            head = dotted_name(node.func)
            if head in JIT_NAMES | SHARD_NAMES and node.args:
                fn, bound = self._resolve(node.args[0])
                if fn is not None:
                    reg = _reg_from_call(
                        node, "jit" if head in JIT_NAMES else "shard_map")
                    reg.bound_names |= bound
                    fn.regs.append(reg)
            elif head in LOOP_NAMES:
                for arg in node.args:
                    fn, bound = self._resolve(arg)
                    if fn is not None:
                        reg = _Reg("loop-body", node.lineno)
                        reg.bound_names |= bound
                        fn.regs.append(reg)

    # -- reachability --------------------------------------------------

    def _propagate(self) -> None:
        work = [fn for fn in self.fns.values() if fn.regs]
        for fn in work:
            fn.in_context = True
        while work:
            fn = work.pop()
            # lexically nested defs trace with their parent
            for node in ast.walk(fn.node):
                sub = self.fns.get(node)
                if sub is not None and not sub.in_context:
                    sub.in_context = True
                    work.append(sub)
            # same-module functions called by bare name
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name):
                    for callee in self.by_name.get(node.func.id, []):
                        if not callee.in_context:
                            callee.in_context = True
                            work.append(callee)

    def contexts(self):
        return [fn for fn in self.fns.values() if fn.in_context]

    def own_body(self, fn: _Fn):
        """Walk fn's body but stop at nested function boundaries (each
        nested def is its own context and reports its own findings)."""
        body = (fn.node.body if isinstance(fn.node.body, list)
                else [fn.node.body])
        stack: list[ast.AST] = list(body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# taint: "could this expression hold a tracer-dependent value?"
# ---------------------------------------------------------------------------


def _tainted(node: ast.AST, names: set[str]) -> bool:
    if isinstance(node, ast.Name):
        return node.id in names
    if isinstance(node, ast.Constant):
        return False
    if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        # `x is None` resolves at trace time (a tracer is never None):
        # a structure check, not a value branch
        return False
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return _tainted(node.value, names)
    if isinstance(node, ast.Call):
        head = dotted_name(node.func)
        if head in UNTAINT_CALLS:
            return False
        if head.endswith("axis_index"):     # per-device varying value
            return True
        return any(_tainted(a, names) for a in node.args) or any(
            _tainted(k.value, names) for k in node.keywords)
    if isinstance(node, ast.Lambda):
        return False
    return any(_tainted(c, names) for c in ast.iter_child_nodes(node)
               if isinstance(c, ast.expr))


def _assign_names(target: ast.expr) -> list[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: list[str] = []
        for el in target.elts:
            out.extend(_assign_names(el))
        return out
    return []


class _TaintWalk:
    """Statement-order taint pass over one context's own body; collects
    retrace-hazard (tainted if/while tests) and host-sync (int/float on
    tainted values) findings along the way."""

    def __init__(self, mod: Module, fn: _Fn):
        self.mod = mod
        self.fn = fn
        self.findings: list[Finding] = []
        statics = fn.static_params()
        # Only functions with a DIRECT registration (jit/shard_map
        # decorator or call site, or a lax.scan/cond body) have params
        # we KNOW are tracers. Contexts reached through the call graph
        # or lexical nesting often receive static closure values — their
        # params stay untainted (axis_index-derived values still taint).
        self.tainted: set[str] = (
            {p for p in fn.all_params
             if p not in statics and p not in ("self", "cls")}
            if fn.regs else set())

    def run(self) -> list[Finding]:
        body = (self.fn.node.body
                if isinstance(self.fn.node.body, list)
                else [])           # a Lambda body has no statements
        self._stmts(body)
        return self.findings

    def _stmts(self, stmts: list[ast.stmt]) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return                            # nested contexts walk alone
        # cast-scan only the expressions evaluated AT this statement —
        # compound bodies are scanned statement-by-statement below, with
        # the taint state current at each one
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_casts(stmt.test)
        elif isinstance(stmt, ast.For):
            self._scan_casts(stmt.iter)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_casts(item.context_expr)
        elif not isinstance(stmt, (ast.Try, ast.ClassDef)):
            self._scan_casts(stmt)
        if isinstance(stmt, (ast.If, ast.While)):
            if _tainted(stmt.test, self.tainted):
                word = "if" if isinstance(stmt, ast.If) else "while"
                self._emit(
                    "retrace-hazard", stmt,
                    f"Python `{word}` branches on a traced value — a "
                    "retrace per distinct value (or a Concretization"
                    "TypeError); use jnp.where/lax.cond/lax.while_loop, "
                    "or mark the operand static")
            before = set(self.tainted)
            self._stmts(stmt.body)
            after_body = self.tainted
            self.tainted = set(before)
            self._stmts(stmt.orelse)
            self.tainted |= after_body
        elif isinstance(stmt, ast.For):
            for n in _assign_names(stmt.target):
                if _tainted(stmt.iter, self.tainted):
                    self.tainted.add(n)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._stmts(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body)
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
        elif isinstance(stmt, ast.Assign):
            val = _tainted(stmt.value, self.tainted)
            for t in stmt.targets:
                for n in _assign_names(t):
                    (self.tainted.add if val
                     else self.tainted.discard)(n)
        elif isinstance(stmt, ast.AugAssign):
            if _tainted(stmt.value, self.tainted):
                for n in _assign_names(stmt.target):
                    self.tainted.add(n)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            val = _tainted(stmt.value, self.tainted)
            for n in _assign_names(stmt.target):
                (self.tainted.add if val else self.tainted.discard)(n)

    def _scan_casts(self, root: ast.AST) -> None:
        """int()/float()/bool() on a tracer force a host sync; on static
        values they are fine — so only tainted operands flag. Nested
        function subtrees are skipped entirely (they walk alone)."""
        stack = [root]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue                      # do not descend
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in ("int", "float", "bool")
                    and len(node.args) == 1
                    and _tainted(node.args[0], self.tainted)):
                self._emit(
                    "host-sync-in-jit", node,
                    f"`{node.func.id}()` on a traced value forces a "
                    "device→host sync (or a ConcretizationTypeError) "
                    "inside a traced function")
            stack.extend(ast.iter_child_nodes(node))

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        f = self.mod.finding(rule, node, message, context=self.fn.qualname)
        if f is not None:
            self.findings.append(f)


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def _check_host_sync(mod: Module, model: _ModuleModel) -> list[Finding]:
    out: list[Finding] = []
    for fn in model.contexts():
        for node in model.own_body(fn):
            if not isinstance(node, ast.Call):
                continue
            head = dotted_name(node.func)
            msg = None
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in SYNC_METHODS):
                msg = (f"`.{node.func.attr}()` is a device→host sync "
                       "inside a traced function")
            elif head in SYNC_CALLS:
                msg = (f"`{head}()` materializes on the host inside a "
                       "traced function — hoist it out of the jitted "
                       "program")
            if msg:
                f = mod.finding("host-sync-in-jit", node, msg,
                                context=fn.qualname)
                if f is not None:
                    out.append(f)
    return out


def _check_taint_rules(mod: Module, model: _ModuleModel) -> list[Finding]:
    out: list[Finding] = []
    for fn in model.contexts():
        if isinstance(fn.node, ast.Lambda):
            continue
        out.extend(_TaintWalk(mod, fn).run())
    # unhashable static-arg defaults (retrace hazard family)
    for fn in model.contexts():
        statics = fn.static_params()
        if not statics or isinstance(fn.node, ast.Lambda):
            continue
        a = fn.node.args
        params = a.posonlyargs + a.args
        defaults = [None] * (len(params) - len(a.defaults)) + list(
            a.defaults)
        pairs = list(zip(params, defaults)) + list(
            zip(a.kwonlyargs, a.kw_defaults))
        for p, d in pairs:
            if p.arg in statics and isinstance(
                    d, (ast.List, ast.Dict, ast.Set)):
                f = mod.finding(
                    "retrace-hazard", d,
                    f"static arg '{p.arg}' of '{fn.node.name}' defaults "
                    "to an unhashable container — jit static args must "
                    "hash (use a tuple/frozen value)",
                    context=fn.qualname)
                if f is not None:
                    out.append(f)
    return out


def _check_donation(mod: Module, model: _ModuleModel) -> list[Finding]:
    out: list[Finding] = []
    for fn in model.fns.values():
        if isinstance(fn.node, ast.Lambda):
            continue
        pos = fn.pos_params
        for reg in fn.regs:
            if reg.kind != "jit":
                continue          # scan bodies / shard_map can't donate
            for i, pname in enumerate(pos):
                if pname in ("self", "cls"):
                    continue
                tokens = set(pname.lower().split("_"))
                if not tokens & BUFFER_TOKENS:
                    continue
                if i in reg.donated_nums or pname in reg.donated_names:
                    continue
                if mod.suppressions.is_suppressed("donation", reg.line):
                    continue
                out.append(Finding(
                    "donation", mod.relpath, reg.line,
                    f"jitted function '{fn.node.name}' takes device "
                    f"buffer '{pname}' (positional arg {i}) without "
                    "donating it — the input buffer stays live across "
                    "the dispatch, double-allocating it "
                    "(donate_argnums)", fn.qualname))
    return out


def _prng_call(node: ast.Call) -> tuple[str, bool] | None:
    """(op, consuming) when the call is a jax.random-family op."""
    head = dotted_name(node.func)
    for pref in _PRNG_PREFIXES:
        if head.startswith(pref):
            op = head[len(pref):]
            if "." in op:
                return None
            return op, op not in _PRNG_NONCONSUMING
    return None


class _PrngWalk:
    """Per-function key lifecycle: fresh → (used | split-dead | escaped).
    Loop bodies run twice so a consume-without-resplit across iterations
    surfaces; findings dedupe on (line, message)."""

    #: param-name tokens that mark an incoming PRNG key
    KEY_TOKENS = {"key", "rng", "prng"}

    def __init__(self, mod: Module, fn_node, qualname: str):
        self.mod = mod
        self.qualname = qualname
        self.node = fn_node
        self.state: dict[str, str] = {}
        a = fn_node.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if set(p.arg.lower().split("_")) & self.KEY_TOKENS:
                self.state[p.arg] = "fresh"
        self.findings: dict[tuple, Finding] = {}

    def run(self) -> list[Finding]:
        self._stmts(self.node.body)
        return list(self.findings.values())

    # -- helpers -------------------------------------------------------

    def _emit(self, node: ast.AST, message: str) -> None:
        f = self.mod.finding("prng-reuse", node, message,
                             context=self.qualname)
        if f is not None:
            self.findings[(f.line, f.message)] = f

    def _handle_calls(self, stmt_value: ast.AST) -> None:
        for node in ast.walk(stmt_value):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if not isinstance(node, ast.Call):
                continue
            prng = _prng_call(node)
            key_args = [a for a in node.args
                        if isinstance(a, ast.Name)
                        and a.id in self.state]
            if prng is not None:
                op, consuming = prng
                for a in key_args:
                    st = self.state.get(a.id)
                    if consuming:
                        if st == "used":
                            self._emit(node, (
                                f"key '{a.id}' consumed by a second "
                                "random op without an intervening "
                                "jax.random.split — draws are "
                                "correlated"))
                        elif st == "split":
                            self._emit(node, (
                                f"key '{a.id}' was already split; "
                                "consuming it again reuses the same "
                                "randomness as its children"))
                        self.state[a.id] = "used"
                    elif op == "split":
                        if self.state.get(a.id) == "split":
                            self._emit(node, (
                                f"key '{a.id}' split twice — both "
                                "splits yield identical children"))
                        self.state[a.id] = "split"
            elif (isinstance(node.func, ast.Name)
                    and node.func.id in SAMPLE_LIKE):
                for a in key_args:
                    if self.state.get(a.id) == "used":
                        self._emit(node, (
                            f"key '{a.id}' consumed by a second random "
                            "op without an intervening jax.random.split"
                            " — draws are correlated"))
                    self.state[a.id] = "used"
            else:
                # key escapes into an unknown callee: stop tracking
                for a in key_args:
                    self.state.pop(a.id, None)

    def _assign(self, targets: list[ast.expr], value: ast.AST) -> None:
        names: list[str] = []
        for t in targets:
            names.extend(_assign_names(t))
        fresh = False
        if isinstance(value, ast.Call):
            prng = _prng_call(value)
            if prng is not None and prng[0] in ("PRNGKey", "key", "split",
                                                "fold_in",
                                                "wrap_key_data"):
                fresh = True
        for n in names:
            if fresh:
                self.state[n] = "fresh"
            else:
                self.state.pop(n, None)

    # -- statement walk ------------------------------------------------
    # _stmts/_stmt return True when the block is GUARANTEED to leave the
    # function (return/raise) — a terminated branch's key state must not
    # merge into the fall-through path (early returns make branch-local
    # consumes exclusive, not sequential).

    def _merge(self, other: dict[str, str]) -> None:
        order = {"fresh": 0, "split": 1, "used": 2}
        for k, v in other.items():
            cur = self.state.get(k)
            if cur is None or order.get(v, 0) > order.get(cur, 0):
                self.state[k] = v

    def _stmts(self, stmts: list[ast.stmt]) -> bool:
        for stmt in stmts:
            if self._stmt(stmt):
                return True
        return False

    def _stmt(self, stmt: ast.stmt) -> bool:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return False
        if isinstance(stmt, (ast.Return, ast.Raise)):
            if getattr(stmt, "value", None) is not None:
                self._handle_calls(stmt.value)
            if isinstance(stmt, ast.Raise) and stmt.exc is not None:
                self._handle_calls(stmt.exc)
            return True
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if getattr(stmt, "value", None) is not None:
                self._handle_calls(stmt.value)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            if getattr(stmt, "value", None) is not None:
                self._assign(targets, stmt.value)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._handle_calls(stmt.test)
            before = dict(self.state)
            rounds = 2 if isinstance(stmt, ast.While) else 1
            body_term = False
            for _ in range(rounds):
                body_term = self._stmts(stmt.body)
            body_state = self.state
            self.state = dict(before)
            else_term = self._stmts(stmt.orelse)
            if not body_term:
                if else_term:
                    self.state = dict(body_state)
                else:
                    self._merge(body_state)
            return body_term and else_term
        elif isinstance(stmt, ast.For):
            self._handle_calls(stmt.iter)
            for _ in range(2):
                if self._stmts(stmt.body):
                    break
            self._stmts(stmt.orelse)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._handle_calls(item.context_expr)
            return self._stmts(stmt.body)
        elif isinstance(stmt, ast.Try):
            term = self._stmts(stmt.body)
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            fin = self._stmts(stmt.finalbody)
            return fin or (term and not stmt.handlers)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._handle_calls(child)
        return False


def _check_prng(mod: Module, model: _ModuleModel) -> list[Finding]:
    out: list[Finding] = []
    for fn in model.fns.values():
        if isinstance(fn.node, ast.Lambda):
            continue
        out.extend(_PrngWalk(mod, fn.node, fn.qualname).run())
    return out


def _declared_axes(mod: Module) -> set[str]:
    """Axis names any mesh/shard_map surface in this module binds."""
    assert mod.tree is not None
    declared: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            head = dotted_name(node.func)
            tail = head.rsplit(".", 1)[-1]
            if ("mesh" in tail.lower()
                    or tail in ("PartitionSpec", "P", "NamedSharding")):
                declared.update(str_constants(node))
            for k in node.keywords:
                # NOT the singular `axis_name` — that is the collectives'
                # own kwarg, which must be checked, not declared
                if k.arg in ("axis_names", "axis_resources",
                             "in_specs", "out_specs"):
                    declared.update(str_constants(k.value))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            params = a.posonlyargs + a.args
            defaults = [None] * (len(params) - len(a.defaults)) + list(
                a.defaults)
            for p, d in list(zip(params, defaults)) + list(
                    zip(a.kwonlyargs, a.kw_defaults)):
                if d is not None and (
                        p.arg in ("axis", "axis_name")
                        or p.arg.endswith("_axis")):
                    declared.update(str_constants(d))
    return declared


def _check_collective_axes(mod: Module, model: _ModuleModel
                           ) -> list[Finding]:
    declared = _declared_axes(mod)
    if not declared:
        # No mesh/spec surface in this module: literal axes are bound by
        # a caller's mesh we cannot see — stay silent rather than guess.
        return []
    assert mod.tree is not None
    out: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        head = dotted_name(node.func)
        if not head.startswith(_COLLECTIVE_PREFIXES):
            continue
        op = head.rsplit(".", 1)[-1]
        if op not in COLLECTIVES:
            continue
        pos = COLLECTIVES[op]
        axis_expr = kw(node, "axis_name") or kw(node, "axis")
        if axis_expr is None and len(node.args) > pos:
            axis_expr = node.args[pos]
        if axis_expr is None:
            continue
        for name in str_constants(axis_expr):
            if name not in declared:
                f = mod.finding(
                    "collective-axis", node,
                    f"collective `{op}` names axis '{name}', which no "
                    "mesh/shard_map/PartitionSpec declaration in this "
                    f"module binds (declared: {sorted(declared)})")
                if f is not None:
                    out.append(f)
    return out


def check(mod: Module) -> list[Finding]:
    """All JAX rules for one module. Syntax errors are policy's job."""
    if mod.tree is None:
        return []
    model = _ModuleModel(mod)
    out: list[Finding] = []
    out.extend(_check_host_sync(mod, model))
    out.extend(_check_taint_rules(mod, model))
    out.extend(_check_donation(mod, model))
    out.extend(_check_prng(mod, model))
    out.extend(_check_collective_axes(mod, model))
    return out
