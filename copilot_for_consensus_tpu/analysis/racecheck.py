"""Rule group ``race``: static concurrency analysis for the thread plane.

PRs 7 and 8 grew a real multi-threaded runtime — dispatcher thread,
watchdog, outbox replayer, backpressure pacer, shipping-logger pump —
and review kept catching the SAME bug classes by hand: callbacks fired
while holding the lock the watchdog shares, ledgers mutated with and
without their lock, wrapper delegation silently defeated by a concrete
base-class default. These are exactly what syntactic lock-consistency
analyzers (Infer's RacerD, the kernel's lockdep) catch cheaply, so this
group turns them into a machine-checked gate.

Five rules over one per-module model (``_ModuleScan``) built on the
shared assignment-provenance :class:`~.base.LockModel`:

* ``race-lock-order`` — per-module lock-acquisition graph ("lock A held
  while acquiring lock B", with call-graph propagation in the style of
  jaxlint's host-sync context propagation). A cycle is a potential
  deadlock; acquiring a held NON-reentrant lock is a guaranteed one.
* ``race-callback-under-lock`` — a user-supplied callable (anything
  bound from a constructor/registration parameter: done-callbacks,
  ``error_reporter``, subscriber handlers) invoked while a lock is
  held. Done-callbacks may re-enter ``submit()`` — the exact PR-7
  re-entrancy class. Propagates through the call graph, including
  calls to other classes' callback-firing methods in the same module
  (``handle._resolve(...)`` under the dispatcher lock).
* ``race-unlocked-field`` — RacerD-style lock consistency: a ``self._x``
  written under a lock in one method and read/written bare in another
  method of the same class. The bare access is the finding.
* ``race-thread-lifecycle`` — every ``threading.Thread(target=...)``
  needs a reachable stop path: either the target (transitively) polls a
  stop ``Event`` (``.wait()``/``.is_set()``) or the thread object is
  ``join()``ed somewhere in its owner. Daemon-and-forget loops are
  findings.
* ``race-wrapper-shadow`` — a class relying on ``__getattr__``
  delegation whose concrete base class defines the same method as a
  trivial default (``pass`` / ``return {}``): the delegation never
  fires, so the wrapper silently serves the default instead of the
  wrapped driver's implementation — the PR-8 ``ValidatingPublisher.
  saturation()`` bug as a lint rule. The per-file pass resolves
  same-module bases; :func:`check_cross` resolves bases across the
  package via the import graph (skipped under ``--fast`` and for
  explicit-path runs).

Held-lock reasoning: a method's body holds what its ``with`` blocks
hold lexically, PLUS what every internal call site holds when the
method is private, never referenced as a value (callbacks/thread
targets escape), and only ever called with that lock held — the
``# caller holds _replay_lock`` idiom, inferred instead of trusted.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from copilot_for_consensus_tpu.analysis.base import (
    Finding,
    LockInfo,
    LockModel,
    Module,
    dotted_name,
    kw,
)

RULES = (
    "race-lock-order",
    "race-callback-under-lock",
    "race-unlocked-field",
    "race-thread-lifecycle",
    "race-wrapper-shadow",
)

#: container-method names that mutate their receiver: a call
#: ``self._x.append(...)`` is a WRITE of ``_x`` for lock-consistency
MUTATORS = {"append", "appendleft", "extend", "insert", "add", "update",
            "pop", "popleft", "popitem", "remove", "discard", "clear",
            "setdefault"}

#: methods excluded from unlocked-field: construction happens-before
#: every cross-thread access, so bare writes there are fine
CONSTRUCTORS = {"__init__", "__post_init__"}

#: constructors that mark a field as a plain shared CONTAINER — only
#: these get their element mutations (``self._x[k] = v``,
#: ``self._x.append(...)``) counted as writes OF THE FIELD. An object
#: field (``self.outbox.append(...)``) synchronizes itself; calling
#: its methods is not a data race on the reference.
CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                   "OrderedDict", "Counter"}


# ---------------------------------------------------------------------------
# per-module scan: units, accesses, acquisitions, call edges
# ---------------------------------------------------------------------------


@dataclass
class _Access:
    fld: str
    write: bool
    held: frozenset
    node: ast.AST


@dataclass
class _Acq:
    lock: LockInfo
    held: frozenset          # locks held at the acquisition site
    node: ast.AST


@dataclass
class _Call:
    name: str                # method/callback name
    kind: str                # "cb" | "self" | "attr"
    held: frozenset
    node: ast.AST


@dataclass
class _ThreadCtor:
    node: ast.Call
    target: ast.expr | None
    assigned: LockInfo | None   # thread provenance when visible


@dataclass(eq=False)       # identity semantics: units live in sets
class _Unit:
    """One scan unit: a method, module function, or nested function
    (nested defs can run on other threads, so they scan as their own
    unit with an empty initial held set)."""

    node: ast.AST
    qualname: str
    cls: str | None          # enclosing class name, None at module level
    name: str                # bare function name
    accesses: list[_Access] = field(default_factory=list)
    acquisitions: list[_Acq] = field(default_factory=list)
    calls: list[_Call] = field(default_factory=list)
    threads: list[_ThreadCtor] = field(default_factory=list)
    joins: set[int] = field(default_factory=set)   # id(thread LockInfo)
    #: a join whose receiver has NO provenance (`for t in threads:
    #: t.join()`) — it may join anything, so it excuses untracked
    #: threads; a join of a KNOWN other thread excuses nothing
    untracked_join: bool = False
    polls_stop: bool = False
    # summaries (fixpoint over the call graph)
    acquires: set[int] = field(default_factory=set)
    invokes_cb: bool = False
    inherited_held: frozenset = frozenset()


def _is_self(node: ast.AST) -> bool:
    return isinstance(node, ast.Name) and node.id == "self"


class _ModuleScan:
    """Builds every unit plus per-class method/callback-field tables."""

    def __init__(self, mod: Module, locks: LockModel):
        self.mod = mod
        self.locks = locks
        self.units: list[_Unit] = []
        #: class -> {method name -> unit}
        self.methods: dict[str, dict[str, _Unit]] = {}
        #: class -> field names bound from a parameter (user-supplied
        #: callables when invoked) — direct or container-element
        self.cb_fields: dict[str, set[str]] = {}
        #: class -> method names referenced as values (escape: may run
        #: on any thread, so they inherit no held locks)
        self.escapes: dict[str, set[str]] = {}
        #: class -> fields holding plain shared containers (element
        #: mutations count as writes of the field)
        self.container_fields: dict[str, set[str]] = {}
        assert mod.tree is not None
        self._collect_cb_fields()
        self._collect_container_fields()
        self._collect_units(mod.tree, cls=None)
        for u in self.units:
            if u.cls is not None:
                self.methods.setdefault(u.cls, {}).setdefault(u.name, u)
        for u in self.units:
            _UnitWalk(self, u).run()
        self._fixpoint()

    # -- discovery -----------------------------------------------------

    def _enclosing_class(self, node: ast.AST) -> str | None:
        cur = self.mod.parent(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a method of a class nested in a function still
                # belongs to that class; a plain nested def does not
                pass
            cur = self.mod.parent(cur)
        return None

    def _collect_units(self, tree: ast.AST, cls: str | None) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.units.append(_Unit(
                    node, self.mod.qualname(node),
                    self._enclosing_class(node), node.name))

    def _collect_cb_fields(self) -> None:
        """Fields assigned from a parameter anywhere in their class:
        ``self.F = param``, ``self.F.append(param)``,
        ``self.F[k] = param`` — the provenance that makes a later
        ``self.F(...)`` (or element call) a user-callback invocation."""
        assert self.mod.tree is not None
        for fn in ast.walk(self.mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            cls = self._enclosing_class(fn)
            if cls is None:
                continue
            a = fn.args
            params = {p.arg for p in
                      a.posonlyargs + a.args + a.kwonlyargs} - {"self"}
            if not params:
                continue
            bucket = self.cb_fields.setdefault(cls, set())
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Name) \
                        and node.value.id in params:
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and _is_self(t.value):
                            bucket.add(t.attr)
                        elif isinstance(t, ast.Subscript) and isinstance(
                                t.value, ast.Attribute) \
                                and _is_self(t.value.value):
                            bucket.add(t.value.attr)
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr in ("append", "add", "insert",
                                               "setdefault") \
                        and isinstance(node.func.value, ast.Attribute) \
                        and _is_self(node.func.value.value) \
                        and any(isinstance(arg, ast.Name)
                                and arg.id in params
                                for arg in node.args):
                    bucket.add(node.func.value.attr)
        # a field that is a lock/event/thread is never a callback
        for cls, fields in self.cb_fields.items():
            fields.difference_update(self.locks.class_fields.get(cls, {}))

    def _is_container_ctor(self, value: ast.AST) -> bool:
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            tail = dotted_name(value.func).rsplit(".", 1)[-1]
            if tail in CONTAINER_CTORS:
                return True
            if tail == "field":
                df = kw(value, "default_factory")
                if df is not None and dotted_name(df).rsplit(
                        ".", 1)[-1] in CONTAINER_CTORS:
                    return True
        return False

    def _collect_container_fields(self) -> None:
        assert self.mod.tree is not None
        for node in ast.walk(self.mod.tree):
            targets: list[ast.expr] = []
            value: ast.AST | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None:
                targets, value = [node.target], node.value
            if value is None or not self._is_container_ctor(value):
                continue
            cls = self._enclosing_class(node)
            if cls is None:
                continue
            for t in targets:
                if isinstance(t, ast.Attribute) and _is_self(t.value):
                    self.container_fields.setdefault(cls, set()).add(
                        t.attr)
                elif isinstance(t, ast.Name) and isinstance(
                        self.mod.parent(node), ast.ClassDef):
                    # class-body (dataclass) field declaration
                    self.container_fields.setdefault(cls, set()).add(
                        t.id)

    # -- summaries -----------------------------------------------------

    def attr_callees(self, name: str) -> list[_Unit]:
        """Units a ``<obj>.name(...)`` call may reach — name-based
        cross-class resolution, trusted only when the name is defined
        by exactly ONE class in the module (``handle._resolve(...)``
        resolves; ubiquitous names like ``close`` stay opaque rather
        than smearing every class's summary onto every receiver)."""
        cands = [u for u in self.units
                 if u.name == name and u.cls is not None]
        classes = {u.cls for u in cands}
        return cands if len(classes) == 1 else []

    def _fixpoint(self) -> None:
        by_cls = self.methods
        for u in self.units:
            u.acquires = {id(a.lock) for a in u.acquisitions}
            u.invokes_cb = any(c.kind == "cb" for c in u.calls)
        for _ in range(12):
            changed = False
            for u in self.units:
                for c in u.calls:
                    callees: list[_Unit] = []
                    if c.kind == "self" and u.cls is not None:
                        callee = by_cls.get(u.cls, {}).get(c.name)
                        if callee is not None:
                            callees = [callee]
                    elif c.kind == "attr":
                        callees = self.attr_callees(c.name)
                    for callee in callees:
                        if callee.acquires - u.acquires:
                            u.acquires |= callee.acquires
                            changed = True
                        if callee.invokes_cb and not u.invokes_cb:
                            u.invokes_cb = True
                            changed = True
            if not changed:
                break
        # inherited held locks: private, non-escaping, internally
        # called methods inherit the INTERSECTION of their call sites'
        # held sets (the "# caller holds the lock" idiom, verified)
        sites: dict[int, list[frozenset]] = {}
        for _ in range(4):
            sites.clear()
            for u in self.units:
                for c in u.calls:
                    # EVERY resolvable call site counts — a lock-free
                    # cross-class call (`h._cancel()`) must shrink the
                    # intersection, or a racy bare access inside the
                    # callee hides behind its self-call sites' locks
                    callees: list[_Unit] = []
                    if c.kind == "self" and u.cls is not None:
                        callee = by_cls.get(u.cls, {}).get(c.name)
                        if callee is not None:
                            callees = [callee]
                    elif c.kind == "attr":
                        callees = self.attr_callees(c.name)
                    for callee in callees:
                        if callee.cls is None:
                            continue
                        sites.setdefault(id(callee), []).append(
                            c.held | u.inherited_held)
            changed = False
            for u in self.units:
                if (u.cls is None or not u.name.startswith("_")
                        or u.name.startswith("__")
                        or u.name in self.escapes.get(u.cls, ())):
                    continue
                held_sets = sites.get(id(u))
                if not held_sets:
                    continue
                inherited = frozenset.intersection(*held_sets)
                if inherited != u.inherited_held:
                    u.inherited_held = inherited
                    changed = True
            if not changed:
                break


class _UnitWalk:
    """One unit's body: tracks the lexically-held lock set, records
    field accesses, lock acquisitions, calls, thread constructions.
    Stops at nested function boundaries (each nested def is its own
    unit — it may run on another thread with nothing held)."""

    def __init__(self, scan: _ModuleScan, unit: _Unit):
        self.scan = scan
        self.unit = unit
        self.mod = scan.mod
        self.locks = scan.locks
        self.cb_fields = scan.cb_fields.get(unit.cls or "", set())
        self.containers = scan.container_fields.get(unit.cls or "",
                                                    set())
        self.methods = set(scan.methods.get(unit.cls or "", ()))
        #: local names holding user-callback values (from cb fields,
        #: through tuple unpacking / iteration / container reads)
        self.cb_locals: set[str] = set()

    def run(self) -> None:
        body = getattr(self.unit.node, "body", [])
        self._stmts(body, frozenset())

    # -- statements ----------------------------------------------------

    def _stmts(self, stmts: list[ast.stmt], held: frozenset) -> None:
        for stmt in stmts:
            self._stmt(stmt, held)

    def _stmt(self, stmt: ast.stmt, held: frozenset) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                         # separate units walk alone
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in stmt.items:
                self._expr(item.context_expr, held)
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    expr = expr.func
                info = self.locks.resolve(expr, item.context_expr)
                if info is not None and info.role == "lock":
                    self.unit.acquisitions.append(
                        _Acq(info, new_held, item.context_expr))
                    new_held = new_held | {id(info)}
            self._stmts(stmt.body, new_held)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test, held)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, ast.For):
            self._expr(stmt.iter, held)
            if isinstance(stmt.target, ast.Name) \
                    and self._is_cb_value(stmt.iter):
                self.cb_locals.add(stmt.target.id)
            self._target(stmt.target, held)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body, held)
            for h in stmt.handlers:
                self._stmts(h.body, held)
            self._stmts(stmt.orelse, held)
            self._stmts(stmt.finalbody, held)
        elif isinstance(stmt, ast.Assign):
            self._expr(stmt.value, held)
            self._taint(stmt.targets, stmt.value)
            for t in stmt.targets:
                self._target(t, held)
        elif isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, held)
            self._field_of_target(stmt.target, held, read_too=True)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value, held)
                self._taint([stmt.target], stmt.value)
            self._target(stmt.target, held)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._field_of_target(t, held)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child, held)

    # -- callback-value taint ------------------------------------------

    def _is_cb_value(self, expr: ast.AST) -> bool:
        """Does this expression yield a user callback (or a container
        of them)? ``self.F`` for a cb field, a tainted local, an
        element read of either (``x[k]`` / ``x.get(k)``)."""
        if isinstance(expr, ast.Attribute) and _is_self(expr.value):
            return expr.attr in self.cb_fields
        if isinstance(expr, ast.Name):
            return expr.id in self.cb_locals
        if isinstance(expr, ast.Subscript):
            return self._is_cb_value(expr.value)
        if isinstance(expr, ast.Call) and isinstance(
                expr.func, ast.Attribute) \
                and expr.func.attr in ("get", "pop", "popleft"):
            return self._is_cb_value(expr.func.value)
        return False

    def _taint(self, targets: list[ast.expr], value: ast.AST) -> None:
        if len(targets) == 1 and isinstance(targets[0], ast.Tuple) \
                and isinstance(value, ast.Tuple) \
                and len(targets[0].elts) == len(value.elts):
            for t, v in zip(targets[0].elts, value.elts):
                if isinstance(t, ast.Name):
                    if self._is_cb_value(v):
                        self.cb_locals.add(t.id)
                    else:
                        self.cb_locals.discard(t.id)
            return
        tainted = self._is_cb_value(value)
        for t in targets:
            if isinstance(t, ast.Name):
                if tainted:
                    self.cb_locals.add(t.id)
                else:
                    self.cb_locals.discard(t.id)

    # -- targets / field accesses --------------------------------------

    def _record_access(self, fld: str, write: bool, held: frozenset,
                       node: ast.AST) -> None:
        prov = self.locks.class_fields.get(self.unit.cls or "", {})
        info = prov.get(fld)
        if info is not None and info.role in ("lock", "event"):
            return            # the primitives themselves are not data
        if fld in self.methods:
            return
        self.unit.accesses.append(_Access(fld, write, held, node))

    def _field_of_target(self, t: ast.expr, held: frozenset,
                         read_too: bool = False) -> None:
        """A store target: ``self.X = ...`` and ``self.X[k] = ...``
        are writes of X (the container mutation included)."""
        if isinstance(t, ast.Attribute) and _is_self(t.value):
            if read_too:
                self._record_access(t.attr, False, held, t)
            self._record_access(t.attr, True, held, t)
        elif isinstance(t, ast.Subscript):
            self._expr(t.slice, held)
            inner = t.value
            if isinstance(inner, ast.Attribute) and _is_self(inner.value):
                # element store: a write of the FIELD only for plain
                # shared containers; other objects own their state
                write = inner.attr in self.containers
                if read_too or not write:
                    self._record_access(inner.attr, False, held, inner)
                if write:
                    self._record_access(inner.attr, True, held, inner)
            else:
                self._expr(inner, held)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._field_of_target(el, held, read_too)

    def _target(self, t: ast.expr, held: frozenset) -> None:
        self._field_of_target(t, held)

    # -- expressions ---------------------------------------------------

    def _expr(self, root: ast.AST, held: frozenset) -> None:
        stack: list[ast.AST] = [root]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue                   # separate unit / opaque
            if isinstance(node, ast.Call):
                self._call(node, held)
                stack.extend(node.args)
                stack.extend(k.value for k in node.keywords)
                continue
            if isinstance(node, ast.Attribute):
                if _is_self(node.value):
                    if node.attr in self.methods:
                        # a method referenced as a VALUE escapes: it
                        # may run on any thread (Thread target,
                        # registered callback) — no held inheritance
                        self.scan.escapes.setdefault(
                            self.unit.cls or "", set()).add(node.attr)
                    else:
                        self._record_access(node.attr, False, held, node)
                    continue
                stack.append(node.value)
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _call(self, node: ast.Call, held: frozenset) -> None:
        head = dotted_name(node.func)
        if head == "threading.Thread" or (
                head == "Thread"
                and "Thread" in self.locks.bare_names):
            self._thread_ctor(node)
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            attr = node.func.attr
            if _is_self(recv):
                if attr in self.methods:
                    self.unit.calls.append(_Call(attr, "self", held,
                                                 node))
                elif attr in self.cb_fields:
                    self.unit.calls.append(_Call(attr, "cb", held, node))
                else:
                    self._record_access(attr, False, held, node.func)
                return
            # mutator call on self.X through one attribute level — a
            # write of X only when X is a plain shared container
            if isinstance(recv, ast.Attribute) and _is_self(recv.value):
                if attr in MUTATORS and recv.attr in self.containers:
                    self._record_access(recv.attr, True, held, recv)
                else:
                    self._record_access(recv.attr, False, held, recv)
            else:
                self._expr(recv, held)
            if attr == "join":
                info = self.locks.resolve(recv, node)
                if info is not None and info.role == "thread":
                    self.unit.joins.add(id(info))
                else:
                    self.unit.untracked_join = True
            elif attr in ("wait", "is_set"):
                info = self.locks.resolve(recv, node)
                name = dotted_name(recv).rsplit(".", 1)[-1].lower()
                if (info is not None and info.role == "event") \
                        or "stop" in name.replace("-", "_").split("_"):
                    self.unit.polls_stop = True
            if self._is_cb_value(node.func.value) \
                    and attr not in ("get", "pop", "popleft"):
                # a method call ON a callback value is not an
                # invocation, and must stay OPAQUE: recording it as an
                # attr call would let name-based resolution smear an
                # unrelated class's lock/callback summary onto the
                # callback receiver
                return
            self.unit.calls.append(_Call(attr, "attr", held, node))
            return
        if isinstance(node.func, ast.Name):
            if node.func.id in self.cb_locals:
                self.unit.calls.append(_Call(node.func.id, "cb", held,
                                             node))
            return
        if isinstance(node.func, ast.Subscript) \
                and self._is_cb_value(node.func):
            # direct element invocation: ``self._handlers[key](env)``
            name = dotted_name(node.func.value).rsplit(".", 1)[-1] \
                or "<callback>"
            self.unit.calls.append(_Call(f"{name}[...]", "cb", held,
                                         node))
            self._expr(node.func.slice, held)
            inner = node.func.value
            if isinstance(inner, ast.Attribute) and _is_self(inner.value):
                self._record_access(inner.attr, False, held, inner)
            return
        self._expr(node.func, held)

    def _thread_ctor(self, node: ast.Call) -> None:
        target = None
        for k in node.keywords:
            if k.arg == "target":
                target = k.value
        assigned = None
        # climb through the enclosing assignment (if any) to find the
        # thread's binding — provenance gives it a stable identity the
        # join scan can match
        cur: ast.AST | None = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.mod.parent(cur)
        if isinstance(cur, ast.Assign) and len(cur.targets) == 1:
            assigned = self.locks.resolve(cur.targets[0], node)
            if assigned is not None and assigned.role != "thread":
                assigned = None
        self.unit.threads.append(_ThreadCtor(node, target, assigned))


# ---------------------------------------------------------------------------
# rule 1: race-lock-order
# ---------------------------------------------------------------------------


def _check_lock_order(mod: Module, scan: _ModuleScan) -> list[Finding]:
    out: list[Finding] = []
    info_by_id: dict[int, LockInfo] = {}
    for u in scan.units:
        for a in u.acquisitions:
            info_by_id[id(a.lock)] = a.lock
    #: (A, B) -> (unit qualname, node) of a representative site
    edges: dict[tuple[int, int], tuple[str, ast.AST]] = {}

    def note_edge(a: int, b: int, unit: _Unit, node: ast.AST) -> None:
        edges.setdefault((a, b), (unit.qualname, node))

    for u in scan.units:
        ih = u.inherited_held
        for a in u.acquisitions:
            held = a.held | ih
            for lid in held:
                if lid == id(a.lock):
                    if not a.lock.reentrant:
                        f = mod.finding(
                            "race-lock-order", a.node,
                            f"non-reentrant lock '{a.lock.name}' "
                            "acquired while already held on this path "
                            "— a guaranteed self-deadlock (use an "
                            "RLock or release first)",
                            context=u.qualname)
                        if f is not None:
                            out.append(f)
                else:
                    note_edge(lid, id(a.lock), u, a.node)
        for c in u.calls:
            held = c.held | ih
            if not held:
                continue
            callees: list[_Unit] = []
            if c.kind == "self" and u.cls is not None:
                callee = scan.methods.get(u.cls, {}).get(c.name)
                if callee is not None:
                    callees = [callee]
            elif c.kind == "attr":
                callees = scan.attr_callees(c.name)
            acq: set[int] = set()
            for callee in callees:
                acq |= callee.acquires
            for b in acq:
                binfo = info_by_id.get(b)
                if b in held:
                    if binfo is not None and not binfo.reentrant:
                        f = mod.finding(
                            "race-lock-order", c.node,
                            f"call to '{c.name}()' re-acquires "
                            f"non-reentrant lock '{binfo.name}' that "
                            "is already held at this call site — a "
                            "guaranteed self-deadlock",
                            context=u.qualname)
                        if f is not None:
                            out.append(f)
                else:
                    for a in held:
                        note_edge(a, b, u, c.node)

    # cycles in the order graph (lockdep's invariant: the "held while
    # acquiring" relation must stay acyclic)
    adj: dict[int, set[int]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)

    def reachable(src: int, dst: int) -> bool:
        seen = {src}
        stack = [src]
        while stack:
            n = stack.pop()
            for m in adj.get(n, ()):
                if m == dst:
                    return True
                if m not in seen:
                    seen.add(m)
                    stack.append(m)
        return False

    reported: set[frozenset] = set()
    for (a, b), (qual, node) in sorted(
            edges.items(),
            key=lambda kv: (getattr(kv[1][1], "lineno", 0), kv[0])):
        if not reachable(b, a):
            continue
        key = frozenset((a, b))
        if key in reported:
            continue
        reported.add(key)
        na = info_by_id.get(a)
        nb = info_by_id.get(b)
        an = na.name if na else "?"
        bn = nb.name if nb else "?"
        f = mod.finding(
            "race-lock-order", node,
            f"lock-order cycle: '{an}' is held while acquiring "
            f"'{bn}' here, but another path acquires '{an}' while "
            f"holding '{bn}' — a potential deadlock; pick one order "
            "and document it",
            context=qual)
        if f is not None:
            out.append(f)
    return out


# ---------------------------------------------------------------------------
# rule 2: race-callback-under-lock
# ---------------------------------------------------------------------------


def _check_callback_under_lock(mod: Module,
                               scan: _ModuleScan) -> list[Finding]:
    out: list[Finding] = []
    for u in scan.units:
        ih = u.inherited_held
        for c in u.calls:
            held = c.held | ih
            if not held:
                continue
            if c.kind == "cb":
                f = mod.finding(
                    "race-callback-under-lock", c.node,
                    f"user-supplied callback '{c.name}' invoked while "
                    "holding a lock — a callback may re-enter the "
                    "lock's owner (e.g. a done-callback calling "
                    "submit()) and deadlock, or run arbitrary code in "
                    "the critical section; collect under the lock, "
                    "fire outside it",
                    context=u.qualname)
                if f is not None:
                    out.append(f)
                continue
            callees: list[_Unit] = []
            if c.kind == "self" and u.cls is not None:
                callee = scan.methods.get(u.cls, {}).get(c.name)
                if callee is not None:
                    callees = [callee]
            elif c.kind == "attr":
                callees = scan.attr_callees(c.name)
            if any(cal.invokes_cb for cal in callees):
                f = mod.finding(
                    "race-callback-under-lock", c.node,
                    f"call to '{c.name}()', which fires user-supplied "
                    "callbacks, made while holding a lock — the "
                    "callback runs inside the critical section and "
                    "may re-enter it; resolve/fail handles outside "
                    "the lock",
                    context=u.qualname)
                if f is not None:
                    out.append(f)
    return out


# ---------------------------------------------------------------------------
# rule 3: race-unlocked-field
# ---------------------------------------------------------------------------


def _check_unlocked_field(mod: Module, scan: _ModuleScan
                          ) -> list[Finding]:
    out: list[Finding] = []
    #: class -> field -> list[(unit, access, effective held)]
    table: dict[str, dict[str, list]] = {}
    for u in scan.units:
        if u.cls is None or u.name in CONSTRUCTORS:
            continue
        for a in u.accesses:
            held = a.held | u.inherited_held
            table.setdefault(u.cls, {}).setdefault(a.fld, []).append(
                (u, a, held))
    info_names: dict[int, str] = {}
    for u in scan.units:
        for a in u.acquisitions:
            info_names[id(a.lock)] = a.lock.name
    for u in scan.units:
        for f, i in scan.locks.class_fields.get(u.cls or "", {}).items():
            info_names.setdefault(id(i), i.name)
    for cls, fields in table.items():
        if not scan.locks.locks_of(cls):
            continue               # no locks in this class: nothing to
        for fld, accs in fields.items():       # be inconsistent WITH
            locked_writes = [x for x in accs if x[1].write and x[2]]
            locked_any = [x for x in accs if x[2]]
            if not locked_any:
                continue
            guards = sorted({info_names.get(lid, "?")
                             for _, _, held in locked_any
                             for lid in held})
            guard_s = "/".join(f"'{g}'" for g in guards)
            # RacerD's actual invariant is a COMMON lock: accesses
            # under two different locks race just like a bare one
            # does. When the lockset intersection over all guarded
            # accesses (at least one a write) is empty, flag once.
            common = frozenset.intersection(
                *(held for _, _, held in locked_any))
            if not common and locked_writes and len(locked_any) > 1:
                unit, acc, held = locked_any[-1]
                f = mod.finding(
                    "race-unlocked-field", acc.node,
                    f"accesses of field '{fld}' share NO common lock "
                    f"(guards seen: {guard_s}) — holding different "
                    "locks does not synchronize; pick one guard for "
                    "every cross-thread access",
                    context=unit.qualname)
                if f is not None:
                    out.append(f)
            seen_lines: set[int] = set()
            for unit, acc, held in accs:
                if held:
                    continue
                others = ({x[0] for x in locked_writes}
                          if not acc.write
                          else {x[0] for x in locked_any})
                if not (others - {unit}):
                    continue
                line = getattr(acc.node, "lineno", 1)
                if line in seen_lines:
                    continue
                seen_lines.add(line)
                verb = "written" if acc.write else "read"
                f = mod.finding(
                    "race-unlocked-field", acc.node,
                    f"field '{fld}' is guarded by {guard_s} elsewhere "
                    f"in this class but {verb} here without it — "
                    "lock-consistency violation (either every "
                    "cross-thread access holds the guard, or none "
                    "needs to)",
                    context=unit.qualname)
                if f is not None:
                    out.append(f)
    return out


# ---------------------------------------------------------------------------
# rule 4: race-thread-lifecycle
# ---------------------------------------------------------------------------


def _check_thread_lifecycle(mod: Module, scan: _ModuleScan
                            ) -> list[Finding]:
    out: list[Finding] = []
    by_name: dict[str, list[_Unit]] = {}
    for u in scan.units:
        by_name.setdefault(u.name, []).append(u)

    def polls(unit: _Unit, seen: set[int]) -> bool:
        if id(unit) in seen:
            return False
        seen.add(id(unit))
        if unit.polls_stop:
            return True
        for c in unit.calls:
            callees: list[_Unit] = []
            if c.kind == "self" and unit.cls is not None:
                callee = scan.methods.get(unit.cls, {}).get(c.name)
                if callee is not None:
                    callees = [callee]
            elif c.kind == "attr":
                callees = scan.attr_callees(c.name)
            if any(polls(cal, seen) for cal in callees):
                return True
        # nested units (a `def loop():` thread body defines helpers)
        for v in scan.units:
            if v is not unit and v.qualname.startswith(
                    unit.qualname + "."):
                if v.polls_stop:
                    return True
        return False

    def resolve_target(t: ast.expr | None,
                       owner: _Unit) -> _Unit | None:
        if t is None:
            return None
        if isinstance(t, ast.Attribute) and _is_self(t.value) \
                and owner.cls is not None:
            return scan.methods.get(owner.cls, {}).get(t.attr)
        if isinstance(t, ast.Name):
            # local def first (qualname nesting), then module-level
            for u in scan.units:
                if u.name == t.id and u.qualname.startswith(
                        owner.qualname + "."):
                    return u
            for u in by_name.get(t.id, []):
                if u.cls is None and "." not in u.qualname.replace(
                        u.name, "", 1).strip("."):
                    return u
            cands = by_name.get(t.id, [])
            return cands[0] if cands else None
        return None

    for u in scan.units:
        # the owning scope: the whole class for methods, every
        # module-level function for module-level owners (a thread
        # created in start() and joined in stop() shares the module
        # global that carries it)
        cls_units = [v for v in scan.units if v.cls == u.cls]
        for tc in u.threads:
            target_unit = resolve_target(tc.target, u)
            joined = False
            if tc.assigned is not None:
                joined = any(id(tc.assigned) in v.joins
                             for v in cls_units)
            if not joined:
                # fallback: a provenance-free join in the owning scope
                # (the `for t in threads: t.join()` idiom) may join
                # anything, including this thread. Joins of KNOWN
                # other threads don't count — a class that joins _a
                # but forgets _b must still flag _b.
                joined = any(v.untracked_join for v in cls_units)
            stoppable = (target_unit is not None
                         and polls(target_unit, set()))
            if joined or stoppable:
                continue
            tname = (dotted_name(tc.target)
                     if tc.target is not None else "<unknown>")
            f = mod.finding(
                "race-thread-lifecycle", tc.node,
                f"thread target '{tname}' has no reachable stop path: "
                "the target never polls a stop Event "
                "(`.wait(timeout)`/`.is_set()`) and the thread is "
                "never join()ed by its owner — a daemon-and-forget "
                "loop that outlives shutdown and races teardown",
                context=u.qualname)
            if f is not None:
                out.append(f)
    return out


# ---------------------------------------------------------------------------
# rule 5: race-wrapper-shadow
# ---------------------------------------------------------------------------


def _is_trivial_default(fn: ast.AST) -> bool:
    """A concrete do-nothing default: body (docstring aside) is
    ``pass`` / ``...`` / ``return`` of a constant or empty container.
    These exist to be overridden — and they are exactly what defeats
    ``__getattr__`` delegation silently."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for deco in fn.decorator_list:
        name = dotted_name(deco).rsplit(".", 1)[-1]
        if name in ("abstractmethod", "abstractproperty", "property"):
            return False
    body = list(fn.body)
    if body and isinstance(body[0], ast.Expr) and isinstance(
            body[0].value, ast.Constant) and isinstance(
            body[0].value.value, str):
        body = body[1:]
    if len(body) != 1:
        return False
    stmt = body[0]
    if isinstance(stmt, ast.Pass):
        return True
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                 ast.Constant):
        return True
    if isinstance(stmt, ast.Return):
        v = stmt.value
        if v is None or isinstance(v, ast.Constant):
            return True
        if isinstance(v, (ast.Dict, ast.List, ast.Tuple, ast.Set)) \
                and not getattr(v, "elts", None) \
                and not getattr(v, "keys", None):
            return True
    return False


def _delegating_getattr(cls: ast.ClassDef) -> ast.FunctionDef | None:
    """The class's ``__getattr__`` when it forwards to a wrapped
    object (``getattr(self.<field>, ...)`` anywhere in the body)."""
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) \
                and node.name == "__getattr__":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Name) \
                        and sub.func.id == "getattr" and sub.args \
                        and isinstance(sub.args[0], ast.Attribute) \
                        and _is_self(sub.args[0].value):
                    return node
    return None


class _ClassIndex:
    """Class lookup across one or many modules, import-graph aware."""

    def __init__(self, modules: list[Module]):
        self.classes: dict[tuple[str, str], ast.ClassDef] = {}
        #: importer relpath -> {local name -> (source module path,
        #: ORIGINAL name)} — `from x import Y as Z` stores Z -> Y so
        #: lookup in the defining module uses the name it defines
        self.imports: dict[str, dict[str, tuple[str, str]]] = {}
        self.mods: dict[str, Module] = {}
        for mod in modules:
            if mod.tree is None:
                continue
            self.mods[mod.relpath] = mod
            imap = self.imports.setdefault(mod.relpath, {})
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    self.classes[(mod.relpath, node.name)] = node
                elif isinstance(node, ast.ImportFrom) and node.module:
                    if node.level:
                        # relative import: resolve against the
                        # importing module's own path so `from .base
                        # import X` in bus/ means bus/base.py, never
                        # some other base.py in the tree
                        parts = mod.relpath.split("/")[:-1]
                        if node.level > 1:
                            parts = parts[:-(node.level - 1)]
                        src = "/".join(
                            parts + node.module.split(".")) + ".py"
                    else:
                        src = node.module.replace(".", "/") + ".py"
                    for alias in node.names:
                        imap[alias.asname or alias.name] = (src,
                                                            alias.name)

    def _module_for(self, suffix: str) -> str | None:
        # component-boundary suffix match so "copilot_for_consensus_
        # tpu/bus/base.py" resolves whether relpaths are repo-relative
        # or absolute — and "base.py" never matches "database.py"
        for rel in self.mods:
            if rel == suffix or rel.endswith("/" + suffix):
                return rel
        return None

    def resolve_base(self, mod: Module,
                     base: ast.expr) -> ast.ClassDef | None:
        name = dotted_name(base).rsplit(".", 1)[-1]
        if not name:
            return None
        hit = self.classes.get((mod.relpath, name))
        if hit is not None:
            return hit
        entry = self.imports.get(mod.relpath, {}).get(name)
        if entry is not None:
            src, original = entry
            target = self._module_for(src)
            if target is not None:
                return self.classes.get((target, original))
        return None

    def owner_of(self, cls: ast.ClassDef) -> Module | None:
        for (rel, name), node in self.classes.items():
            if node is cls:
                return self.mods.get(rel)
        return None


def _ancestor_chain(cls: ast.ClassDef, mod: Module,
                    index: _ClassIndex
                    ) -> list[tuple[ast.ClassDef, Module]]:
    """Resolvable ancestors, breadth-first — Python's MRO,
    approximately: the first definition of a name wins."""
    chain: list[tuple[ast.ClassDef, Module]] = []
    queue: list[tuple[ast.ClassDef, Module]] = []
    for b in cls.bases:
        owner = index.resolve_base(mod, b)
        if owner is not None:
            queue.append((owner, index.owner_of(owner) or mod))
    seen: set[int] = set()
    while queue:
        base, base_mod = queue.pop(0)
        if id(base) in seen:
            continue
        seen.add(id(base))
        chain.append((base, base_mod))
        for b in base.bases:
            owner = index.resolve_base(base_mod, b)
            if owner is not None:
                queue.append((owner, index.owner_of(owner) or base_mod))
    return chain


def _check_wrapper_shadow(mod: Module,
                          index: _ClassIndex) -> list[Finding]:
    out: list[Finding] = []
    assert mod.tree is not None
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        chain = _ancestor_chain(cls, mod, index)
        # the delegation may itself be inherited (a `_Wrapper` base
        # providing __getattr__): the subclass still shadows it with
        # any OTHER ancestor's concrete trivial default
        ga: ast.AST | None = _delegating_getattr(cls)
        if ga is None:
            for base, _ in chain:
                if _delegating_getattr(base) is not None:
                    ga = cls           # anchor at the class statement
                    break
        if ga is None:
            continue
        defined = {n.name for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        for base, _ in chain:
            for m in base.body:
                if not isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                if m.name in defined or m.name.startswith("__"):
                    continue
                if _is_trivial_default(m):
                    f = mod.finding(
                        "race-wrapper-shadow", ga,
                        f"'{cls.name}' delegates through __getattr__, "
                        f"but concrete base-class default "
                        f"'{base.name}.{m.name}()' shadows it — "
                        f"__getattr__ only fires for MISSING "
                        f"attributes, so '{m.name}' silently serves "
                        "the base default instead of the wrapped "
                        "object's implementation; add an explicit "
                        f"override that forwards '{m.name}'",
                        context=cls.name)
                    if f is not None:
                        out.append(f)
                defined.add(m.name)
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def check(mod: Module) -> list[Finding]:
    """All five race rules for one module (wrapper-shadow resolves
    same-module bases only here; :func:`check_cross` adds the
    package-wide base resolution)."""
    if mod.tree is None:
        return []
    locks = LockModel(mod)
    scan = _ModuleScan(mod, locks)
    out: list[Finding] = []
    out.extend(_check_lock_order(mod, scan))
    out.extend(_check_callback_under_lock(mod, scan))
    out.extend(_check_unlocked_field(mod, scan))
    out.extend(_check_thread_lifecycle(mod, scan))
    out.extend(_check_wrapper_shadow(mod, _ClassIndex([mod])))
    return out


def check_cross(paths, modules: list[Module] | None = None
                ) -> list[Finding]:
    """The cross-module wrapper-shadow pass: resolves base classes
    through the package import graph, so a wrapper in ``bus/
    validating.py`` is checked against the concrete defaults its ABC
    in ``bus/base.py`` defines. Skipped under ``--fast`` and for
    explicit-path runs (it needs the whole package to resolve
    imports). Pass ``modules`` to reuse already-parsed trees (the CLI
    does — the per-file groups parsed the same files moments ago)."""
    if modules is None:
        modules = [Module(p) for p in paths]
    index = _ClassIndex(modules)
    out: list[Finding] = []
    for mod in modules:
        if mod.tree is None:
            continue
        out.extend(_check_wrapper_shadow(mod, index))
    return out
