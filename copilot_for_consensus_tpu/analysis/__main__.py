from copilot_for_consensus_tpu.analysis import main

if __name__ == "__main__":
    raise SystemExit(main())
