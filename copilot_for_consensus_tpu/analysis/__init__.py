"""jaxlint — first-party static analysis for the serving stack.

``python -m copilot_for_consensus_tpu.analysis`` runs every rule group
over the repo and exits non-zero on any non-baselined finding:

* ``jax`` group (jax_rules.py): host-sync-in-jit, retrace-hazard,
  donation, prng-reuse, collective-axis — the invariants that keep the
  engine's jitted hot paths fast and correct.
* ``concurrency`` group (concurrency.py): blocking-call — handler-thread
  hygiene for the bus and services.
* ``race`` group (racecheck.py): static concurrency analysis over the
  serving/pipeline thread plane — lock-order cycles, callbacks invoked
  under locks, RacerD-style lock-consistency on fields, thread
  stop/join lifecycle, and ``__getattr__`` wrappers shadowed by
  concrete base-class defaults. Its wrapper-shadow rule additionally
  runs a cross-module pass (base classes resolved through the package
  import graph) that ``--fast`` and explicit-path runs skip.
* ``policy`` group (policy.py): the original validate_python lane
  (syntax, import smoke, mutable defaults, unused imports, bare except).
* ``dura`` group (duracheck.py): the crash-safety / exactly-once
  contracts from docs/RESILIENCE.md — commit/publish crash windows,
  raw publishes bypassing the outbox, handlers swallowing transient
  failures into silent acks, journal-before-admit / retire-at-harvest
  ordering, dup-tolerant inserts under at-least-once dispatch, and
  sqlite-ledger hygiene (WAL, transaction-scoped loops, owner-joined
  close). Receivers resolve through the effect-provenance model in
  base.py, not name tokens.
* ``shard`` group (shardcheck.py): the SEMANTIC pass — traces the
  contract-declared jitted entrypoints with ``jax.eval_shape`` under
  the declared meshes (CPU, virtual devices) and verifies sharding
  rules, collective axis binding, donation aliasing, KV-cache layout
  agreement, and padding-bucket coverage. Skipped under ``--fast`` and
  for explicit-path runs (it is registry-wide, not per-file).
* ``hlo`` group (hlocheck.py): the POST-LOWERING pass — actually
  lowers and compiles the contract-declared jitted entrypoints under
  the virtual 8-device CPU platform and verifies properties of the
  compiled artifact itself: donation survives as input_output_alias,
  forbidden-op fingerprints (no pool-working-set gather on the kernel
  route), exact collective counts vs the declared budget, peak HBM vs
  the declared budget, and program-cache cardinality across bucket
  tables. Skipped under ``--fast`` and for explicit-path runs, like
  ``shard``.

Suppression: inline ``# jaxlint: disable=<rule>`` with a justification,
or an entry in ``jaxlint_baseline.json`` (every entry must carry a
written justification). Workflow docs: ``docs/STATIC_ANALYSIS.md``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import pathlib
import sys

# NOTE: shardcheck is imported lazily (inside main) so that
# ``python -m copilot_for_consensus_tpu.analysis.shardcheck`` doesn't
# trip runpy's already-imported warning. The engine modules' top-level
# ``analysis.contracts`` import still executes this package body — the
# three ast rule groups below are stdlib-only and cheap — but never
# pulls jax or spawns anything.
from copilot_for_consensus_tpu.analysis import (
    concurrency,
    duracheck,
    jax_rules,
    policy,
    racecheck,
)
from copilot_for_consensus_tpu.analysis.base import (
    DEFAULT_BASELINE,
    Finding,
    Module,
    PACKAGE,
    apply_baseline,
    baseline_entries_for,
    load_baseline,
    rel,
    unjustified_entries,
)

#: ast group name → per-module check (run per parsed file)
GROUPS = {
    "jax": jax_rules.check,
    "concurrency": concurrency.check,
    "race": racecheck.check,
    "policy": policy.check,
    "dura": duracheck.check,
}

#: groups that run once per invocation, not per file
SEMANTIC_GROUPS = {"shard", "hlo"}
ALL_GROUPS = set(GROUPS) | SEMANTIC_GROUPS

#: every individual rule id → its group (for ``--rules`` filtering and
#: docs; keep in sync with docs/STATIC_ANALYSIS.md)
RULES = {
    "host-sync-in-jit": "jax",
    "retrace-hazard": "jax",
    "donation": "jax",
    "prng-reuse": "jax",
    "collective-axis": "jax",
    "blocking-call": "concurrency",
    "policy-syntax": "policy",
    "race-lock-order": "race",
    "race-callback-under-lock": "race",
    "race-unlocked-field": "race",
    "race-thread-lifecycle": "race",
    "race-wrapper-shadow": "race",
    "policy-mutable-default": "policy",
    "policy-bare-except": "policy",
    "policy-unused-import": "policy",
    "policy-import-smoke": "policy",
}
# keep in sync with duracheck.RULES (test_static_analysis.py enforces it)
RULES.update({rule: "dura" for rule in duracheck.RULES})
# keep in sync with shardcheck.RULES (test_shardcheck.py enforces it)
RULES.update({rule: "shard" for rule in (
    "shard-rule-axis",
    "shard-divisibility",
    "shard-collective",
    "shard-donation",
    "shard-kv-layout",
    "shard-bucket",
    "shard-contract",
)})
# keep in sync with hlocheck.RULES (test_hlocheck.py enforces it)
RULES.update({rule: "hlo" for rule in (
    "hlo-donation-alias",
    "hlo-materialize",
    "hlo-collective-budget",
    "hlo-peak-memory",
    "hlo-program-cache",
    "hlo-contract",
)})


def _package_files() -> list[pathlib.Path]:
    return [p for p in sorted(PACKAGE.rglob("*.py"))
            if "__pycache__" not in p.parts]


def _expand(paths: list[str]) -> list[pathlib.Path]:
    out: list[pathlib.Path] = []
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            out.extend(q for q in sorted(path.rglob("*.py"))
                       if "__pycache__" not in q.parts)
        else:
            out.append(path)
    return out


def _selected_groups(rules_arg: str | None) -> tuple[set[str], set[str]]:
    """('groups to run', 'individual rules to keep' — empty = all)."""
    if not rules_arg:
        return set(ALL_GROUPS), set()
    groups: set[str] = set()
    rules: set[str] = set()
    for tok in rules_arg.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok in ALL_GROUPS:
            groups.add(tok)
        elif tok in RULES:
            groups.add(RULES[tok])
            rules.add(tok)
        else:
            raise SystemExit(f"unknown rule or group {tok!r}; "
                             f"known: {sorted(ALL_GROUPS) + sorted(RULES)}")
    return groups, rules


def analyze_files(paths: list[pathlib.Path],
                  groups: set[str] | None = None) -> list[Finding]:
    """Run the per-file rule groups over explicit files (no import
    smoke, no semantic pass). The API the tests drive fixtures
    through."""
    return _analyze_modules([Module(p) for p in paths], groups)


def _analyze_modules(mods: list[Module],
                     groups: set[str] | None = None) -> list[Finding]:
    groups = set(GROUPS) if groups is None else groups & set(GROUPS)
    findings: list[Finding] = []
    for mod in mods:
        for g in sorted(groups):
            findings.extend(GROUPS[g](mod))
    return _dedupe(findings)


def _dedupe(findings: list[Finding]) -> list[Finding]:
    seen: set[tuple] = set()
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule,
                                             f.message)):
        k = (f.rule, f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m copilot_for_consensus_tpu.analysis",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the package "
                         "for jax/concurrency rules, the legacy "
                         "validate_python set for policy rules; "
                         "explicit paths skip the shard group)")
    ap.add_argument("--fast", action="store_true",
                    help="skip the import-smoke stage, the semantic "
                         "(shard) pass, and race's cross-module pass")
    ap.add_argument("--rules",
                    help="comma list of rule ids or groups "
                         f"({', '.join(sorted(ALL_GROUPS))}) to run")
    ap.add_argument("--group", action="append", dest="groups",
                    choices=sorted(ALL_GROUPS), metavar="GROUP",
                    help="run only this rule family (repeatable; "
                         f"one of {', '.join(sorted(ALL_GROUPS))}) — "
                         "the dev-loop filter the CI job matrix also "
                         "uses; composes with --rules by intersection")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (default: jaxlint_baseline.json "
                         "at the repo root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="print current findings as baseline JSON "
                         "(justifications left as TODO) and exit 0")
    ap.add_argument("--strict", action="store_true",
                    help="stale baseline entries are failures, not "
                         "warnings (CI uses this so the baseline "
                         "shrinks instead of rotting)")
    ap.add_argument("--format", choices=("text", "github"),
                    default="text",
                    help="finding output format; 'github' emits GitHub "
                         "Actions ::error annotations for inline PR "
                         "review")
    ap.add_argument("--output-json",
                    help="also write findings/errors as JSON to this "
                         "path (CI uploads it as a build artifact)")
    args = ap.parse_args(argv)

    groups, only_rules = _selected_groups(args.rules)
    if args.groups:
        groups &= set(args.groups)
        only_rules = {r for r in only_rules if RULES.get(r) in groups}
        if not groups:
            # a contradictory --rules/--group pairing must fail loudly
            # (rc 2), not sail through as a 0-file "CLEAN" run
            ap.error(f"--rules {args.rules!r} and --group "
                     f"{','.join(args.groups)} select no common rule "
                     "family — nothing would run")
    #: did race's cross-module (wrapper-shadow over the import graph)
    #: pass run? When it didn't, its baseline entries are exempt from
    #: stale judgment — same reasoning as dropping a skipped group.
    race_cross_ran = False
    findings: list[Finding] = []
    if args.paths:
        analyzed = _expand(args.paths)
        missing = [p for p in analyzed if not p.is_file()]
        if missing:
            for p in missing:
                print(f"jaxlint: no such file: {p}", file=sys.stderr)
            return 2
        findings = analyze_files(analyzed, groups)
        for sem_group in sorted(SEMANTIC_GROUPS & groups):
            print(f"jaxlint: {sem_group} group only runs on full-repo "
                  "invocations (it traces the contract registry, not "
                  "files); skipped", file=sys.stderr)
            # a skipped group must not judge baseline entries: keeping
            # it here would mark still-valid entries stale
            groups = groups - {sem_group}
    else:
        # The semantic workers are spawned FIRST so their ~10s
        # jax-import + trace/lower passes overlap the ast groups and
        # the import-smoke subprocess instead of serializing after
        # them. The two workers also overlap EACH OTHER — they are
        # independent subprocesses over disjoint rule families.
        shard_proc = None
        hlo_proc = None
        if "shard" in groups:
            if args.fast:
                print("jaxlint: shard group skipped under --fast",
                      file=sys.stderr)
                groups = groups - {"shard"}   # don't judge its baseline
            else:
                from copilot_for_consensus_tpu.analysis import shardcheck

                shard_proc = shardcheck.spawn_worker()
        if "hlo" in groups:
            if args.fast:
                print("jaxlint: hlo group skipped under --fast",
                      file=sys.stderr)
                groups = groups - {"hlo"}   # don't judge its baseline
            else:
                from copilot_for_consensus_tpu.analysis import hlocheck

                hlo_proc = hlocheck.spawn_worker()
        # package files get every selected ast group in ONE parse; the
        # policy extras (scripts/tools/root entry files) get policy
        # only; a semantic-only run parses nothing
        pkg = _package_files() if groups & set(GROUPS) else []
        analyzed = list(pkg)
        pkg_mods = [Module(p) for p in pkg]
        findings.extend(_analyze_modules(pkg_mods, groups))
        if "race" in groups and not args.fast:
            # cross-module wrapper-shadow: resolves base classes
            # through the package import graph (a wrapper in bus/
            # validating.py vs the concrete defaults of its ABC in
            # bus/base.py). Cheap (pure ast, trees reused from the
            # per-file pass), but it needs the whole package — hence
            # full-repo runs only.
            findings.extend(racecheck.check_cross(pkg, modules=pkg_mods))
            race_cross_ran = True
        if "policy" in groups:
            extras = [p for p in policy.policy_files()
                      if PACKAGE not in p.resolve().parents]
            analyzed += extras
            findings.extend(analyze_files(extras, {"policy"}))
            if not args.fast:
                findings.extend(policy.check_import_smoke())
        if shard_proc is not None:
            sem, sem_checked = shardcheck.check_semantic(proc=shard_proc)
            findings.extend(sem)
            seen = {p.resolve() for p in analyzed}
            analyzed += [p for p in sem_checked
                         if p.resolve() not in seen]
        if hlo_proc is not None:
            sem, sem_checked = hlocheck.check_semantic(proc=hlo_proc)
            findings.extend(sem)
            seen = {p.resolve() for p in analyzed}
            analyzed += [p for p in sem_checked
                         if p.resolve() not in seen]
        findings = _dedupe(findings)
    if only_rules:
        findings = [f for f in findings if f.rule in only_rules]

    errors: list[str] = []
    if args.write_baseline:
        print(json.dumps(baseline_entries_for(findings), indent=2))
        return 0
    if not args.no_baseline:
        entries, errors = load_baseline(pathlib.Path(args.baseline))
        # a filtered run can only judge entries for the rules it ran
        entries = [e for e in entries
                   if RULES.get(e.get("rule"), e.get("rule")) in groups
                   and (not only_rules or e.get("rule") in only_rules)]
        if not errors:
            # A justification that still starts with the
            # --write-baseline TODO placeholder is not a justification:
            # warn always, fail under --strict (finding id
            # baseline-unjustified). The entries still APPLY either way
            # — one placeholder must surface as one clear error, not as
            # a flood of resurfaced properly-baselined findings.
            for e in unjustified_entries(entries):
                msg = (f"baseline-unjustified: {e['rule']} in "
                       f"{e['path']} [{e['context']}]: justification "
                       f"still starts with TODO — explain why this "
                       f"finding is deliberate")
                if args.strict:
                    errors.append(f"jaxlint --strict: {msg}")
                else:
                    print(f"jaxlint: {msg}", file=sys.stderr)
            findings, stale = apply_baseline(findings, entries)
            # staleness is only judgeable for files this run analyzed —
            # a scoped run must not tell maintainers to prune entries
            # that still match the rest of the repo
            analyzed_rel = {rel(p) for p in analyzed}
            for e in stale:
                if e["path"] not in analyzed_rel:
                    continue
                if (e.get("rule") == "race-wrapper-shadow"
                        and not race_cross_ran):
                    # cross-module-only findings can't be judged stale
                    # by a run that skipped the cross-module pass
                    continue
                msg = (f"stale baseline entry (no longer matches): "
                       f"{e['rule']} in {e['path']} [{e['context']}]")
                if args.strict:
                    errors.append(f"jaxlint --strict: {msg}")
                else:
                    print(f"jaxlint: {msg}", file=sys.stderr)

    if args.output_json:
        payload = {
            "findings": [dataclasses.asdict(f) for f in findings],
            "errors": errors,
            "checked_files": len(analyzed),
            "groups": sorted(groups),
        }
        pathlib.Path(args.output_json).write_text(
            json.dumps(payload, indent=2) + "\n")

    if args.format == "github":
        for e in errors:
            print(f"::error title=jaxlint::{e}")
        for f in findings:
            print(f.render_github())
    else:
        for e in errors:
            print(e)
        for f in findings:
            print(f.render())
    verdict = ("CLEAN" if not (findings or errors)
               else f"{len(findings) + len(errors)} finding(s)")
    print(f"jaxlint: checked {len(analyzed)} file(s) "
          f"[{','.join(sorted(groups))}]: {verdict}", file=sys.stderr)
    return 1 if (findings or errors) else 0
