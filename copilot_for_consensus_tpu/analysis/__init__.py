"""jaxlint — first-party static analysis for the serving stack.

``python -m copilot_for_consensus_tpu.analysis`` runs every rule group
over the repo and exits non-zero on any non-baselined finding:

* ``jax`` group (jax_rules.py): host-sync-in-jit, retrace-hazard,
  donation, prng-reuse, collective-axis — the invariants that keep the
  engine's jitted hot paths fast and correct.
* ``concurrency`` group (concurrency.py): blocking-call — handler-thread
  hygiene for the bus and services.
* ``policy`` group (policy.py): the original validate_python lane
  (syntax, import smoke, mutable defaults, unused imports, bare except).

Suppression: inline ``# jaxlint: disable=<rule>`` with a justification,
or an entry in ``jaxlint_baseline.json`` (every entry must carry a
written justification). Workflow docs: ``docs/STATIC_ANALYSIS.md``.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from copilot_for_consensus_tpu.analysis import (
    concurrency,
    jax_rules,
    policy,
)
from copilot_for_consensus_tpu.analysis.base import (
    DEFAULT_BASELINE,
    Finding,
    Module,
    PACKAGE,
    apply_baseline,
    baseline_entries_for,
    load_baseline,
    rel,
)

#: group name → (per-module check, default scan roots)
GROUPS = {
    "jax": jax_rules.check,
    "concurrency": concurrency.check,
    "policy": policy.check,
}

#: every individual rule id → its group (for ``--rules`` filtering and
#: docs; keep in sync with docs/STATIC_ANALYSIS.md)
RULES = {
    "host-sync-in-jit": "jax",
    "retrace-hazard": "jax",
    "donation": "jax",
    "prng-reuse": "jax",
    "collective-axis": "jax",
    "blocking-call": "concurrency",
    "policy-syntax": "policy",
    "policy-mutable-default": "policy",
    "policy-bare-except": "policy",
    "policy-unused-import": "policy",
    "policy-import-smoke": "policy",
}


def _package_files() -> list[pathlib.Path]:
    return [p for p in sorted(PACKAGE.rglob("*.py"))
            if "__pycache__" not in p.parts]


def _expand(paths: list[str]) -> list[pathlib.Path]:
    out: list[pathlib.Path] = []
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            out.extend(q for q in sorted(path.rglob("*.py"))
                       if "__pycache__" not in q.parts)
        else:
            out.append(path)
    return out


def _selected_groups(rules_arg: str | None) -> tuple[set[str], set[str]]:
    """('groups to run', 'individual rules to keep' — empty = all)."""
    if not rules_arg:
        return set(GROUPS), set()
    groups: set[str] = set()
    rules: set[str] = set()
    for tok in rules_arg.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok in GROUPS:
            groups.add(tok)
        elif tok in RULES:
            groups.add(RULES[tok])
            rules.add(tok)
        else:
            raise SystemExit(f"unknown rule or group {tok!r}; "
                             f"known: {sorted(GROUPS) + sorted(RULES)}")
    return groups, rules


def analyze_files(paths: list[pathlib.Path],
                  groups: set[str] | None = None) -> list[Finding]:
    """Run the per-file rule groups over explicit files (no import
    smoke). The API the tests drive fixtures through."""
    groups = set(GROUPS) if groups is None else groups
    findings: list[Finding] = []
    for path in paths:
        mod = Module(path)
        for g in sorted(groups):
            findings.extend(GROUPS[g](mod))
    return _dedupe(findings)


def _dedupe(findings: list[Finding]) -> list[Finding]:
    seen: set[tuple] = set()
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule,
                                             f.message)):
        k = (f.rule, f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m copilot_for_consensus_tpu.analysis",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the package "
                         "for jax/concurrency rules, the legacy "
                         "validate_python set for policy rules)")
    ap.add_argument("--fast", action="store_true",
                    help="skip the import-smoke stage")
    ap.add_argument("--rules",
                    help="comma list of rule ids or groups "
                         f"({', '.join(sorted(GROUPS))}) to run")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (default: jaxlint_baseline.json "
                         "at the repo root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="print current findings as baseline JSON "
                         "(justifications left as TODO) and exit 0")
    args = ap.parse_args(argv)

    groups, only_rules = _selected_groups(args.rules)
    findings: list[Finding] = []
    if args.paths:
        analyzed = _expand(args.paths)
        missing = [p for p in analyzed if not p.is_file()]
        if missing:
            for p in missing:
                print(f"jaxlint: no such file: {p}", file=sys.stderr)
            return 2
        findings = analyze_files(analyzed, groups)
    else:
        # package files get every selected group in ONE parse; the
        # policy extras (scripts/tools/root entry files) get policy only
        pkg = _package_files()
        analyzed = list(pkg)
        findings.extend(analyze_files(pkg, groups))
        if "policy" in groups:
            extras = [p for p in policy.policy_files()
                      if PACKAGE not in p.resolve().parents]
            analyzed += extras
            findings.extend(analyze_files(extras, {"policy"}))
            if not args.fast:
                findings.extend(policy.check_import_smoke())
        findings = _dedupe(findings)
    if only_rules:
        findings = [f for f in findings if f.rule in only_rules]

    errors: list[str] = []
    if args.write_baseline:
        print(json.dumps(baseline_entries_for(findings), indent=2))
        return 0
    if not args.no_baseline:
        entries, errors = load_baseline(pathlib.Path(args.baseline))
        # a filtered run can only judge entries for the rules it ran
        entries = [e for e in entries
                   if RULES.get(e.get("rule"), e.get("rule")) in groups
                   and (not only_rules or e.get("rule") in only_rules)]
        if not errors:
            findings, stale = apply_baseline(findings, entries)
            # staleness is only judgeable for files this run analyzed —
            # a scoped run must not tell maintainers to prune entries
            # that still match the rest of the repo
            analyzed_rel = {rel(p) for p in analyzed}
            for e in stale:
                if e["path"] not in analyzed_rel:
                    continue
                print(f"jaxlint: stale baseline entry (no longer "
                      f"matches): {e['rule']} in {e['path']} "
                      f"[{e['context']}]", file=sys.stderr)

    for e in errors:
        print(e)
    for f in findings:
        print(f.render())
    verdict = ("CLEAN" if not (findings or errors)
               else f"{len(findings) + len(errors)} finding(s)")
    print(f"jaxlint: checked {len(analyzed)} file(s) "
          f"[{','.join(sorted(groups))}]: {verdict}", file=sys.stderr)
    return 1 if (findings or errors) else 0
