"""Rule group ``policy``: the original first-party lint lane.

This is ``scripts/validate_python.py`` folded into the analyzer (the
script remains as a thin shim for existing callers). Same checks, same
exemptions, one entry point:

* ``policy-syntax`` — every file compiles;
* ``policy-mutable-default`` — no list/dict/set literals or
  ``list()``/``dict()``/``set()`` constructor calls as parameter
  defaults;
* ``policy-bare-except`` — no ``except:`` (swallows
  KeyboardInterrupt/SystemExit);
* ``policy-unused-import`` — imported names never referenced
  (``__init__.py`` re-exports, ``noqa`` lines, and string/`__all__`
  references are exempt);
* ``policy-import-smoke`` — every package module imports in isolation
  (skipped under ``--fast``; the test suite already imports everything).
"""

from __future__ import annotations

import ast
import pathlib
import subprocess
import sys

from copilot_for_consensus_tpu.analysis.base import (
    Finding,
    Module,
    PACKAGE,
    ROOT,
    rel,
)

#: the legacy scan set: package + scripts + tools + the root entry files
#: (tests are exercised by pytest; fuzz harnesses intentionally do odd
#: things)
CHECKED_DIRS = (PACKAGE, ROOT / "scripts", ROOT / "tools")
CHECKED_FILES = (ROOT / "bench.py", ROOT / "train.py",
                 ROOT / "__graft_entry__.py")


def policy_files() -> list[pathlib.Path]:
    out = [p for d in CHECKED_DIRS if d.exists()
           for p in sorted(d.rglob("*.py"))
           if "__pycache__" not in p.parts]
    out += [p for p in CHECKED_FILES if p.exists()]
    return out


def check_syntax(mod: Module) -> list[Finding]:
    if mod.syntax_error is None:
        return []
    exc = mod.syntax_error
    return [Finding("policy-syntax", mod.relpath, exc.lineno or 1,
                    f"syntax: {exc.msg}")]


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("list", "dict", "set"))


def check_mutable_defaults(mod: Module) -> list[Finding]:
    if mod.tree is None:
        return []
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for default in (node.args.defaults
                        + [d for d in node.args.kw_defaults if d]):
            if _is_mutable_default(default):
                f = mod.finding(
                    "policy-mutable-default", default,
                    f"mutable default in {node.name}() — shared across "
                    "calls", context=mod.qualname(node))
                if f is not None:
                    out.append(f)
    return out


def check_bare_except(mod: Module) -> list[Finding]:
    if mod.tree is None:
        return []
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            f = mod.finding(
                "policy-bare-except", node,
                "bare 'except:' (swallows KeyboardInterrupt/SystemExit)")
            if f is not None:
                out.append(f)
    return out


class _ImportUse(ast.NodeVisitor):
    def __init__(self):
        self.imported: dict[str, int] = {}
        self.used: set[str] = set()

    def visit_Import(self, node):
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.imported[name] = node.lineno

    def visit_ImportFrom(self, node):
        for alias in node.names:
            if alias.name == "*":
                continue
            self.imported[alias.asname or alias.name] = node.lineno

    def visit_Name(self, node):
        self.used.add(node.id)


def check_unused_imports(mod: Module) -> list[Finding]:
    if mod.tree is None or mod.path.name == "__init__.py":
        return []                         # __init__: re-export surface
    visitor = _ImportUse()
    visitor.visit(mod.tree)
    out = []
    for name, lineno in sorted(visitor.imported.items()):
        if name in visitor.used or name == "annotations":
            continue
        line = mod.lines[lineno - 1] if lineno <= len(mod.lines) else ""
        if "noqa" in line:
            continue
        if f"\"{name}\"" in mod.source or f"'{name}'" in mod.source:
            continue                       # __all__ / string reference
        if mod.suppressions.is_suppressed("policy-unused-import", lineno):
            continue
        out.append(Finding("policy-unused-import", mod.relpath, lineno,
                           f"unused import '{name}'"))
    return out


def check_import_smoke() -> list[Finding]:
    """Import every package module in ONE subprocess (isolated from the
    caller, cheap enough for CI)."""
    modules = []
    for f in sorted(PACKAGE.rglob("*.py")):
        if "__pycache__" in f.parts:
            continue
        parts = list(f.relative_to(ROOT).with_suffix("").parts)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        if parts[-1] == "__main__":
            continue
        modules.append(".".join(parts))
    prog = (
        "import importlib, sys\n"
        "failed = []\n"
        f"for m in {modules!r}:\n"
        "    try:\n"
        "        importlib.import_module(m)\n"
        "    except Exception as exc:\n"
        "        failed.append(f'{m}: {type(exc).__name__}: {exc}')\n"
        "for f in failed:\n"
        "    print(f)\n"
        "sys.exit(1 if failed else 0)\n"
    )
    proc = subprocess.run([sys.executable, "-c", prog], cwd=ROOT,
                          capture_output=True, text=True, timeout=600)
    if proc.returncode == 0:
        return []
    lines = proc.stdout.strip().splitlines() or [
        proc.stderr.strip()[-200:]]
    return [Finding("policy-import-smoke", rel(PACKAGE), 1,
                    f"import smoke: {ln}") for ln in lines]


def check(mod: Module) -> list[Finding]:
    """Per-file policy checks (import smoke is run-level, not per-file)."""
    out = check_syntax(mod)
    out += check_mutable_defaults(mod)
    out += check_bare_except(mod)
    out += check_unused_imports(mod)
    return out
