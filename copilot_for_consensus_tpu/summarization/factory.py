"""LLM-backend driver registry (reference dispatch: ``factory.py:89-94``
of ``copilot_summarization`` — llm_local/llm_llamacpp/llm_openai/... all
collapse into ``tpu`` here, plus ``mock``)."""

from __future__ import annotations

from typing import Any

from copilot_for_consensus_tpu.core.factory import register_driver
from copilot_for_consensus_tpu.core.openai_compat import (
    azure_default_api_version,
)
from copilot_for_consensus_tpu.summarization.base import (
    MockSummarizer,
    Summarizer,
)


def _cfg_get(config: Any, key: str, default=None):
    if config is None:
        return default
    if isinstance(config, dict):
        return config.get(key, default)
    return getattr(config, key, default)


def create_summarizer(config: Any = None, **kwargs: Any) -> Summarizer:
    driver = _cfg_get(config, "driver", "mock")
    if driver == "mock":
        return MockSummarizer(
            max_sentences=int(_cfg_get(config, "max_sentences", 3)))
    if driver == "tpu":
        from copilot_for_consensus_tpu.summarization.tpu_summarizer import (
            TPUSummarizer,
        )
        return TPUSummarizer(
            model=_cfg_get(config, "model", "mistral-7b"),
            max_new_tokens=int(_cfg_get(config, "max_new_tokens", 256)),
            num_slots=int(_cfg_get(config, "num_slots", 4)),
            max_len=int(_cfg_get(config, "max_len", 4096)),
            checkpoint=_cfg_get(config, "checkpoint"),
            kv_dtype=_cfg_get(config, "kv_dtype"),
            quantize=_cfg_get(config, "quantize", "int8"),
            long_context=bool(_cfg_get(config, "long_context", False)),
            profile_dir=_cfg_get(config, "profile_dir"),
            # resilience (engine/supervisor.py): supervisor=true wires
            # watchdog + request replay + degraded-mode breakers into
            # the engine's dispatcher; deadline_s drops expired work
            supervisor=_cfg_get(config, "supervisor", None),
            deadline_s=_cfg_get(config, "deadline_s", None),
            # durable request journal (engine/journal.py): a config
            # dict {"path": ..., "checkpoint_every": ...} or the
            # "journal_path" string shorthand — either way the engine
            # warm-restarts from it, so a pipeline-process kill costs
            # latency, not work
            journal=(_cfg_get(config, "journal", None)
                     or _cfg_get(config, "journal_path", None)),
            **kwargs,
        )
    if driver in ("openai", "azure_openai"):
        # One client covers the reference's llm_openai AND
        # llm_azure_openai_gpt drivers (openai_summarizer.py:23), plus
        # any OpenAI-compatible server (vLLM/Ollama/llama.cpp).
        from copilot_for_consensus_tpu.summarization.openai_summarizer \
            import OpenAISummarizer

        return OpenAISummarizer(
            base_url=_cfg_get(config, "base_url", ""),
            api_key=_cfg_get(config, "api_key", "") or "",
            model=_cfg_get(config, "model", "gpt-4o-mini"),
            temperature=float(_cfg_get(config, "temperature", 0.2)),
            max_tokens=int(_cfg_get(config, "max_tokens", 512)),
            api_version=azure_default_api_version(
                driver, _cfg_get(config, "api_version", "")),
        )
    raise ValueError(f"unknown llm_backend driver {driver!r}")


register_driver("llm_backend", "mock", create_summarizer)
register_driver("llm_backend", "tpu", create_summarizer)
register_driver("llm_backend", "openai", create_summarizer)
register_driver("llm_backend", "azure_openai", create_summarizer)
