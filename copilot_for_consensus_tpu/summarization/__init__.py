"""Summarizer adapter (reference: ``adapters/copilot_summarization``).

Drivers: ``tpu`` (first-party continuous-batching GenerationEngine — the
replacement for Ollama/llama.cpp/OpenAI, ``factory.py:89-94`` of the
reference) and ``mock`` (extractive, parity with ``mock_summarizer.py:17``).
"""

from copilot_for_consensus_tpu.summarization.base import (
    Citation,
    MockSummarizer,
    RateLimitError,
    Summarizer,
    Summary,
    ThreadContext,
)
from copilot_for_consensus_tpu.summarization.factory import create_summarizer

__all__ = [
    "Citation",
    "MockSummarizer",
    "RateLimitError",
    "Summarizer",
    "Summary",
    "ThreadContext",
    "create_summarizer",
]
