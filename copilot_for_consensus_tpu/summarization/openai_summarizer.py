"""OpenAI-compatible chat-completions summarizer driver.

The reference speaks this API twice — ``OpenAISummarizer``
(``copilot_summarization/openai_summarizer.py:23,46``, which also serves
the azure_openai_gpt driver) and, shape-wise, its Ollama/llama.cpp local
backends. One driver here covers all of them: any endpoint implementing
``POST {base_url}/chat/completions`` (OpenAI, Azure OpenAI, vLLM,
Ollama's compat mode, llama.cpp's server) plugs into the pipeline as an
alternative to the first-party TPU engine. stdlib-HTTP only; the
container is zero-egress, so tests drive it against an in-process mock
server and real use needs network access.

Citations still come from the retrieved chunks, never parsed out of the
model's text — the reference's deliberate design
(``summarization/app/service.py:291-307``).
"""

from __future__ import annotations

from typing import Any

from copilot_for_consensus_tpu.core.openai_compat import openai_post
from copilot_for_consensus_tpu.summarization.base import (
    RateLimitError,
    SummarizationError,
    Summarizer,
    Summary,
    ThreadContext,
    citations_from_chunks,
)
from copilot_for_consensus_tpu.summarization.tpu_summarizer import (
    DEFAULT_SYSTEM,
    DEFAULT_TEMPLATE,
    build_prompt,
)


class OpenAISummarizer(Summarizer):
    """Chat-completions client. ``base_url`` up to the API root (e.g.
    ``https://api.openai.com/v1`` or ``http://ollama:11434/v1``);
    ``api_version`` switches to Azure OpenAI conventions (api-key header
    + query parameter)."""

    def __init__(self, base_url: str, *, api_key: str = "",
                 model: str = "gpt-4o-mini", temperature: float = 0.2,
                 max_tokens: int = 512, timeout_s: float = 60.0,
                 api_version: str = "",
                 template: str = DEFAULT_TEMPLATE,
                 system: str = DEFAULT_SYSTEM):
        if not base_url:
            raise ValueError("openai summarizer needs a base_url")
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key
        self.model = model
        self.temperature = temperature
        self.max_tokens = max_tokens
        self.timeout_s = timeout_s
        self.api_version = api_version
        self.template = template
        self.system = system

    def _request(self, body: dict[str, Any]) -> dict[str, Any]:
        return openai_post(
            self.base_url, "/chat/completions", body,
            api_key=self.api_key, api_version=self.api_version,
            timeout_s=self.timeout_s, error_cls=SummarizationError,
            rate_limit_cls=RateLimitError)

    def summarize(self, thread: ThreadContext) -> Summary:
        out = self._request({
            "model": self.model,
            "temperature": self.temperature,
            "max_tokens": self.max_tokens,
            "messages": [
                {"role": "system", "content": self.system},
                {"role": "user",
                 "content": build_prompt(thread, self.template, "")},
            ],
        })
        try:
            text = out["choices"][0]["message"]["content"]
        except (KeyError, IndexError, TypeError) as exc:
            raise SummarizationError(
                f"malformed completion response: {out!r:.300}") from exc
        usage = out.get("usage") or {}
        return Summary(
            thread_id=thread.thread_id,
            summary_text=(text or "").strip(),
            citations=citations_from_chunks(thread.chunks),
            model=out.get("model", self.model),
            prompt_tokens=int(usage.get("prompt_tokens", 0)),
            completion_tokens=int(usage.get("completion_tokens", 0)),
        )
