"""TPU summarizer: prompt building over the continuous-batching engine.

Replaces the reference's per-request HTTP call to Ollama
(``local_llm_summarizer.py:106-115``) with an in-process engine. Prompt
template variables match the reference's substitution set
(``summarization/app/service.py:450``: thread_id, email_chunks,
participants, message_count, subject).
"""

from __future__ import annotations


from copilot_for_consensus_tpu.summarization.base import (
    Summarizer,
    Summary,
    ThreadContext,
    citations_from_chunks,
)

DEFAULT_SYSTEM = (
    "You are a mailing-list analyst. Summarize the discussion thread "
    "faithfully, noting points of agreement and disagreement."
)
DEFAULT_TEMPLATE = (
    "{system}\n\n"
    "Thread: {subject} (id {thread_id})\n"
    "Participants: {participants}\n"
    "Messages: {message_count}\n\n"
    "Excerpts:\n{email_chunks}\n\n"
    "Summary:"
)


def build_prompt(thread: ThreadContext, template: str = DEFAULT_TEMPLATE,
                 system: str = DEFAULT_SYSTEM) -> str:
    excerpts = "\n---\n".join(
        (c.get("text") or "").strip() for c in thread.chunks)
    return template.format(
        system=system,
        subject=thread.subject,
        thread_id=thread.thread_id,
        participants=", ".join(thread.participants[:12]),
        message_count=thread.message_count,
        email_chunks=excerpts,
    )


#: per-thread template variables, in any order — everything BEFORE the
#: first of these in the template is byte-identical across threads
_THREAD_FIELDS = ("subject", "thread_id", "participants",
                  "message_count", "email_chunks")


def shared_template_head(template: str = DEFAULT_TEMPLATE,
                         system: str = DEFAULT_SYSTEM) -> str:
    """The rendered prompt span shared by EVERY thread's prompt: the
    template head up to the first per-thread placeholder, with the
    (deployment-constant) system prompt substituted. This is the span
    the summarizer marks as prefix-cache-eligible — guaranteed to
    repeat across requests, so publishing it can never pollute the
    bounded block pool with thread-unique KV."""
    cut = len(template)
    for fld in _THREAD_FIELDS:
        i = template.find("{" + fld + "}")
        if i >= 0:
            cut = min(cut, i)
    # only {system} may appear in the head; replace (not .format) so
    # stray braces in a custom template cannot raise
    return template[:cut].replace("{system}", system)


class TPUSummarizer(Summarizer):
    def __init__(self, model: str = "mistral-7b", *, engine=None,
                 tokenizer=None, max_new_tokens: int = 256,
                 template: str = DEFAULT_TEMPLATE,
                 system: str = DEFAULT_SYSTEM, num_slots: int = 4,
                 max_len: int = 4096, params=None, mesh=None, dtype=None,
                 checkpoint: str | None = None, long_engine=None,
                 long_context: bool = False, kv_dtype: str | None = None,
                 quantize: bool | str = "int8",
                 cache_scope: str = "full",
                 profile_dir: str | None = None,
                 tenant: str = "", priority: str = "",
                 supervisor=None, deadline_s: float | None = None,
                 journal=None):
        # jax imports deferred: host-only processes must not load them.
        from copilot_for_consensus_tpu.engine.tokenizer import (
            ByteTokenizer,
            Tokenizer,
        )

        self._model = model
        self.max_new_tokens = max_new_tokens
        self.template = template
        self.system = system
        #: default scheduling identity for this summarizer's requests
        #: (engine/scheduler.py); per-call kwargs override
        self.tenant = tenant
        self.priority = priority
        #: resilience (engine/supervisor.py): True/SupervisorConfig
        #: wires watchdog + containment + request replay + degraded-
        #: mode breakers into the lazily-built AsyncEngineRunner;
        #: deadline_s is the default per-request wall-clock budget
        #: (expired work is dropped, not computed)
        self.supervisor = supervisor
        self.deadline_s = deadline_s
        #: durable request journal (engine/journal.py): a path / config
        #: dict / EngineJournal, handed to the engine so a serving-
        #: process death costs latency (warm restart resumes from the
        #: journal), not work. None disables.
        self.journal = journal
        #: obs/errors.py reporter for engine dispatch failures — set by
        #: the owning service (SummarizationService wires its own); the
        #: lazily-built AsyncEngineRunner picks it up so an engine
        #: error reports with the flight-recorder dump + correlation ids
        self.error_reporter = None
        if engine is None:
            import jax.numpy as jnp

            from copilot_for_consensus_tpu.engine.generation import (
                GenerationEngine,
            )
            from copilot_for_consensus_tpu.models import decoder_config

            if checkpoint is not None:
                # Real weights: the serving default for production
                # (reference: factory dispatch to a pulled Ollama model,
                # ``factory.py:89-94``).
                engine = GenerationEngine.from_checkpoint(
                    checkpoint, mesh=mesh, num_slots=num_slots,
                    max_len=max_len, profile_dir=profile_dir,
                    kv_dtype=kv_dtype, journal=journal,
                    dtype=dtype if dtype is not None else jnp.bfloat16)
                self._model = f"checkpoint:{checkpoint}"
                if tokenizer is None:
                    from copilot_for_consensus_tpu.checkpoint import (
                        load_tokenizer,
                    )
                    tokenizer = load_tokenizer(checkpoint)
                    if tokenizer is None:
                        # A byte-level fallback against a BPE-trained
                        # model yields garbage; refuse loudly.
                        raise ValueError(
                            f"checkpoint {checkpoint} has no "
                            "tokenizer.json; pass tokenizer= explicitly")
            else:
                # No checkpoint: random weights (bench/dev). Serving
                # dtypes still matter — a 7B bf16 init would not fit one
                # chip, so weights default to int8 (checkpoints carry
                # their own quantization mode in metadata instead).
                cfg = decoder_config(model)
                engine = GenerationEngine(
                    cfg, params, mesh=mesh, num_slots=num_slots,
                    max_len=min(max_len, cfg.max_seq_len),
                    profile_dir=profile_dir, kv_dtype=kv_dtype,
                    quantize=quantize, journal=journal,
                    dtype=dtype if dtype is not None else jnp.bfloat16)
        self.engine = engine
        if long_engine is None and long_context:
            from copilot_for_consensus_tpu.engine.longctx import (
                LongContextEngine,
            )
            if mesh is None:
                # Config-driven default: shard the sequence over every
                # local device (the short engine holds its own mesh or
                # none; the long engine's parallelism is sp by design).
                import jax as _jax

                from copilot_for_consensus_tpu.parallel import (
                    MeshConfig,
                    build_mesh,
                )
                mesh = build_mesh(
                    MeshConfig(dp=1, sp=len(_jax.devices()), ep=1, tp=1))
            long_engine = LongContextEngine(
                engine.cfg, engine.params, mesh=mesh,
                eos_id=sorted(engine._eos_set),
                max_new_tokens=max_new_tokens,
                profile_dir=profile_dir)
        # Whole-thread contexts beyond the batch engine's window route to
        # the sequence-parallel long-context engine instead of being
        # tail-truncated (the reference's only strategy is top-k
        # truncation to a token budget, ``context_selectors.py:94-107``).
        self.long_engine = long_engine
        self.tokenizer: Tokenizer = tokenizer or ByteTokenizer(
            max(259, self.engine.cfg.vocab_size))
        if self.tokenizer.vocab_size > self.engine.cfg.vocab_size:
            raise ValueError("tokenizer vocab exceeds model vocab")
        # Prefix-cache publish scope — how much of each prompt this
        # summarizer marks cache-eligible (GenerationEngine.submit's
        # cache_eligible_tokens):
        #   "full"     — whole prompt; thread re-summarization re-sends
        #                a near-identical context prefix, so the engine
        #                may reuse past the template (LRU handles churn);
        #   "template" — only the shared template head (every prompt
        #                opens with it); right for small block pools
        #                where thread-unique context KV would evict the
        #                always-hot template blocks;
        #   "off"      — never publish from this summarizer.
        if cache_scope not in ("full", "template", "off"):
            raise ValueError(f"unknown cache_scope {cache_scope!r}")
        self.cache_scope = cache_scope
        if cache_scope == "template":
            # Token count of the span shared across ALL prompts. BPE
            # merges at the boundary may differ between encoding the
            # head alone and a full prompt; the publish cap is
            # block-aligned anyway, so shaving one boundary token keeps
            # the marked span strictly inside the shared bytes.
            head = shared_template_head(self.template, self.system)
            self._cache_eligible = max(
                0, len(self.tokenizer.encode(head, add_bos=True)) - 1)
        elif cache_scope == "off":
            self._cache_eligible = 0
        else:
            self._cache_eligible = None

    @property
    def _short_limit(self) -> int:
        return self.engine.prompt_limit

    def summarize(self, thread: ThreadContext) -> Summary:
        return self.summarize_batch([thread])[0]

    def _engine_generate(self, prompts: list[list[int]]) -> list:
        """All short-path generation funnels through here so the
        single-owner invariant holds: once summarize_async has started
        the dispatcher thread, IT owns the engine, and synchronous
        callers must route through it rather than racing device calls
        from their own thread."""
        runner = getattr(self, "_runner", None)
        if runner is None:
            return self.engine.generate(
                prompts, self.max_new_tokens,
                cache_eligible_tokens=self._cache_eligible)
        handles = [runner.submit(p, self.max_new_tokens,
                                 cache_eligible_tokens=self._cache_eligible,
                                 tenant=self.tenant,
                                 priority=self.priority,
                                 deadline_s=self.deadline_s)
                   for p in prompts]
        return [self._checked(h.result(timeout=600.0))
                for h in handles]

    @staticmethod
    def _checked(comp):
        """A deadline-expired completion (dropped un-computed, empty
        tokens) must surface as a structured FAILURE, not decode into
        an empty 'successful' summary the service would store and
        publish — the bus retry policy is the recovery layer here,
        same as every other engine failure mode."""
        if comp.finish_reason == "deadline" and not comp.tokens:
            from copilot_for_consensus_tpu.engine.supervisor import (
                EngineFailed,
            )
            raise EngineFailed(
                f"request {comp.request_id} dropped at its deadline "
                f"before any tokens were generated",
                request_id=comp.request_id, reason="deadline-expired")
        return comp

    def summarize_async(self, thread: ThreadContext, *,
                        correlation_id: str = "", tenant: str = "",
                        priority: str = ""):
        """Submit one thread into the continuous batch WITHOUT waiting:
        returns a zero-arg callable that blocks for and returns the
        Summary. Many in-flight submissions share the decode batch —
        this is what actually fills the engine's slots when callers
        (the summarization service) receive work one event at a time.
        Long-context prompts fall back to the synchronous path (the
        sp-sharded engine is single-request by design).

        ``correlation_id`` (the pipeline event id) tags the request's
        engine telemetry span, so a flight-recorder dump or engine
        error report names the pipeline event, not just a slot.
        ``tenant``/``priority`` (falling back to the summarizer's
        defaults) feed the engine scheduler's fairness and shedding
        policy; an overloaded scheduler raises ``EngineOverloaded``
        HERE, synchronously, so the caller can back off honestly."""
        from copilot_for_consensus_tpu.engine.async_runner import (
            AsyncEngineRunner,
        )

        fi = getattr(self.engine, "faults", None)
        if fi is not None:
            # tokenization is a host boundary of the serving path too —
            # the chaos harness scripts kind="tokenize" faults here; an
            # injected fault raises synchronously and the service's
            # failure handling contains it like any bad request
            fi.check("tokenize")
        prompt = self.tokenizer.encode(
            build_prompt(thread, self.template, self.system),
            add_bos=True)
        if self.long_engine is not None and \
                len(prompt) > self._short_limit:
            # The long engine is a separate device program owner, so the
            # synchronous call cannot race the batch engine's dispatcher
            # thread (self.engine must NOT be driven here: once a runner
            # exists it is the engine's single owner).
            comp = self.long_engine.generate(
                prompt, max_new_tokens=self.max_new_tokens,
                correlation_id=correlation_id)
            summary = Summary(
                thread_id=thread.thread_id,
                summary_text=self.tokenizer.decode(comp.tokens).strip(),
                citations=citations_from_chunks(thread.chunks),
                model=f"tpu:{self._model}",
                prompt_tokens=comp.prompt_len,
                completion_tokens=len(comp.tokens),
            )
            return lambda timeout=None: summary
        if getattr(self, "_runner", None) is None:
            self._runner = AsyncEngineRunner(
                self.engine,
                error_reporter=self.error_reporter,
                supervisor=self.supervisor).start()
        handle = self._runner.submit(
            prompt, self.max_new_tokens,
            cache_eligible_tokens=self._cache_eligible,
            correlation_id=correlation_id,
            tenant=tenant or self.tenant,
            priority=priority or self.priority,
            deadline_s=self.deadline_s)

        def wait(timeout: float | None = 600.0) -> Summary:
            comp = self._checked(handle.result(timeout))
            return Summary(
                thread_id=thread.thread_id,
                summary_text=self.tokenizer.decode(comp.tokens).strip(),
                citations=citations_from_chunks(thread.chunks),
                model=f"tpu:{self._model}",
                prompt_tokens=comp.prompt_len,
                completion_tokens=len(comp.tokens),
            )

        return wait

    def drain(self, deadline_s: float = 30.0) -> bool:
        """Graceful-drain hook (``Pipeline.drain_engines``): wait for
        the dispatcher to finish queued + active work up to
        ``deadline_s``, then stop it — whatever did not finish stays
        checkpointed in the engine journal for the next process to
        resume. True when the engine fully drained."""
        runner = getattr(self, "_runner", None)
        if runner is None:
            return True
        drained = runner.drain(deadline_s)
        runner.stop()
        self._runner = None
        return drained

    def close(self) -> None:
        runner = getattr(self, "_runner", None)
        if runner is not None:
            runner.stop()
            self._runner = None

    def summarize_batch(self, threads: list[ThreadContext]) -> list[Summary]:
        """Continuous batching: all threads share the decode batch; any
        prompt exceeding the batch window runs on the long-context path."""
        prompts = [
            self.tokenizer.encode(
                build_prompt(t, self.template, self.system), add_bos=True)
            for t in threads
        ]
        comps: list = [None] * len(threads)
        short_idx = list(range(len(threads)))
        if self.long_engine is not None:
            long_set = {i for i in short_idx
                        if len(prompts[i]) > self._short_limit}
            short_idx = [i for i in short_idx if i not in long_set]
            long_idx = sorted(long_set)
            for i in long_idx:
                comps[i] = self.long_engine.generate(
                    prompts[i], max_new_tokens=self.max_new_tokens)
        if short_idx:
            for i, c in zip(short_idx, self._engine_generate(
                    [prompts[i] for i in short_idx])):
                comps[i] = c
        out = []
        for thread, comp in zip(threads, comps):
            out.append(Summary(
                thread_id=thread.thread_id,
                summary_text=self.tokenizer.decode(comp.tokens).strip(),
                citations=citations_from_chunks(thread.chunks),
                model=f"tpu:{self._model}",
                prompt_tokens=comp.prompt_len,
                completion_tokens=len(comp.tokens),
            ))
        return out
