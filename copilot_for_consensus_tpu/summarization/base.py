"""Summarizer ABC + domain models + mock driver.

Models mirror the reference's ``copilot_summarization/models.py:10-65``
(Citation / Thread / Summary) and the ABC mirrors
``summarizer.py:11-32`` (``summarize(Thread) -> Summary``). Citations are
derived from the retrieved chunks, not parsed out of LLM output — the
reference's deliberate choice (``summarization/app/service.py:291-307``)
kept here.
"""

from __future__ import annotations

import abc
import time
from dataclasses import dataclass, field
from typing import Any


class SummarizationError(Exception):
    pass


class RateLimitError(SummarizationError):
    """Backend asked us to slow down (reference
    ``openai_summarizer.py:23,46``); the service retry loop waits."""

    def __init__(self, message: str = "", retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclass
class Citation:
    chunk_id: str
    message_doc_id: str = ""
    snippet: str = ""
    score: float = 0.0


@dataclass
class ThreadContext:
    """What the summarizer sees: the thread plus pre-selected context."""

    thread_id: str
    subject: str = ""
    participants: list[str] = field(default_factory=list)
    message_count: int = 0
    chunks: list[dict[str, Any]] = field(default_factory=list)
    # each chunk dict: {chunk_id, message_doc_id, text, score}
    context_window_tokens: int = 4096


@dataclass
class Summary:
    thread_id: str
    summary_text: str
    citations: list[Citation] = field(default_factory=list)
    model: str = ""
    generated_at: float = field(default_factory=time.time)
    prompt_tokens: int = 0
    completion_tokens: int = 0


class Summarizer(abc.ABC):
    @abc.abstractmethod
    def summarize(self, thread: ThreadContext) -> Summary: ...

    def close(self) -> None:
        pass


def citations_from_chunks(chunks: list[dict[str, Any]],
                          max_snippet: int = 160) -> list[Citation]:
    return [
        Citation(
            chunk_id=c.get("chunk_id", ""),
            message_doc_id=c.get("message_doc_id", ""),
            snippet=(c.get("text") or "")[:max_snippet],
            score=float(c.get("score", 0.0)),
        )
        for c in chunks
    ]


class MockSummarizer(Summarizer):
    """Extractive mock: first sentences of the top chunks. Deterministic,
    dependency-free — the test backbone, like the reference's
    ``MockSummarizer`` (``mock_summarizer.py:17``)."""

    def __init__(self, max_sentences: int = 3):
        self.max_sentences = max_sentences

    def summarize(self, thread: ThreadContext) -> Summary:
        sentences: list[str] = []
        for chunk in thread.chunks[: self.max_sentences]:
            text = (chunk.get("text") or "").strip().replace("\n", " ")
            if text:
                sentences.append(text.split(". ")[0][:200].strip())
        body = ". ".join(sentences) if sentences else "(no content)"
        return Summary(
            thread_id=thread.thread_id,
            summary_text=f"Thread '{thread.subject}' with "
                         f"{thread.message_count} message(s): {body}",
            citations=citations_from_chunks(thread.chunks),
            model="mock",
        )
