"""Fetcher driver registry."""

from __future__ import annotations

from typing import Any

from copilot_for_consensus_tpu.core.factory import register_driver
from copilot_for_consensus_tpu.fetch.base import (
    ArchiveFetcher,
    HTTPFetcher,
    IMAPFetcher,
    LocalFetcher,
    MockFetcher,
    RsyncFetcher,
)

_DRIVERS = {
    "local": LocalFetcher,
    "http": HTTPFetcher,
    "imap": IMAPFetcher,
    "rsync": RsyncFetcher,
    "mock": MockFetcher,
}


def create_archive_fetcher(config: Any = None, **kwargs: Any
                           ) -> ArchiveFetcher:
    driver = "local"
    if config is not None:
        driver = (config.get("driver", "local")
                  if isinstance(config, dict)
                  else getattr(config, "driver", "local"))
    cls = _DRIVERS.get(driver)
    if cls is None:
        raise ValueError(f"unknown archive_fetcher driver {driver!r}")
    return cls(**kwargs)


for _name in _DRIVERS:
    register_driver("archive_fetcher", _name, create_archive_fetcher)
