"""Archive fetchers (reference: ``adapters/copilot_archive_fetcher``)."""

from copilot_for_consensus_tpu.fetch.base import (
    ArchiveFetcher,
    FetchedArchive,
    FetchError,
    LocalFetcher,
    MockFetcher,
    SourceConfig,
)
from copilot_for_consensus_tpu.fetch.factory import create_archive_fetcher

__all__ = [
    "ArchiveFetcher",
    "FetchedArchive",
    "FetchError",
    "LocalFetcher",
    "MockFetcher",
    "SourceConfig",
    "create_archive_fetcher",
]
