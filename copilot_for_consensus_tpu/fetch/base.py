"""ArchiveFetcher ABC + drivers.

Reference surface: ``copilot_archive_fetcher/base.py:13`` with HTTP /
IMAP / Local / Rsync drivers and ``SourceConfig`` (``models.py:22``).
This container is zero-egress, so the network drivers (http, imap,
rsync) exist as config-selectable stubs that fail with a clear error
unless the runtime has network access; ``local`` and ``mock`` carry the
pipeline.
"""

from __future__ import annotations

import abc
import pathlib
from dataclasses import dataclass, field
from typing import Any, Iterator


class FetchError(Exception):
    pass


@dataclass
class SourceConfig:
    name: str
    fetcher: str = "local"                 # local|http|imap|rsync|mock
    location: str = ""                     # path / url / server
    enabled: bool = True
    schedule_seconds: int = 0              # 0 = manual trigger only
    options: dict[str, Any] = field(default_factory=dict)


@dataclass
class FetchedArchive:
    uri: str                               # where it came from
    filename: str
    content: bytes


class ArchiveFetcher(abc.ABC):
    @abc.abstractmethod
    def fetch(self, source: SourceConfig) -> Iterator[FetchedArchive]:
        """Yield archives for the source (an mbox file each)."""


class LocalFetcher(ArchiveFetcher):
    """Reads mbox files from a local path (file or directory)."""

    def fetch(self, source: SourceConfig) -> Iterator[FetchedArchive]:
        path = pathlib.Path(source.location)
        if not path.exists():
            raise FetchError(f"local path does not exist: {path}")
        files = [path] if path.is_file() else sorted(
            p for p in path.iterdir()
            if p.is_file() and p.suffix in (".mbox", ".mail", ".txt", ""))
        for f in files:
            yield FetchedArchive(uri=str(f), filename=f.name,
                                 content=f.read_bytes())


class MockFetcher(ArchiveFetcher):
    """Returns canned archives injected at construction (tests)."""

    def __init__(self, archives: list[FetchedArchive] | None = None):
        self.archives = archives or []

    def fetch(self, source: SourceConfig) -> Iterator[FetchedArchive]:
        yield from self.archives


class HTTPFetcher(ArchiveFetcher):
    """Downloads archives over HTTP(S) (stdlib urllib; reference
    ``http_fetcher.py:15``). Fails fast in zero-egress environments."""

    def __init__(self, timeout_s: float = 30.0):
        self.timeout_s = timeout_s

    def fetch(self, source: SourceConfig) -> Iterator[FetchedArchive]:
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(source.location,
                                        timeout=self.timeout_s) as resp:
                content = resp.read()
        except (urllib.error.URLError, OSError) as exc:
            raise FetchError(f"http fetch failed for {source.location}: "
                             f"{exc}") from exc
        name = source.location.rstrip("/").rsplit("/", 1)[-1] or "archive.mbox"
        yield FetchedArchive(uri=source.location, filename=name, content=content)


class IMAPFetcher(ArchiveFetcher):
    """IMAP mailbox export (reference ``imap_fetcher.py:17``). Requires
    network; options: mailbox, username, password_secret."""

    def fetch(self, source: SourceConfig) -> Iterator[FetchedArchive]:
        import imaplib

        opts = source.options
        try:
            conn = imaplib.IMAP4_SSL(source.location)
            conn.login(opts.get("username", ""), opts.get("password", ""))
            conn.select(opts.get("mailbox", "INBOX"), readonly=True)
            _, data = conn.search(None, "ALL")
            lines = []
            for num in data[0].split():
                _, msg_data = conn.fetch(num, "(RFC822)")
                raw = msg_data[0][1]
                lines.append(b"From fetcher@imap\n" + raw + b"\n")
            conn.logout()
        except (OSError, imaplib.IMAP4.error) as exc:
            raise FetchError(f"imap fetch failed for {source.location}: "
                             f"{exc}") from exc
        yield FetchedArchive(uri=f"imap://{source.location}",
                             filename=f"{source.name}.mbox",
                             content=b"".join(lines))


class RsyncFetcher(ArchiveFetcher):
    """rsync-based mirror (reference ``rsync_fetcher.py:16``): syncs the
    remote path into a scratch dir, then reads like LocalFetcher."""

    def __init__(self, scratch_dir: str = "/tmp/copilot-rsync"):
        self.scratch_dir = scratch_dir

    def fetch(self, source: SourceConfig) -> Iterator[FetchedArchive]:
        import subprocess

        dest = pathlib.Path(self.scratch_dir) / source.name
        dest.mkdir(parents=True, exist_ok=True)
        proc = subprocess.run(
            ["rsync", "-az", "--timeout=60", source.location, str(dest) + "/"],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise FetchError(f"rsync failed for {source.location}: "
                             f"{proc.stderr.strip()}")
        yield from LocalFetcher().fetch(
            SourceConfig(name=source.name, location=str(dest)))
