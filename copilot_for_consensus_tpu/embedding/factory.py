"""Embedding driver registry + factory (reference: ``factory.py:26`` of
``copilot_embedding``)."""

from __future__ import annotations

from typing import Any

from copilot_for_consensus_tpu.core.factory import register_driver
from copilot_for_consensus_tpu.core.openai_compat import (
    azure_default_api_version,
)
from copilot_for_consensus_tpu.embedding.base import (
    EmbeddingProvider,
    MockEmbeddingProvider,
    TPUEmbeddingProvider,
)


def _cfg_get(config: Any, key: str, default=None):
    if config is None:
        return default
    if isinstance(config, dict):
        return config.get(key, default)
    return getattr(config, key, default)


def create_embedding_provider(config: Any = None) -> EmbeddingProvider:
    driver = _cfg_get(config, "driver", "mock")
    if driver == "mock":
        return MockEmbeddingProvider(
            dimension=int(_cfg_get(config, "dimension", 32)))
    if driver == "tpu":
        return TPUEmbeddingProvider(
            model=_cfg_get(config, "model", "minilm-l6"),
            checkpoint=_cfg_get(config, "checkpoint"),
            batch_size=int(_cfg_get(config, "batch_size", 64)))
    if driver in ("openai", "azure_openai"):
        from copilot_for_consensus_tpu.embedding.openai_provider import (
            OpenAIEmbeddingProvider,
        )

        return OpenAIEmbeddingProvider(
            base_url=_cfg_get(config, "base_url", ""),
            api_key=_cfg_get(config, "api_key", "") or "",
            model=_cfg_get(config, "model", "text-embedding-3-small"),
            dimension=int(_cfg_get(config, "dimension", 1536)),
            api_version=azure_default_api_version(
                driver, _cfg_get(config, "api_version", "")),
            batch_size=int(_cfg_get(config, "batch_size", 256)))
    raise ValueError(f"unknown embedding driver {driver!r}")


register_driver("embedding_backend", "mock", create_embedding_provider)
register_driver("embedding_backend", "tpu", create_embedding_provider)
register_driver("embedding_backend", "openai", create_embedding_provider)
register_driver("embedding_backend", "azure_openai",
                create_embedding_provider)
