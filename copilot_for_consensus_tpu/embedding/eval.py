"""Retrieval-quality evaluation: recall@k over a labeled fixture.

The reference's semantic-search quality rests on pretrained
sentence-transformers weights (``sentence_transformer_provider.py:19-51``)
and is never measured in-repo. Here retrieval quality is a first-class,
testable number: embed a labeled corpus, query through the on-device
vector store, and report recall@k — so the random-weight hashed-BoW
fallback can never silently masquerade as semantic retrieval again.

The synthetic fixture is built for exactly that distinction: every topic
has two *disjoint* vocabularies — documents draw from one, queries from
the other — so lexical/hash overlap carries zero signal and only an
encoder that has learned the topic structure (contrastively tuned or
pretrained) can score.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np


@dataclass
class RetrievalFixture:
    """Labeled corpus: docs, queries, and relevance sets (qrels)."""

    docs: list[dict] = field(default_factory=list)       # {id, text, topic}
    queries: list[dict] = field(default_factory=list)    # {id, text, relevant}

    def training_pairs(self, n: int, seed: int = 0,
                       batch: int | None = None) -> list[tuple[str, str]]:
        """(query-style, doc-style) same-topic pairs, freshly sampled —
        never the eval queries themselves. With ``batch`` set, topics
        within each batch-sized block are drawn without replacement, so
        in-batch InfoNCE negatives are never same-topic false negatives."""
        rng = random.Random(seed + 7)
        topics = sorted({d["topic"] for d in self.docs})
        out: list[tuple[str, str]] = []
        if batch is None:
            return [_pair_for_topic(rng.choice(topics), rng)
                    for _ in range(n)]
        while len(out) < n:
            # Without replacement per block; when batch > n_topics,
            # cycle fresh permutations (collisions then unavoidable but
            # minimized).
            block: list[int] = []
            while len(block) < batch:
                block.extend(rng.sample(topics, len(topics)))
            out.extend(_pair_for_topic(t, rng) for t in block[:batch])
        return out[:n]


def _topic_vocab(topic: int, style: str, size: int = 16) -> list[str]:
    return [f"{style}{topic}w{i}" for i in range(size)]


def _sample_text(topic: int, style: str, rng: random.Random,
                 n_words: int) -> str:
    vocab = _topic_vocab(topic, style)
    return " ".join(rng.choice(vocab) for _ in range(n_words))


def _pair_for_topic(topic: int, rng: random.Random) -> tuple[str, str]:
    return (_sample_text(topic, "q", rng, 6),
            _sample_text(topic, "d", rng, 12))


def synthetic_fixture(n_topics: int = 8, docs_per_topic: int = 8,
                      queries_per_topic: int = 4,
                      seed: int = 0) -> RetrievalFixture:
    """Deterministic labeled fixture with doc/query vocabulary disjointness
    (see module docstring)."""
    rng = random.Random(seed)
    fx = RetrievalFixture()
    for t in range(n_topics):
        doc_ids = []
        for i in range(docs_per_topic):
            doc_id = f"t{t}d{i}"
            doc_ids.append(doc_id)
            fx.docs.append({"id": doc_id, "topic": t,
                            "text": _sample_text(t, "d", rng, 12)})
        for i in range(queries_per_topic):
            fx.queries.append({"id": f"t{t}q{i}", "topic": t,
                               "text": _sample_text(t, "q", rng, 6),
                               "relevant": list(doc_ids)})
    return fx


def recall_at_k(embed_fn: Callable[[Sequence[str]], np.ndarray],
                fixture: RetrievalFixture,
                ks: Sequence[int] = (1, 5, 10)) -> dict[str, float]:
    """Embed docs+queries with ``embed_fn`` ([N texts] → [N, dim]), rank
    by cosine, and report mean recall@k = |top-k ∩ relevant| / min(k, R).
    Retrieval runs through the on-device vector store — the same ANN
    path production queries take."""
    from copilot_for_consensus_tpu.vectorstore.tpu import TPUVectorStore

    doc_vecs = np.asarray(embed_fn([d["text"] for d in fixture.docs]),
                          dtype=np.float32)
    q_vecs = np.asarray(embed_fn([q["text"] for q in fixture.queries]),
                        dtype=np.float32)
    store = TPUVectorStore({"dimension": int(doc_vecs.shape[1]),
                            "dtype": "float32"})
    store.add_embeddings([(d["id"], v.tolist(), None)
                          for d, v in zip(fixture.docs, doc_vecs)])
    out: dict[str, float] = {}
    max_k = max(ks)
    hits_per_q = []
    for q, vec in zip(fixture.queries, q_vecs):
        got = store.query(vec.tolist(), top_k=max_k)
        hits_per_q.append(([g.id for g in got], set(q["relevant"])))
    for k in ks:
        vals = [len(set(ids[:k]) & rel) / min(k, len(rel))
                for ids, rel in hits_per_q]
        out[f"recall@{k}"] = float(np.mean(vals))
    return out


def train_encoder_on_fixture(fixture: RetrievalFixture, *, cfg=None,
                             steps: int = 60, batch: int = 16,
                             lr: float = 3e-3, seed: int = 0,
                             max_len: int = 16):
    """Contrastively tune a small encoder on fixture-style pairs; returns
    (cfg, params, tokenizer) ready for an EmbeddingEngine. The proof-of-
    loop behind ``scripts/eval_retrieval.py --backend trained``."""
    import jax
    import jax.numpy as jnp

    from copilot_for_consensus_tpu import train
    from copilot_for_consensus_tpu.engine.tokenizer import HashWordTokenizer
    from copilot_for_consensus_tpu.models import encoder
    from copilot_for_consensus_tpu.models.configs import EncoderConfig

    cfg = cfg or EncoderConfig(name="tiny-retrieval", vocab_size=2048,
                               d_model=64, n_layers=2, n_heads=4, d_ff=128,
                               max_positions=max_len)
    tok = HashWordTokenizer(cfg.vocab_size)
    params = encoder.init_params(jax.random.PRNGKey(seed), cfg,
                                 dtype=jnp.float32)
    optimizer = train.default_optimizer(lr)
    step = jax.jit(train.make_contrastive_step(cfg, optimizer))
    opt_state = optimizer.init(params)

    rng = random.Random(seed + 1)
    pairs = fixture.training_pairs(steps * batch, seed=seed, batch=batch)

    def batch_tokens(texts: list[str]):
        toks = np.zeros((len(texts), max_len), dtype=np.int32)
        lens = np.ones(len(texts), dtype=np.int32)
        for i, t in enumerate(texts):
            ids = tok.encode(t)[:max_len]
            toks[i, :len(ids)] = ids
            lens[i] = max(1, len(ids))
        return jnp.asarray(toks), jnp.asarray(lens)

    loss = None
    for s in range(steps):
        chunk = pairs[s * batch:(s + 1) * batch]
        rng.shuffle(chunk)
        qt, ql = batch_tokens([q for q, _ in chunk])
        pt, pl = batch_tokens([p for _, p in chunk])
        params, opt_state, loss = step(params, opt_state, qt, ql, pt, pl)
    return cfg, params, tok, (float(loss) if loss is not None else None)
