"""Embedding-provider adapter (reference: ``adapters/copilot_embedding``).

Drivers: ``tpu`` (first-party EmbeddingEngine — the point of this
framework), ``mock`` (deterministic hash vectors for tests, parity with
``mock_provider.py:15``).
"""

from copilot_for_consensus_tpu.embedding.base import (
    EmbeddingProvider,
    MockEmbeddingProvider,
)
from copilot_for_consensus_tpu.embedding.factory import (
    create_embedding_provider,
)

__all__ = [
    "EmbeddingProvider",
    "MockEmbeddingProvider",
    "create_embedding_provider",
]
