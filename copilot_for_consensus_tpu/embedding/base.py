"""EmbeddingProvider ABC + mock driver.

Interface parity with the reference ABC
(``copilot_embedding/base.py:12-25``: ``embed(text) -> list[float]``),
extended with the batched call the reference lacks — its embedding
service loops ``embed()`` per text (``embedding/app/service.py:393``);
our services call ``embed_batch`` and get real cross-text batching.
"""

from __future__ import annotations

import abc
import hashlib
import math
from typing import Sequence


class EmbeddingError(Exception):
    pass


from copilot_for_consensus_tpu.core.retry import (  # noqa: E402
    RetryableError as _RetryableError,
)


class EmbeddingRateLimitError(EmbeddingError, _RetryableError):
    """Backend 429: transient by definition. Also a RetryableError, so
    the service retry loop backs off and re-attempts instead of
    terminally failing the document's embedding."""

    def __init__(self, message: str = "", retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class EmbeddingProvider(abc.ABC):
    @property
    @abc.abstractmethod
    def dimension(self) -> int: ...

    @property
    def model_name(self) -> str:
        return type(self).__name__

    @abc.abstractmethod
    def embed(self, text: str) -> list[float]: ...

    def embed_batch(self, texts: Sequence[str]) -> list[list[float]]:
        return [self.embed(t) for t in texts]


class MockEmbeddingProvider(EmbeddingProvider):
    """Deterministic, normalized hash vectors. Texts sharing words get
    correlated vectors, so top-k retrieval behaves sensibly in tests."""

    def __init__(self, dimension: int = 32):
        self._dim = dimension

    @property
    def dimension(self) -> int:
        return self._dim

    @property
    def model_name(self) -> str:
        return "mock"

    def embed(self, text: str) -> list[float]:
        vec = [0.0] * self._dim
        for word in (text or "").lower().split():
            h = hashlib.sha1(word.encode()).digest()
            idx = int.from_bytes(h[:4], "big") % self._dim
            sign = 1.0 if h[4] % 2 else -1.0
            vec[idx] += sign
        norm = math.sqrt(sum(x * x for x in vec)) or 1.0
        return [x / norm for x in vec]


class TPUEmbeddingProvider(EmbeddingProvider):
    """First-party TPU encoder behind the adapter interface."""

    def __init__(self, model: str = "minilm-l6", *, params=None, mesh=None,
                 tokenizer=None, batch_size: int = 64, dtype=None,
                 checkpoint: str | None = None, attn_impl: str = "auto"):
        # Heavy imports deferred so host-only processes never load jax.
        import jax.numpy as jnp

        from copilot_for_consensus_tpu.engine.embedding import EmbeddingEngine
        from copilot_for_consensus_tpu.models import encoder_config

        if checkpoint is not None:
            # Real weights (BERT/MiniLM-family HF dir) — the serving
            # default for production retrieval quality.
            self._engine = EmbeddingEngine.from_checkpoint(
                checkpoint, mesh=mesh, tokenizer=tokenizer,
                batch_size=batch_size, attn_impl=attn_impl)
            self._model = f"checkpoint:{checkpoint}"
        else:
            cfg = encoder_config(model)
            self._engine = EmbeddingEngine(
                cfg, params, mesh=mesh, tokenizer=tokenizer,
                batch_size=batch_size, dtype=dtype or jnp.bfloat16,
                attn_impl=attn_impl)
            self._model = model

    @property
    def dimension(self) -> int:
        return self._engine.dimension

    @property
    def model_name(self) -> str:
        return f"tpu:{self._model}"

    def embed(self, text: str) -> list[float]:
        return self._engine.embed(text)

    def embed_batch(self, texts: Sequence[str]) -> list[list[float]]:
        return self._engine.embed_batch(texts).tolist()
