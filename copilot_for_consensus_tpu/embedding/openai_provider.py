"""OpenAI-compatible ``/embeddings`` provider driver.

Covers the reference's ``OpenAIEmbeddingProvider``
(``copilot_embedding/openai_provider.py:20``) — and any endpoint
implementing the same API (Azure OpenAI, vLLM, Ollama compat, TEI) —
as an alternative to the first-party TPU encoder. stdlib-HTTP only;
zero-egress tests drive an in-process mock server. Real batching: one
request per ``embed_batch`` call, not one per text (the reference loops
``embed()`` per chunk — its own SLO bottleneck)."""

from __future__ import annotations

from typing import Any, Sequence

from copilot_for_consensus_tpu.core.openai_compat import openai_post
from copilot_for_consensus_tpu.embedding.base import (
    EmbeddingError,
    EmbeddingProvider,
    EmbeddingRateLimitError,
)


class OpenAIEmbeddingProvider(EmbeddingProvider):
    def __init__(self, base_url: str, *, api_key: str = "",
                 model: str = "text-embedding-3-small",
                 dimension: int = 1536, timeout_s: float = 30.0,
                 api_version: str = "", batch_size: int = 256):
        if not base_url:
            raise ValueError("openai embedding provider needs a base_url")
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key
        self.model = model
        self._dimension = dimension
        self.timeout_s = timeout_s
        self.api_version = api_version
        self.batch_size = batch_size

    @property
    def dimension(self) -> int:
        return self._dimension

    @property
    def model_name(self) -> str:
        return self.model

    def _request(self, texts: Sequence[str]) -> list[list[float]]:
        out = openai_post(
            self.base_url, "/embeddings",
            {"model": self.model, "input": list(texts)},
            api_key=self.api_key, api_version=self.api_version,
            timeout_s=self.timeout_s, error_cls=EmbeddingError,
            rate_limit_cls=EmbeddingRateLimitError)
        try:
            rows: list[Any] = sorted(out["data"], key=lambda d: d["index"])
            vecs = [list(map(float, d["embedding"])) for d in rows]
        except (KeyError, TypeError) as exc:
            raise EmbeddingError(
                f"malformed embeddings response: {out!r:.300}") from exc
        if len(vecs) != len(texts):
            raise EmbeddingError(
                f"backend returned {len(vecs)} vectors for "
                f"{len(texts)} inputs")
        return vecs

    def embed(self, text: str) -> list[float]:
        return self._request([text])[0]

    def embed_batch(self, texts: Sequence[str]) -> list[list[float]]:
        out: list[list[float]] = []
        for i in range(0, len(texts), self.batch_size):
            out.extend(self._request(texts[i:i + self.batch_size]))
        return out
