"""Error reporting abstraction (parity with ``copilot_error_reporting``)."""

from __future__ import annotations

import abc
import traceback
from typing import Any

from copilot_for_consensus_tpu.obs.logging import Logger, get_logger


class ErrorReporter(abc.ABC):
    @abc.abstractmethod
    def report(self, exc: BaseException, context: dict[str, Any] | None = None) -> None: ...


class ConsoleErrorReporter(ErrorReporter):
    def __init__(self, logger: Logger | None = None):
        self.logger = logger or get_logger()

    def report(self, exc, context=None):
        self.logger.error(
            "unhandled error",
            error=str(exc),
            error_type=type(exc).__name__,
            traceback="".join(traceback.format_exception(exc)),
            **(context or {}),
        )


class SilentErrorReporter(ErrorReporter):
    def report(self, exc, context=None):
        pass


class CollectingErrorReporter(ErrorReporter):
    """Stores reports for assertions in tests."""

    def __init__(self):
        self.reports: list[tuple[BaseException, dict]] = []

    def report(self, exc, context=None):
        self.reports.append((exc, dict(context or {})))


def create_error_reporter(config: Any = None) -> ErrorReporter:
    cfg = dict(config or {})
    driver = cfg.get("driver", "console")
    if driver == "console":
        return ConsoleErrorReporter()
    if driver == "silent":
        return SilentErrorReporter()
    if driver == "collecting":
        return CollectingErrorReporter()
    raise ValueError(f"unknown error_reporter driver {driver!r}")
