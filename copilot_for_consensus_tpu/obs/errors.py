"""Error reporting abstraction (parity with ``copilot_error_reporting``).

Drivers: console (structured log), silent, collecting (tests), and
``http`` — the Sentry-role driver (reference
``copilot_error_reporting/sentry_error_reporter.py``): events POST as
JSON to a configurable endpoint with fingerprint-based rate limiting,
release/environment tags, and best-effort delivery that never takes the
pipeline down with the error tracker.
"""

from __future__ import annotations

import abc
import hashlib
import json
import threading
import time
import traceback
from typing import Any

from copilot_for_consensus_tpu.obs.logging import Logger, get_logger


def extract_correlation_ids(context: dict[str, Any] | None) -> list[str]:
    """Normalize the correlation ids out of a report context: accepts
    ``correlation_id`` (one) and/or ``correlation_ids`` (many) and
    returns a de-duplicated, order-preserving list. Every reporter
    driver uses this so an engine error names the requests in flight
    the same way regardless of where the report lands."""
    if not context:
        return []
    ids: list[str] = []
    one = context.get("correlation_id")
    if one:
        ids.append(str(one))
    many = context.get("correlation_ids")
    if isinstance(many, (list, tuple)):
        ids.extend(str(c) for c in many if c)
    elif many:
        ids.append(str(many))
    seen: set[str] = set()
    out = []
    for c in ids:
        if c not in seen:
            seen.add(c)
            out.append(c)
    return out


class ErrorReporter(abc.ABC):
    @abc.abstractmethod
    def report(self, exc: BaseException, context: dict[str, Any] | None = None) -> None: ...


class ConsoleErrorReporter(ErrorReporter):
    def __init__(self, logger: Logger | None = None):
        self.logger = logger or get_logger()

    def report(self, exc, context=None):
        self.logger.error(
            "unhandled error",
            error=str(exc),
            error_type=type(exc).__name__,
            traceback="".join(traceback.format_exception(exc)),
            **(context or {}),
        )


class SilentErrorReporter(ErrorReporter):
    def report(self, exc, context=None):
        pass


class CollectingErrorReporter(ErrorReporter):
    """Stores reports for assertions in tests."""

    def __init__(self):
        self.reports: list[tuple[BaseException, dict]] = []

    def report(self, exc, context=None):
        self.reports.append((exc, dict(context or {})))


class HTTPErrorReporter(ErrorReporter):
    """Sentry-role driver: POST error events to a tracking endpoint.

    Shapes the event like an error tracker expects (type, message,
    stacktrace, fingerprint, tags, timestamp), dedup-rate-limits by
    fingerprint (at most one send per ``min_interval_s`` per distinct
    error site), sends from a background thread with a bounded queue,
    and degrades to the console reporter when the endpoint is down —
    an outage of the tracker must never cascade into the pipeline.
    """

    def __init__(self, endpoint: str, *, release: str = "",
                 environment: str = "production",
                 min_interval_s: float = 60.0, queue_size: int = 256,
                 timeout_s: float = 5.0,
                 fallback: ErrorReporter | None = None):
        import collections

        self.endpoint = endpoint
        self.release = release
        self.environment = environment
        self.min_interval_s = min_interval_s
        self.timeout_s = timeout_s
        self.fallback = fallback or ConsoleErrorReporter()
        self._last_sent: dict[str, float] = {}
        self._queue: "collections.deque[dict]" = collections.deque(
            maxlen=queue_size)
        self._wake = threading.Event()
        self.sent = 0
        self.suppressed = 0
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="error-reporter")
        self._thread.start()

    @staticmethod
    def _fingerprint(exc: BaseException) -> str:
        tb = exc.__traceback__
        frames = []
        while tb is not None:
            frames.append(f"{tb.tb_frame.f_code.co_filename}:"
                          f"{tb.tb_frame.f_code.co_name}")
            tb = tb.tb_next
        raw = f"{type(exc).__name__}|{'|'.join(frames[-5:])}"
        return hashlib.sha256(raw.encode()).hexdigest()[:16]

    def report(self, exc, context=None):
        fp = self._fingerprint(exc)
        now = time.time()
        if now - self._last_sent.get(fp, 0.0) < self.min_interval_s:
            self.suppressed += 1
            return
        self._last_sent[fp] = now
        event = {
            "timestamp": now,
            "fingerprint": fp,
            "error_type": type(exc).__name__,
            "message": str(exc),
            "stacktrace": "".join(traceback.format_exception(exc)),
            "release": self.release,
            "environment": self.environment,
            "tags": {k: str(v) for k, v in (context or {}).items()},
        }
        # Correlation ids are first-class on the event (not flattened
        # into a tag string): the error tracker's UI joins them against
        # the logstore, and an engine failure's ids name the requests
        # that were in flight (engine/telemetry.py flight recorder).
        ids = extract_correlation_ids(context)
        if ids:
            event["correlation_ids"] = ids
        self._queue.append(event)
        self._wake.set()

    def _pump(self) -> None:
        import urllib.request

        while True:
            self._wake.wait(1.0)
            self._wake.clear()
            while self._queue:
                event = self._queue.popleft()
                req = urllib.request.Request(
                    self.endpoint, method="POST",
                    data=json.dumps(event).encode(),
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(
                            req, timeout=self.timeout_s):
                        self.sent += 1
                except Exception:
                    # OSError covers the common network failures, but a
                    # schemeless endpoint (ValueError) or a malformed
                    # response (http.client.HTTPException) must not kill
                    # the sender thread either — a dead pump silently
                    # disables error reporting forever.
                    # endpoint down: hand the event to the fallback and
                    # drop the rest of this batch rather than spin
                    try:
                        self.fallback.report(
                            RuntimeError(event["message"]),
                            {"error_type": event["error_type"],
                             "via": "http_reporter_fallback"})
                    except Exception:
                        pass
                    break


def create_error_reporter(config: Any = None) -> ErrorReporter:
    cfg = dict(config or {})
    driver = cfg.get("driver", "console")
    if driver == "console":
        return ConsoleErrorReporter()
    if driver == "silent":
        return SilentErrorReporter()
    if driver == "collecting":
        return CollectingErrorReporter()
    if driver == "http":
        endpoint = cfg.get("endpoint")
        if not endpoint:
            raise ValueError("http error_reporter needs an endpoint")
        return HTTPErrorReporter(
            endpoint,
            release=cfg.get("release", ""),
            environment=cfg.get("environment", "production"),
            min_interval_s=float(cfg.get("min_interval_s", 60.0)))
    raise ValueError(f"unknown error_reporter driver {driver!r}")
