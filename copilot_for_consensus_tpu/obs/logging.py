"""Structured JSON logging with correlation ids.

Parity with ``copilot_logging`` (ABC Logger / StdoutLogger JSON-lines /
SilentLogger). Correlation ids flow through every pipeline stage so a
document's journey can be traced across services from the logs alone —
the reference's substitute for a distributed tracer (SURVEY.md §5).
"""

from __future__ import annotations

import abc
import json
import sys
import threading
import time
from typing import Any, IO

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}


class Logger(abc.ABC):
    @abc.abstractmethod
    def log(self, level: str, message: str, **fields: Any) -> None: ...

    def debug(self, message: str, **fields: Any) -> None:
        self.log("debug", message, **fields)

    def info(self, message: str, **fields: Any) -> None:
        self.log("info", message, **fields)

    def warning(self, message: str, **fields: Any) -> None:
        self.log("warning", message, **fields)

    def error(self, message: str, **fields: Any) -> None:
        self.log("error", message, **fields)

    def bind(self, **fields: Any) -> "BoundLogger":
        return BoundLogger(self, fields)


class BoundLogger(Logger):
    """Logger with pre-bound context fields (service name, correlation id)."""

    def __init__(self, parent: Logger, fields: dict[str, Any]):
        self.parent = parent
        self.fields = fields

    def log(self, level: str, message: str, **fields: Any) -> None:
        self.parent.log(level, message, **{**self.fields, **fields})


class StdoutLogger(Logger):
    """One JSON object per line to stdout (Loki/Promtail-friendly)."""

    def __init__(self, service: str = "", level: str = "info",
                 stream: IO[str] | None = None):
        self.service = service
        self.min_level = _LEVELS.get(level, 20)
        self.stream = stream or sys.stdout
        self._lock = threading.Lock()

    def log(self, level: str, message: str, **fields: Any) -> None:
        if _LEVELS.get(level, 20) < self.min_level:
            return
        record = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "level": level,
            "service": self.service,
            "message": message,
        }
        record.update(fields)
        line = json.dumps(record, default=str)
        with self._lock:
            self.stream.write(line + "\n")
            self.stream.flush()


class SilentLogger(Logger):
    def log(self, level: str, message: str, **fields: Any) -> None:
        pass


class ShippingLogger(Logger):
    """Tees records to a local logger AND ships them to the logstore
    (``tools/logstore.py`` — the Loki/Promtail role) as JSON lines over
    TCP. Shipping is best-effort: the sink being down must never block
    or crash the pipeline, so sends are background, bounded-queue,
    drop-oldest, with lazy reconnects."""

    def __init__(self, tee: Logger, host: str, port: int,
                 queue_size: int = 4096):
        import collections

        self.tee = tee
        self.host, self.port = host, port
        self._queue: "collections.deque[str]" = collections.deque(
            maxlen=queue_size)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._sock = None
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="log-shipper")
        self._thread.start()

    def log(self, level: str, message: str, **fields: Any) -> None:
        self.tee.log(level, message, **fields)
        record = {"ts": time.time(), "level": level, "message": message,
                  **fields}
        if isinstance(self.tee, StdoutLogger) and self.tee.service:
            record.setdefault("service", self.tee.service)
        self._queue.append(json.dumps(record, default=str))
        self._wake.set()

    def _pump(self) -> None:
        import socket

        while not self._stop.is_set():
            self._wake.wait(1.0)
            self._wake.clear()
            while self._queue:
                line = self._queue.popleft()
                try:
                    if self._sock is None:
                        self._sock = socket.create_connection(
                            (self.host, self.port), timeout=3)
                    self._sock.sendall(line.encode() + b"\n")
                except OSError:
                    try:
                        if self._sock is not None:
                            self._sock.close()
                    finally:
                        self._sock = None
                    # put it back (front) and back off; the deque's
                    # maxlen sheds oldest records under pressure. The
                    # backoff is stop-aware so close() never waits out
                    # a sleeping shipper thread.
                    self._queue.appendleft(line)
                    if self._stop.wait(1.0):
                        return
                    break

    def close(self) -> None:
        """Stop the shipper thread (unsent records are dropped — the
        shipping contract is best-effort)."""
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=2)
        # swap locally: a pump thread that outlived the join may still
        # set self._sock = None in its error handler
        sock, self._sock = self._sock, None
        if sock is not None:
            sock.close()


class MemoryLogger(Logger):
    """Captures records for assertions in tests."""

    def __init__(self):
        self.records: list[dict[str, Any]] = []

    def log(self, level: str, message: str, **fields: Any) -> None:
        self.records.append({"level": level, "message": message, **fields})


_default_logger: Logger = StdoutLogger()


def set_default_logger(logger: Logger) -> None:
    global _default_logger
    _default_logger = logger


def get_logger() -> Logger:
    return _default_logger


def create_logger(config: Any = None) -> Logger:
    """Config-driven logger construction (drivers: stdout, silent,
    memory, shipping)."""
    cfg = dict(config or {})
    driver = cfg.get("driver", "stdout")
    if driver == "stdout":
        return StdoutLogger(service=cfg.get("service", ""),
                            level=cfg.get("level", "info"))
    if driver == "silent":
        return SilentLogger()
    if driver == "memory":
        return MemoryLogger()
    if driver == "shipping":
        return ShippingLogger(
            StdoutLogger(service=cfg.get("service", ""),
                         level=cfg.get("level", "info")),
            host=cfg.get("host", "127.0.0.1"),
            port=int(cfg.get("port", 5140)))
    raise ValueError(f"unknown logger driver {driver!r}")
