"""Structured JSON logging with correlation ids.

Parity with ``copilot_logging`` (ABC Logger / StdoutLogger JSON-lines /
SilentLogger). Correlation ids flow through every pipeline stage so a
document's journey can be traced across services from the logs alone —
the reference's substitute for a distributed tracer (SURVEY.md §5).
"""

from __future__ import annotations

import abc
import json
import sys
import threading
import time
from typing import Any, IO

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}


class Logger(abc.ABC):
    @abc.abstractmethod
    def log(self, level: str, message: str, **fields: Any) -> None: ...

    def debug(self, message: str, **fields: Any) -> None:
        self.log("debug", message, **fields)

    def info(self, message: str, **fields: Any) -> None:
        self.log("info", message, **fields)

    def warning(self, message: str, **fields: Any) -> None:
        self.log("warning", message, **fields)

    def error(self, message: str, **fields: Any) -> None:
        self.log("error", message, **fields)

    def bind(self, **fields: Any) -> "BoundLogger":
        return BoundLogger(self, fields)


class BoundLogger(Logger):
    """Logger with pre-bound context fields (service name, correlation id)."""

    def __init__(self, parent: Logger, fields: dict[str, Any]):
        self.parent = parent
        self.fields = fields

    def log(self, level: str, message: str, **fields: Any) -> None:
        self.parent.log(level, message, **{**self.fields, **fields})


class StdoutLogger(Logger):
    """One JSON object per line to stdout (Loki/Promtail-friendly)."""

    def __init__(self, service: str = "", level: str = "info",
                 stream: IO[str] | None = None):
        self.service = service
        self.min_level = _LEVELS.get(level, 20)
        self.stream = stream or sys.stdout
        self._lock = threading.Lock()

    def log(self, level: str, message: str, **fields: Any) -> None:
        if _LEVELS.get(level, 20) < self.min_level:
            return
        record = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "level": level,
            "service": self.service,
            "message": message,
        }
        record.update(fields)
        line = json.dumps(record, default=str)
        with self._lock:
            self.stream.write(line + "\n")
            self.stream.flush()


class SilentLogger(Logger):
    def log(self, level: str, message: str, **fields: Any) -> None:
        pass


class MemoryLogger(Logger):
    """Captures records for assertions in tests."""

    def __init__(self):
        self.records: list[dict[str, Any]] = []

    def log(self, level: str, message: str, **fields: Any) -> None:
        self.records.append({"level": level, "message": message, **fields})


_default_logger: Logger = StdoutLogger()


def set_default_logger(logger: Logger) -> None:
    global _default_logger
    _default_logger = logger


def get_logger() -> Logger:
    return _default_logger


def create_logger(config: Any = None) -> Logger:
    """Config-driven logger construction (drivers: stdout, silent, memory)."""
    cfg = dict(config or {})
    driver = cfg.get("driver", "stdout")
    if driver == "stdout":
        return StdoutLogger(service=cfg.get("service", ""),
                            level=cfg.get("level", "info"))
    if driver == "silent":
        return SilentLogger()
    if driver == "memory":
        return MemoryLogger()
    raise ValueError(f"unknown logger driver {driver!r}")
