"""jax.profiler integration: flag-gated trace capture on the engines.

SURVEY.md §5 assigns the tracing/profiling subsystem to the TPU build
(the reference's per-event correlation_id covers the host side; device
time needs the XLA profiler). Usage:

    with maybe_profile("var/traces"):            # or None → no-op
        engine.generate(...)

Traces are Perfetto/TensorBoard-compatible (``jax.profiler.trace``).
Enable on the serving engines via config ``llm.profile_dir``
(``GenerationEngine(profile_dir=...)``); the flag defaults off so
production pays zero overhead.

``step_annotation`` wraps each engine dispatch in a
``jax.profiler.StepTraceAnnotation`` whose ``step_num`` is the flight
recorder's step id (``engine/telemetry.py``) — a Perfetto device-trace
row and a host-side ``StepRecord`` then name the SAME step, which is
what makes "slow device step 1234" and "step 1234 was a 2-row padded
prefill wave" one investigation. The annotation is a TraceMe that is
near-free when no profiler session is active, so the engines keep it
on unconditionally.
"""

from __future__ import annotations

import contextlib
import pathlib


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None, *, create_perfetto_link=False):
    """Capture a jax.profiler trace into ``trace_dir`` when set; plain
    no-op when None/empty — callers never branch."""
    if not trace_dir:
        yield None
        return
    import jax

    path = pathlib.Path(trace_dir)
    path.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(path),
                            create_perfetto_link=create_perfetto_link):
        yield str(path)


def step_annotation(name: str, step_num: int | None = None):
    """``StepTraceAnnotation`` context for one engine dispatch.

    ``name`` is the wave kind (prefill/decode/verify/...), ``step_num``
    the flight-recorder step id. Returns a no-op context when the
    profiler API is unavailable (stripped-down jax builds) — callers
    never branch."""
    import jax

    try:
        if step_num is None:
            return jax.profiler.StepTraceAnnotation(name)
        return jax.profiler.StepTraceAnnotation(name, step_num=step_num)
    except Exception:  # pragma: no cover - profiler API missing
        return contextlib.nullcontext()
