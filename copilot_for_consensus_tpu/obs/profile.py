"""jax.profiler integration: flag-gated trace capture on the engines.

SURVEY.md §5 assigns the tracing/profiling subsystem to the TPU build
(the reference's per-event correlation_id covers the host side; device
time needs the XLA profiler). Usage:

    with maybe_profile("var/traces"):            # or None → no-op
        engine.generate(...)

Traces are Perfetto/TensorBoard-compatible (``jax.profiler.trace``).
Enable on the serving engines via config ``llm.profile_dir``
(``GenerationEngine(profile_dir=...)``); the flag defaults off so
production pays zero overhead.
"""

from __future__ import annotations

import contextlib
import pathlib


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None, *, create_perfetto_link=False):
    """Capture a jax.profiler trace into ``trace_dir`` when set; plain
    no-op when None/empty — callers never branch."""
    if not trace_dir:
        yield None
        return
    import jax

    path = pathlib.Path(trace_dir)
    path.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(path),
                            create_perfetto_link=create_perfetto_link):
        yield str(path)
