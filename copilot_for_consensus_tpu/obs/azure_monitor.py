"""Azure Monitor (Application Insights) metrics driver — raw REST.

Fills the role of the reference's
``copilot_metrics/azure_monitor_metrics.py:38``
(AzureMonitorMetricsCollector: OpenTelemetry SDK + Azure Monitor
exporter, periodic batched export, error counting, shutdown-flush).
This image has no Azure/OTel SDKs and no egress, so the driver speaks
the Application Insights ingestion wire protocol directly — the same
``POST {IngestionEndpoint}/v2.1/track`` envelope stream the exporter
emits — making it testable against an in-process mock
(``tests/test_azure_monitor_metrics.py``) and usable against real
Application Insights wherever the runtime has network access.

Semantics mirror the reference collector:

* ``increment`` → counter, exported as the DELTA since the last flush
  (the OTel exporter's delta temporality for counters);
* ``observe`` → pre-aggregated metric envelope (count/min/max/sum — the
  App Insights ``MetricData`` aggregate shape);
* ``gauge`` → latest value at flush time;
* labels ride as envelope ``properties`` (custom dimensions);
* export every ``export_interval_s`` on a background thread, plus on
  ``safe_push()`` and ``shutdown()``; errors are counted
  (``errors_count``) and never raised into the pipeline unless
  ``raise_on_error`` (the reference's testing knob).
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Any

from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics


def parse_connection_string(conn: str) -> tuple[str, str]:
    """``InstrumentationKey=...;IngestionEndpoint=https://...`` →
    (ikey, endpoint). A bare instrumentation key gets the public
    ingestion endpoint, like the SDK."""
    parts = dict(
        kv.split("=", 1) for kv in conn.split(";") if "=" in kv)
    ikey = parts.get("InstrumentationKey", "").strip()
    if not ikey and re.fullmatch(r"[0-9a-fA-F-]{8,}", conn.strip()):
        ikey = conn.strip()
    if not ikey:
        raise ValueError(
            "azure_monitor needs a connection string with an "
            "InstrumentationKey")
    endpoint = parts.get(
        "IngestionEndpoint",
        "https://dc.services.visualstudio.com").rstrip("/")
    return ikey, endpoint


class AzureMonitorMetrics(InMemoryMetrics):
    """In-memory aggregation + periodic App Insights envelope export."""

    def __init__(self, connection_string: str,
                 namespace: str = "copilot",
                 export_interval_s: float = 60.0,
                 timeout_s: float = 10.0,
                 raise_on_error: bool = False):
        super().__init__(namespace=namespace)
        self.ikey, self.endpoint = parse_connection_string(
            connection_string)
        self.export_interval_s = export_interval_s
        self.timeout_s = timeout_s
        self.raise_on_error = raise_on_error
        self.errors_count = 0
        self.exported_envelopes = 0
        # counters export deltas: remember what was already shipped
        self._shipped_counters: dict[str, dict[tuple, float]] = {}
        self._shipped_hists: dict[str, dict[tuple, tuple]] = {}
        self._flush_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if export_interval_s > 0:
            self._thread = threading.Thread(
                target=self._export_loop, daemon=True,
                name="azure-monitor-export")
            self._thread.start()

    # -- envelope construction -----------------------------------------

    def _metric_envelope(self, name: str, key: tuple, *, value: float,
                         count: int = 1, mn: float | None = None,
                         mx: float | None = None) -> dict[str, Any]:
        data_point: dict[str, Any] = {
            "name": f"{self.namespace}.{name}", "value": value,
            "count": count,
        }
        if mn is not None:
            data_point["min"] = mn
        if mx is not None:
            data_point["max"] = mx
        return {
            "name": "Microsoft.ApplicationInsights.Metric",
            "time": time.strftime("%Y-%m-%dT%H:%M:%S.000Z",
                                  time.gmtime()),
            "iKey": self.ikey,
            "tags": {"ai.cloud.role": self.namespace},
            "data": {
                "baseType": "MetricData",
                "baseData": {
                    "metrics": [data_point],
                    "properties": {k: str(v) for k, v in key},
                },
            },
        }

    def _collect_envelopes(self) -> list[dict[str, Any]]:
        out: list[dict[str, Any]] = []
        with self._lock:
            for name, series in self.counters.items():
                shipped = self._shipped_counters.setdefault(name, {})
                for key, total in series.items():
                    delta = total - shipped.get(key, 0.0)
                    if delta:
                        out.append(self._metric_envelope(
                            name, key, value=delta,
                            count=max(int(delta), 1)))
                        shipped[key] = total
            for name, series in self.gauges.items():
                for key, value in series.items():
                    out.append(self._metric_envelope(name, key,
                                                     value=value))
            for name, series in self.histograms.items():
                shipped_h = self._shipped_hists.setdefault(name, {})
                for key, (total, count, _) in series.items():
                    prev_sum, prev_n = shipped_h.get(key, (0.0, 0))
                    dn = count - prev_n
                    if dn > 0:
                        out.append(self._metric_envelope(
                            name, key, value=total - prev_sum,
                            count=dn))
                        shipped_h[key] = (total, count)
        return out

    # -- export ---------------------------------------------------------

    def _export_loop(self) -> None:
        while not self._stop.wait(self.export_interval_s):
            try:
                self.safe_push()
            except Exception:
                # raise_on_error is for foreground callers (tests); the
                # background exporter must outlive transient failures —
                # the error is already counted and the deltas rolled
                # back for the next attempt
                pass

    def safe_push(self) -> None:
        """Flush pending aggregates as one /v2.1/track batch. Network
        failures count and (by default) never raise — metrics must not
        take the pipeline down (same contract as PushgatewayMetrics)."""
        with self._flush_lock:
            # snapshot the shipped watermarks so a failed POST can roll
            # back to exactly this point (clearing them instead would
            # re-ship already-accepted totals as fresh deltas)
            with self._lock:
                saved_counters = {k: dict(v) for k, v in
                                  self._shipped_counters.items()}
                saved_hists = {k: dict(v) for k, v in
                               self._shipped_hists.items()}
            envelopes = self._collect_envelopes()
            if not envelopes:
                return
            body = "\n".join(
                json.dumps(e, separators=(",", ":"))
                for e in envelopes).encode()
            try:
                req = urllib.request.Request(
                    f"{self.endpoint}/v2.1/track", data=body,
                    method="POST",
                    headers={"Content-Type": "application/x-json-stream"})
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    ack = json.loads(resp.read() or b"{}")
                rejected = (ack.get("itemsReceived", 0)
                            - ack.get("itemsAccepted", 0))
                self.errors_count += max(rejected, 0)
                self.exported_envelopes += ack.get(
                    "itemsAccepted", len(envelopes))
            except Exception as exc:
                self.errors_count += 1
                with self._lock:
                    self._shipped_counters = saved_counters
                    self._shipped_hists = saved_hists
                if self.raise_on_error:
                    raise RuntimeError(
                        f"azure monitor export failed: {exc}") from exc

    def shutdown(self) -> None:
        """Final flush + stop the exporter thread (reference
        ``azure_monitor_metrics.py:336``)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.safe_push()

    # parity accessors (reference get_errors_count / get_gauge_value,
    # ``azure_monitor_metrics.py:307,328``); the latter is the
    # inherited accessor under the reference's name
    def get_errors_count(self) -> int:
        # GIL-atomic int read; taking _flush_lock here would block the
        # accessor behind an in-progress flush's network POST for a
        # stale-read-tolerant parity counter.
        # jaxlint: disable=race-unlocked-field
        return self.errors_count

    get_gauge_value = InMemoryMetrics.gauge_value
