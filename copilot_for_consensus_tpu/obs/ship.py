"""Cross-process telemetry plane: crash-safe shipping + merged exposition.

Every telemetry surface before this module — the flight recorder
(engine/telemetry.py), the TraceCollector (obs/trace.py), the
``copilot_*`` metric registries — is process-local: a multichip bench
child communicates by printing one summary JSON line, so its
histograms, spans and post-mortems are invisible to the driver and
vanish entirely on SIGKILL. This module makes telemetry a durable,
mergeable artifact:

* :class:`TelemetrySpool` — a per-process sqlite WAL spool holding an
  append-only row log (``(seq, kind, payload)``; kinds: ``metrics`` /
  ``span`` / ``step``). Same file discipline as the PR-12 engine
  journal and the PR-8 outbox: WAL + ``synchronous=NORMAL``, every
  multi-row write inside one transaction, so committed rows survive a
  SIGKILL mid-storm and a reader can recover them from the dead
  process's file.
* :class:`TelemetryShipper` — snapshots an ``InMemoryMetrics``
  registry (shipping *deltas*, so repeated flushes don't double-count),
  a ``TraceCollector`` ring, and a ``FlightRecorder`` into the spool.
  An optional pump thread flushes on an interval; it is stop-aware
  (polls an Event, no bare sleep) and owner-joined, per the racecheck
  thread-lifecycle / blocking-call disciplines.
* :class:`TelemetryAggregator` — merges N spools (or live registries)
  into ONE exposition: counters sum, gauges last-write-wins (within a
  process; shipping preserves per-process order), histogram buckets
  merge element-wise, and every merged series gains the reserved
  ``proc``/``role`` labels (``obs.metrics.RESERVED_LABELS`` — a
  registry declaring them fails at registration). Spans merge by
  ``trace_id`` with ``proc`` stamped on, so ``tools/tracepath.py``
  reconstructs DAGs whose stages ran in different OS processes.
  Ingestion dedups by ``(proc, seq)``: shipping is at-least-once into
  the aggregator, re-ingesting a spool applies only rows it has not
  seen (docs/RESILIENCE.md "spool commit ≠ delivery").

The merged registry re-exports through the existing
``InMemoryMetrics.render_prometheus`` text format — one scrape for an
N-process topology, same exact-format contract the observability pack
tests pin.
"""

from __future__ import annotations

import json
import os
import pathlib
import sqlite3
import threading
import time
import weakref
from typing import Any, Iterable

from copilot_for_consensus_tpu.obs.metrics import (
    RESERVED_LABELS,
    InMemoryMetrics,
    check_registry_labels,
)

#: spool filename suffix — the aggregator's directory scan and
#: tracepath's source sniffing key off it
SPOOL_SUFFIX = ".spool.sqlite3"

#: row kinds a spool may hold (doc + test anchor)
ROW_KINDS = ("metrics", "span", "step")

#: shipping-plane health series (full exposition names, the BUS_METRICS
#: style) — emitted into the registry being shipped, so ship health
#: rides the same spool it reports on and shows up per-proc in the
#: merged exposition.
SHIP_METRICS = {
    "copilot_ship_rows_total": (
        "counter", ("kind",),
        "spool rows committed by this process's shipper, by row kind "
        "(metrics | span | step)"),
    "copilot_ship_flush_seconds": (
        "histogram", (),
        "one shipper flush: snapshot + delta + single spool "
        "transaction (the <1% overhead budget's unit of work)"),
    "copilot_ship_spool_rows": (
        "gauge", (),
        "total committed rows in this process's spool (recovery "
        "readers compare against this for loss accounting)"),
}

# proc/role are stamped by the aggregator; the shipping plane's own
# registry obeys the same registration-time contract it introduces.
check_registry_labels(SHIP_METRICS, owner="SHIP_METRICS")


def _enc_labels(key: tuple) -> list:
    """Label key tuple → JSON-friendly ``[[k, v], ...]``."""
    return [[k, v] for k, v in key]


def _dec_labels(pairs: Iterable) -> dict:
    return {k: v for k, v in pairs}


# ---------------------------------------------------------------------------
# spool
# ---------------------------------------------------------------------------


class TelemetrySpool:
    """Crash-safe per-process telemetry spool (sqlite WAL).

    File discipline matches the engine journal (engine/journal.py):
    WAL + ``synchronous=NORMAL`` so committed transactions survive
    process SIGKILL; every multi-row append is ONE transaction; the
    handle is closed explicitly. ``seq`` is an AUTOINCREMENT primary
    key starting at 1 with no deletes, so a gap in a recovered spool
    means a committed row was lost — :func:`read_spool` reports that
    as ``lost`` and the chaos gate asserts it stays 0.
    """

    def __init__(self, path: str | os.PathLike, *, proc: str,
                 role: str = ""):
        self.path = str(path)
        self.proc = proc
        self.role = role
        pathlib.Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._db = sqlite3.connect(self.path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        with self._lock, self._db:
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS meta ("
                " key TEXT PRIMARY KEY, value TEXT NOT NULL)")
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS rows ("
                " seq INTEGER PRIMARY KEY AUTOINCREMENT,"
                " kind TEXT NOT NULL,"
                " payload TEXT NOT NULL)")
            self._db.execute(
                "INSERT OR REPLACE INTO meta VALUES ('proc', ?)", (proc,))
            self._db.execute(
                "INSERT OR REPLACE INTO meta VALUES ('role', ?)", (role,))
            self._db.execute(
                "INSERT OR REPLACE INTO meta VALUES ('pid', ?)",
                (str(os.getpid()),))
            self._db.execute(
                "INSERT OR REPLACE INTO meta VALUES ('started_wall', ?)",
                (repr(time.time()),))
        with self._lock:
            cur = self._db.execute("SELECT COUNT(*) FROM rows")
            self._n = int(cur.fetchone()[0])

    def append(self, rows: Iterable[tuple[str, dict]]) -> int:
        """Commit ``(kind, payload)`` rows in ONE transaction.

        All-or-nothing: after a SIGKILL either every row of a flush is
        recoverable or none is — no torn flushes. Returns the total
        committed row count.
        """
        batch = [(kind, json.dumps(payload, sort_keys=True))
                 for kind, payload in rows]
        with self._lock:
            if batch:
                with self._db:
                    for kind, payload in batch:
                        self._db.execute(
                            "INSERT INTO rows (kind, payload) "
                            "VALUES (?, ?)", (kind, payload))
                self._n += len(batch)
            return self._n

    def committed_rows(self) -> int:
        with self._lock:
            return self._n

    def close(self) -> None:
        # Terminal teardown, the EngineJournal idiom: snapshot the
        # handle under the lock, close outside it.
        with self._lock:
            db = self._db
        db.close()


def read_spool(path: str | os.PathLike) -> dict:
    """Read a spool file — typically one left by a SIGKILLed process.

    Opens its own handle (read path, no writes), so it works on a file
    whose writer died mid-WAL; sqlite replays the committed WAL frames
    on open. Returns ``{path, proc, role, meta, rows, lost}`` where
    ``rows`` is ``[(seq, kind, payload), ...]`` in seq order and
    ``lost`` counts seq gaps (committed rows that vanished — the chaos
    gate's zero-loss assertion).
    """
    db = sqlite3.connect(str(path))
    try:
        meta = {k: v for k, v in
                db.execute("SELECT key, value FROM meta")}
        rows = [(int(seq), kind, json.loads(payload))
                for seq, kind, payload in db.execute(
                    "SELECT seq, kind, payload FROM rows ORDER BY seq")]
    finally:
        db.close()
    lost = (rows[-1][0] - len(rows)) if rows else 0
    return {"path": str(path), "proc": meta.get("proc", ""),
            "role": meta.get("role", ""), "meta": meta,
            "rows": rows, "lost": lost}


def list_spools(directory: str | os.PathLike) -> list[str]:
    """Spool files under ``directory`` (non-recursive), sorted."""
    root = pathlib.Path(directory)
    if not root.is_dir():
        return []
    return sorted(str(p) for p in root.iterdir()
                  if p.name.endswith(SPOOL_SUFFIX))


# ---------------------------------------------------------------------------
# shipper
# ---------------------------------------------------------------------------


class TelemetryShipper:
    """Ships one process's telemetry into its crash-safe spool.

    Sources are all optional: an ``InMemoryMetrics`` registry (shipped
    as snapshot *deltas* so the aggregator can sum counters and merge
    histogram buckets without double counting), a ``TraceCollector``
    (each finished span shipped once), and a ``FlightRecorder`` (each
    StepRecord shipped once, watermarked by its monotonic ``seq``).

    ``flush()`` is synchronous and cheap — one snapshot diff plus one
    spool transaction — and safe to call from the serving loop (the
    journal_storm child flushes per step so every completed step is
    recoverable after its SIGKILL). ``start()`` runs a pump thread
    that flushes every ``interval_s``; the pump is stop-aware (waits
    on the stop Event, never a bare sleep) and ``stop()`` joins it —
    the racecheck thread-lifecycle contract, with a fixture pair and
    tripwire pinning it (tests/fixtures/racecheck/ship_pump.py).
    """

    def __init__(self, path: str | os.PathLike | None = None, *,
                 proc: str, role: str = "",
                 metrics: InMemoryMetrics | None = None,
                 collector: Any = None, recorder: Any = None,
                 interval_s: float = 0.25):
        if path is None:
            base = get_default_spool_dir()
            if not base:
                raise ValueError(
                    "TelemetryShipper needs a spool path (or a default "
                    "spool dir via set_default_spool_dir)")
            path = spool_path(base, proc)
        self.proc = proc
        self.role = role
        self.interval_s = float(interval_s)
        self._metrics = metrics
        self._collector = collector
        self._recorder = recorder
        self._spool = TelemetrySpool(path, proc=proc, role=role)
        # flush state — only ever touched inside flush() under the lock
        self._lock = threading.Lock()
        self._last: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        self._shipped_span_ids: set[str] = set()
        self._shipped_step_seq = 0
        self._flushes = 0
        self._shipped = {kind: 0 for kind in ROW_KINDS}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        _live.add(self)

    @property
    def path(self) -> str:
        return self._spool.path

    # -- shipping -------------------------------------------------------

    def _metrics_delta(self) -> dict | None:
        """Diff the registry against the last-shipped snapshot."""
        snap = self._metrics.snapshot()
        prev = self._last
        counters = []
        for name, series in snap["counters"].items():
            prev_series = prev["counters"].get(name, {})
            for key, value in series.items():
                dv = value - prev_series.get(key, 0.0)
                if dv != 0.0:
                    counters.append([name, _enc_labels(key), dv])
        gauges = []
        for name, series in snap["gauges"].items():
            prev_series = prev["gauges"].get(name, {})
            for key, value in series.items():
                if key not in prev_series or prev_series[key] != value:
                    gauges.append([name, _enc_labels(key), value])
        histograms = []
        for name, series in snap["histograms"].items():
            prev_series = prev["histograms"].get(name, {})
            for key, (total, count, buckets) in series.items():
                p = prev_series.get(key, [0.0, 0, [0] * len(buckets)])
                dcount = count - p[1]
                dsum = total - p[0]
                if dcount or dsum:
                    dbuckets = [b - pb for b, pb in zip(buckets, p[2])]
                    histograms.append(
                        [name, _enc_labels(key), dsum, dcount, dbuckets])
        self._last = snap
        if not (counters or gauges or histograms):
            return None
        return {"namespace": self._metrics.namespace,
                "buckets": list(self._metrics.buckets),
                "counters": counters, "gauges": gauges,
                "histograms": histograms}

    def mark(self) -> None:
        """Baseline the shipper at the registry's CURRENT state without
        shipping anything: subsequent flushes ship deltas from here.
        Bench children call this after warmup so compile-time
        observations never pollute the shipped histograms (the merged
        TTFT/ITL columns must measure the timed window, same as the
        direct columns)."""
        with self._lock:
            if self._metrics is not None:
                self._last = self._metrics.snapshot()
            if self._recorder is not None:
                records = self._recorder.records()
                if records:
                    self._shipped_step_seq = records[-1].seq

    def flush(self) -> int:
        """Ship everything new since the last flush in ONE spool
        transaction. Returns the number of rows appended."""
        with self._lock:
            t0 = time.monotonic()
            rows: list[tuple[str, dict]] = []
            if self._metrics is not None:
                delta = self._metrics_delta()
                if delta is not None:
                    rows.append(("metrics", delta))
            if self._collector is not None:
                current = self._collector.spans()
                current_ids = set()
                for s in current:
                    d = s.as_dict() if hasattr(s, "as_dict") else dict(s)
                    current_ids.add(d.get("span_id", ""))
                    if d.get("span_id", "") not in self._shipped_span_ids:
                        rows.append(("span", d))
                # forget ids the ring evicted — bounds the dedup set to
                # the collector capacity
                self._shipped_span_ids = current_ids
            if self._recorder is not None:
                for rec in self._recorder.records():
                    if rec.seq > self._shipped_step_seq:
                        rows.append(("step", rec.as_dict()))
                        self._shipped_step_seq = rec.seq
            total = self._spool.append(rows)
            self._flushes += 1
            for kind, _payload in rows:
                self._shipped[kind] += 1
            if self._metrics is not None:
                for kind, n in self._shipped.items():
                    self._metrics.set_counter(
                        "ship_rows_total", float(n), {"kind": kind})
                self._metrics.observe("ship_flush_seconds",
                                      time.monotonic() - t0)
                self._metrics.gauge("ship_spool_rows", float(total))
            return len(rows)

    # -- pump thread ----------------------------------------------------

    def start(self) -> "TelemetryShipper":
        """Start the background pump (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            thread = threading.Thread(
                target=self._pump, name=f"telemetry-ship-{self.proc}",
                daemon=True)
            self._thread = thread
        thread.start()
        return self

    def _pump(self) -> None:
        # Stop-aware: wake on the Event, never a bare sleep, so stop()
        # returns within one poll interval (racecheck thread-lifecycle
        # + blocking-call disciplines).
        while not self._stop.is_set():
            self._stop.wait(self.interval_s)
            try:
                self.flush()
            except Exception:
                # shipping must never take the serving process down
                pass

    def stop(self) -> None:
        """Stop and join the pump thread (owner-joined)."""
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)

    def close(self) -> None:
        """Stop the pump, ship a final flush, close the spool."""
        self.stop()
        try:
            self.flush()
        except Exception:
            pass
        self._spool.close()

    def stats(self) -> dict:
        with self._lock:
            return {"proc": self.proc, "role": self.role,
                    "path": self._spool.path,
                    "committed_rows": self._spool.committed_rows(),
                    "flushes": self._flushes,
                    "shipped": dict(self._shipped)}


# ---------------------------------------------------------------------------
# aggregator
# ---------------------------------------------------------------------------


class TelemetryAggregator:
    """Merges N processes' telemetry into one exposition.

    Merge semantics (the tentpole contract, pinned by
    tests/test_telemetry_ship.py):

    * counters **sum** — each shipped row is a delta, so applying every
      row once yields the true total;
    * gauges are **last-write-wins** within a process (rows apply in
      seq order; different procs never collide because ``proc`` is in
      the label set);
    * histograms **merge buckets** element-wise (sum, count and each
      cumulative bucket add);
    * every merged series gains the reserved ``proc``/``role`` labels;
      a spool whose own labels claim them is rejected loudly;
    * a series shipped as two different types by two processes is a
      **type conflict** and raises — one exposition, one TYPE line.

    Ingestion dedups by ``(proc, seq)``: re-ingesting the same spool
    (the at-least-once delivery case) applies nothing new.
    """

    def __init__(self, namespace: str = "copilot"):
        self._metrics = InMemoryMetrics(namespace=namespace)
        self._lock = threading.Lock()
        self._types: dict[tuple[str, str], str] = {}
        self._applied: dict[str, int] = {}   # proc -> max applied seq
        self._lost: dict[str, int] = {}
        self._spans: list[dict] = []
        self._steps: dict[str, list[dict]] = {}

    @property
    def metrics(self) -> InMemoryMetrics:
        return self._metrics

    # -- merge plumbing -------------------------------------------------

    def _check_type(self, name: str, typ: str) -> None:
        seen = self._types.get(("series", name))
        if seen is None:
            self._types[("series", name)] = typ
        elif seen != typ:
            raise ValueError(
                f"cross-process type conflict for series {name!r}: "
                f"{seen} vs {typ} — one exposition renders one TYPE "
                f"line per series, refusing to merge")

    def _stamp(self, pairs: Iterable, proc: str, role: str) -> dict:
        labels = _dec_labels(pairs)
        clash = [lb for lb in labels if lb in RESERVED_LABELS]
        if clash:
            raise ValueError(
                f"spool from proc {proc!r} ships reserved label(s) "
                f"{clash}; {RESERVED_LABELS} are stamped by the "
                f"aggregator (see obs.metrics.check_registry_labels)")
        labels["proc"] = proc
        labels["role"] = role
        return labels

    def _apply_metrics(self, payload: dict, proc: str, role: str) -> None:
        for name, pairs, dv in payload.get("counters", ()):
            self._check_type(name, "counter")
            self._metrics.increment(name, dv, self._stamp(pairs, proc, role))
        for name, pairs, value in payload.get("gauges", ()):
            self._check_type(name, "gauge")
            self._metrics.gauge(name, value, self._stamp(pairs, proc, role))
        for name, pairs, dsum, dcount, dbuckets in payload.get(
                "histograms", ()):
            self._check_type(name, "histogram")
            self._metrics.merge_histogram(
                name, self._stamp(pairs, proc, role), dsum, dcount,
                dbuckets)

    def _apply_span(self, payload: dict, proc: str, role: str) -> None:
        d = dict(payload)
        d["proc"] = proc
        if role and not d.get("service"):
            d["service"] = role
        self._spans.append(d)

    # -- ingestion ------------------------------------------------------

    def ingest_spool(self, path: str | os.PathLike) -> dict:
        """Ingest one spool; dedups by (proc, seq). Returns per-call
        stats (``applied``, ``skipped``, ``lost``, ``proc``)."""
        spool = read_spool(path)
        proc, role = spool["proc"], spool["role"]
        applied = skipped = 0
        with self._lock:
            watermark = self._applied.get(proc, 0)
            for seq, kind, payload in spool["rows"]:
                if seq <= watermark:
                    skipped += 1
                    continue
                if kind == "metrics":
                    self._apply_metrics(payload, proc, role)
                elif kind == "span":
                    self._apply_span(payload, proc, role)
                elif kind == "step":
                    self._steps.setdefault(proc, []).append(dict(payload))
                watermark = seq
                applied += 1
            self._applied[proc] = watermark
            self._lost[proc] = spool["lost"]
        return {"proc": proc, "role": role, "applied": applied,
                "skipped": skipped, "lost": spool["lost"]}

    def ingest_dir(self, directory: str | os.PathLike) -> list[dict]:
        """Ingest every spool file under ``directory``."""
        return [self.ingest_spool(p) for p in list_spools(directory)]

    def merge_registry(self, metrics: InMemoryMetrics, *, proc: str,
                       role: str = "") -> None:
        """Merge a live in-process registry (no spool round-trip) —
        the aggregating process's own series join the exposition the
        same way shipped ones do."""
        snap = metrics.snapshot()
        payload = {
            "counters": [[n, _enc_labels(k), v]
                         for n, s in snap["counters"].items()
                         for k, v in s.items()],
            "gauges": [[n, _enc_labels(k), v]
                       for n, s in snap["gauges"].items()
                       for k, v in s.items()],
            "histograms": [[n, _enc_labels(k), e[0], e[1], list(e[2])]
                           for n, s in snap["histograms"].items()
                           for k, e in s.items()],
        }
        with self._lock:
            self._apply_metrics(payload, proc, role)

    def merge_spans(self, spans: Iterable[Any], *, proc: str,
                    role: str = "") -> None:
        """Merge live spans (Span objects or dicts), proc-stamped."""
        with self._lock:
            for s in spans:
                d = s.as_dict() if hasattr(s, "as_dict") else dict(s)
                self._apply_span(d, proc, role)

    # -- views ----------------------------------------------------------

    def render_prometheus(self) -> str:
        """ONE merged scrape, the existing exact text format."""
        return self._metrics.render_prometheus()

    def spans(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def spans_by_trace(self) -> dict[str, list[dict]]:
        out: dict[str, list[dict]] = {}
        for d in self.spans():
            out.setdefault(d.get("trace_id", ""), []).append(d)
        return out

    def steps(self, proc: str | None = None) -> list[dict]:
        with self._lock:
            if proc is not None:
                return list(self._steps.get(proc, ()))
            return [d for rows in self._steps.values() for d in rows]

    def stats(self) -> dict:
        with self._lock:
            return {"procs": sorted(self._applied),
                    "rows_applied": dict(self._applied),
                    "lost": dict(self._lost),
                    "spans": len(self._spans),
                    "steps": sum(len(v) for v in self._steps.values())}


# ---------------------------------------------------------------------------
# default spool dir + live-shipper registry — the conftest failure hook
# bundles every live shipper's spool next to the flight-record dumps
# (one telemetry-bundle artifact; satellite of the COPILOT_FLIGHT_
# RECORD_DIR plumbing).
# ---------------------------------------------------------------------------

_default_spool_dir: str | None = None
_live: "weakref.WeakSet[TelemetryShipper]" = weakref.WeakSet()


def set_default_spool_dir(path: str | None) -> None:
    global _default_spool_dir
    _default_spool_dir = path


def get_default_spool_dir() -> str | None:
    return _default_spool_dir


def spool_path(directory: str | os.PathLike, proc: str) -> str:
    """Canonical spool filename for ``proc`` under ``directory``."""
    safe = "".join(c if (c.isalnum() or c in "-_.") else "-"
                   for c in proc) or "proc"
    return str(pathlib.Path(directory) / f"{safe}{SPOOL_SUFFIX}")


def dump_all(directory: str | None = None, tag: str = "telemetry") \
        -> list[str]:
    """Flush every live shipper and write a bundle manifest into
    ``directory``. Never raises — this runs from failure hooks where a
    second error would mask the first. Returns written paths."""
    directory = directory or _default_spool_dir
    if not directory:
        return []
    spools: list[dict] = []
    for shipper in list(_live):
        try:
            shipper.flush()
            spools.append(shipper.stats())
        except Exception:
            continue
    if not spools:
        return []
    try:
        root = pathlib.Path(directory)
        root.mkdir(parents=True, exist_ok=True)
        manifest = root / f"{tag}-spools.json"
        manifest.write_text(json.dumps(
            {"dumped_wall": time.time(), "spools": spools}, indent=2,
            sort_keys=True))
        return [str(manifest)] + [s["path"] for s in spools]
    except Exception:
        return [s["path"] for s in spools]
