"""Observability plane: structured logging, metrics, error reporting, health.

Capability parity with the reference's ``copilot_logging``,
``copilot_metrics`` and ``copilot_error_reporting`` packages (SURVEY.md §2.1,
§5 "Metrics / logging / observability").
"""
