"""Driver registration for observability adapters."""

from copilot_for_consensus_tpu.core.factory import register_driver

register_driver("logger", "stdout", "copilot_for_consensus_tpu.obs.logging:create_logger")
register_driver("logger", "silent", "copilot_for_consensus_tpu.obs.logging:create_logger")
register_driver("logger", "memory", "copilot_for_consensus_tpu.obs.logging:create_logger")
register_driver("logger", "shipping", "copilot_for_consensus_tpu.obs.logging:create_logger")

for _name in ("noop", "inmemory", "prometheus", "pushgateway"):
    register_driver("metrics", _name,
                    "copilot_for_consensus_tpu.obs.metrics:create_metrics_collector")

for _name in ("console", "silent", "collecting", "http"):
    register_driver("error_reporter", _name,
                    "copilot_for_consensus_tpu.obs.errors:create_error_reporter")
