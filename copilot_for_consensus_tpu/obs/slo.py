"""Declarative SLO scoreboard over merged telemetry registries.

Before this module every SLO verdict in the repo was an ad-hoc inline
comparison (``mixed_traffic``'s ``slo_ok`` closure, the alert pack's
hand-written thresholds). This is the single place an objective is
*declared* — series + percentile + threshold + evaluation window +
workload-class label — and *evaluated*, over any ``InMemoryMetrics``
registry: a single process's, or the cross-process merge a
``TelemetryAggregator`` (obs/ship.py) builds from N spools. That makes
the scoreboard the gate machinery for the multi-process arc: the
ROADMAP item-1 criterion "hold interactive TTFT p99 while batch stays
within 10%" is an :class:`SLObjective` here, judged over real merged
histograms rather than a parsed summary line.

Percentiles are computed from the registry's cumulative histogram
buckets exactly the way PromQL's ``histogram_quantile`` does (linear
interpolation inside the bucket, capped at the largest finite bound),
so a verdict here and a Grafana panel over the same scrape agree.
Error-budget burn is ``violation_fraction / budget`` — burn > 1 means
the window has spent more than its allowance of slow requests even if
the percentile point estimate still sits under the threshold.

CLI: ``python -m copilot_for_consensus_tpu slo <spools-or-dirs...>``
renders the scoreboard for the default registry (rc 1 on any breach),
feeding the same rows ``bench.py`` publishes as ``slo_ok`` columns and
``infra/grafana/dashboards/slo.json`` visualizes.
"""

from __future__ import annotations

import argparse
import json
import pathlib
from dataclasses import dataclass, field
from typing import Iterable, Mapping

from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics


def _matches(key: tuple, labels: Mapping[str, str]) -> bool:
    """Subset label match: every filter pair present in the key."""
    have = dict(key)
    return all(have.get(k) == v for k, v in labels.items())


def _merged_entry(metrics: InMemoryMetrics, name: str,
                  labels: Mapping[str, str]) -> list | None:
    """Sum a histogram's entries across all label keys matching
    ``labels`` (the aggregator fans one series out per proc/role; an
    objective without a proc filter judges the whole fleet)."""
    series = metrics.histograms.get(name)
    if not series:
        return None
    merged: list | None = None
    for key, (total, count, buckets) in series.items():
        if not _matches(key, labels):
            continue
        if merged is None:
            merged = [0.0, 0, [0] * len(buckets)]
        merged[0] += total
        merged[1] += count
        for i, b in enumerate(buckets):
            merged[2][i] += b
    return merged


def histogram_percentile(metrics: InMemoryMetrics, name: str, q: float,
                         labels: Mapping[str, str] | None = None) \
        -> float | None:
    """``histogram_quantile(q, ...)`` over an in-memory registry.

    Returns None when the (label-filtered) series has no observations.
    Interpolates linearly inside the winning bucket and caps at the
    largest finite bound — PromQL semantics, so dashboards and this
    scoreboard cannot disagree about the same scrape.
    """
    entry = _merged_entry(metrics, name, labels or {})
    if entry is None or entry[1] == 0:
        return None
    _total, count, cumulative = entry[0], entry[1], entry[2]
    rank = q * count
    prev_cum, prev_bound = 0, 0.0
    for bound, cum in zip(metrics.buckets, cumulative):
        if cum >= rank:
            width = cum - prev_cum
            frac = (rank - prev_cum) / width if width else 1.0
            return prev_bound + (bound - prev_bound) * frac
        prev_cum, prev_bound = cum, bound
    return metrics.buckets[-1]


def histogram_cdf(metrics: InMemoryMetrics, name: str, x: float,
                  labels: Mapping[str, str] | None = None) \
        -> float | None:
    """Estimated fraction of observations <= ``x`` (linear inside the
    straddling bucket) — the violation-fraction / error-budget input."""
    entry = _merged_entry(metrics, name, labels or {})
    if entry is None or entry[1] == 0:
        return None
    count, cumulative = entry[1], entry[2]
    prev_cum, prev_bound = 0, 0.0
    for bound, cum in zip(metrics.buckets, cumulative):
        if x <= bound:
            width = bound - prev_bound
            frac = (x - prev_bound) / width if width else 1.0
            return (prev_cum + (cum - prev_cum) * frac) / count
        prev_cum, prev_bound = cum, bound
    return 1.0


@dataclass(frozen=True)
class SLObjective:
    """One declarative objective.

    ``series`` is the full exposition name (``copilot_engine_ttft_
    seconds``); evaluation strips the registry namespace. ``labels``
    narrows to a label subset (e.g. ``{"role": "decode"}`` judges only
    decode-role processes in a merged registry). ``budget`` is the
    allowed violation fraction: burn = violations/budget, burn > 1 is
    an exhausted error budget.
    """

    name: str
    series: str
    percentile: float
    threshold_s: float
    window: str = "bench"
    workload: str = ""
    labels: Mapping[str, str] = field(default_factory=dict)
    budget: float = 0.01

    def registry_name(self, namespace: str) -> str:
        prefix = f"{namespace}_"
        if self.series.startswith(prefix):
            return self.series[len(prefix):]
        return self.series

    def evaluate(self, metrics: InMemoryMetrics) -> dict:
        """Scoreboard row for this objective over ``metrics``.

        ``ok`` is None (not False) with zero observations — an absent
        workload is "no data", which callers gate explicitly
        (``require_data=True`` in :meth:`SLORegistry.evaluate`).
        """
        name = self.registry_name(metrics.namespace)
        entry = _merged_entry(metrics, name, self.labels)
        observations = entry[1] if entry else 0
        row = {
            "name": self.name, "series": self.series,
            "workload": self.workload, "window": self.window,
            "percentile": self.percentile,
            "threshold_s": self.threshold_s,
            "labels": dict(self.labels),
            "observations": observations,
            "budget": self.budget,
            "value_s": None, "violation_fraction": None,
            "burn": None, "ok": None,
        }
        if not observations:
            return row
        value = histogram_percentile(metrics, name, self.percentile,
                                     self.labels)
        cdf = histogram_cdf(metrics, name, self.threshold_s, self.labels)
        violation = max(0.0, 1.0 - (cdf if cdf is not None else 1.0))
        burn = violation / self.budget if self.budget > 0 else (
            0.0 if violation == 0.0 else float("inf"))
        row.update({
            "value_s": round(value, 6),
            "violation_fraction": round(violation, 6),
            "burn": round(burn, 4),
            "ok": bool(value <= self.threshold_s),
        })
        return row

    def check(self, value: float) -> dict:
        """Judge an externally computed percentile value (bench arms
        that measure per-request latencies directly) against this
        objective — same row shape, no histogram behind it."""
        return {
            "name": self.name, "series": self.series,
            "workload": self.workload, "window": self.window,
            "percentile": self.percentile,
            "threshold_s": self.threshold_s,
            "labels": dict(self.labels),
            "observations": None, "budget": self.budget,
            "value_s": round(float(value), 6),
            "violation_fraction": None, "burn": None,
            "ok": bool(value <= self.threshold_s),
        }


class SLORegistry:
    """Named set of objectives; registration collides loudly."""

    def __init__(self, objectives: Iterable[SLObjective] = ()):
        self._objectives: dict[str, SLObjective] = {}
        for obj in objectives:
            self.register(obj)

    def register(self, objective: SLObjective) -> SLObjective:
        if objective.name in self._objectives:
            raise ValueError(
                f"SLO objective {objective.name!r} already registered "
                f"— objectives are declarative and unique by name")
        self._objectives[objective.name] = objective
        return objective

    def objectives(self) -> list[SLObjective]:
        return list(self._objectives.values())

    def get(self, name: str) -> SLObjective:
        return self._objectives[name]

    def evaluate(self, metrics: InMemoryMetrics, *,
                 require_data: bool = False) -> dict:
        """Scoreboard over ``metrics``: per-objective rows + verdict.

        ``ok`` is True when every objective *with data* holds;
        ``require_data=True`` additionally fails objectives that saw
        zero observations (bench gates use this — a workload that
        never ran must not pass its SLO vacuously).
        """
        rows = [obj.evaluate(metrics) for obj in self.objectives()]
        evaluated = [r for r in rows if r["ok"] is not None]
        ok = all(r["ok"] for r in evaluated)
        if require_data and len(evaluated) != len(rows):
            ok = False
        return {"objectives": rows, "evaluated": len(evaluated),
                "total": len(rows), "ok": bool(ok)}


def default_registry() -> SLORegistry:
    """The serving-plane defaults — thresholds match the bench knobs
    (BENCH_TTFT_SLO=2.0 / BENCH_ITL_SLO=0.25, bench.py mixed_traffic)
    and the alert pack; the slo.json dashboard renders exactly these
    (pinned by tests/test_slo.py)."""
    return SLORegistry([
        SLObjective(
            name="interactive-ttft-p99",
            series="copilot_engine_ttft_seconds",
            percentile=0.99, threshold_s=2.0, window="bench",
            workload="interactive", budget=0.01),
        SLObjective(
            name="interactive-itl-p95",
            series="copilot_engine_itl_seconds",
            percentile=0.95, threshold_s=0.25, window="bench",
            workload="interactive", budget=0.05),
        SLObjective(
            name="queue-wait-p99",
            series="copilot_engine_queue_wait_seconds",
            percentile=0.99, threshold_s=5.0, window="bench",
            workload="batch", budget=0.01),
        SLObjective(
            name="stage-latency-p95",
            series="copilot_pipeline_stage_duration_seconds",
            percentile=0.95, threshold_s=30.0, window="bench",
            workload="batch", budget=0.05),
        SLObjective(
            name="kv-handoff-wait-p99",
            series="copilot_engine_role_handoff_wait_seconds",
            percentile=0.99, threshold_s=1.0, window="bench",
            workload="disaggregated", budget=0.01),
    ])


def render_scoreboard(board: dict) -> str:
    """Human-readable scoreboard (the CLI's default output)."""
    lines = ["SLO scoreboard "
             f"({board['evaluated']}/{board['total']} objectives with "
             f"data; overall {'OK' if board['ok'] else 'BREACH'})"]
    for r in board["objectives"]:
        if r["ok"] is None:
            verdict, value = "no-data", "-"
        else:
            verdict = "ok" if r["ok"] else "BREACH"
            value = f"{r['value_s']:.4f}s"
        burn = ("-" if r["burn"] is None else f"{r['burn']:.2f}")
        lines.append(
            f"  [{verdict:>7}] {r['name']}: "
            f"p{int(r['percentile'] * 100)}({r['series']}) = {value} "
            f"(threshold {r['threshold_s']}s, burn {burn}, "
            f"workload {r['workload'] or '-'}, window {r['window']})")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="copilot-for-consensus-tpu slo",
        description="Evaluate the declarative SLO registry over "
                    "telemetry spools (obs/ship.py) and print the "
                    "scoreboard. Exit 1 on any breach.")
    parser.add_argument(
        "sources", nargs="*",
        help="spool files or directories of *.spool.sqlite3 (a "
             "multichip bench run's spool dir, a chaos kill phase's "
             "recovered spool, ...)")
    parser.add_argument("--json", action="store_true",
                        help="emit the scoreboard as JSON")
    parser.add_argument("--require-data", action="store_true",
                        help="fail objectives with zero observations "
                             "(bench-gate semantics)")
    args = parser.parse_args(argv)

    from copilot_for_consensus_tpu.obs.ship import TelemetryAggregator

    agg = TelemetryAggregator()
    for src in args.sources:
        p = pathlib.Path(src)
        if p.is_dir():
            agg.ingest_dir(p)
        else:
            agg.ingest_spool(p)

    board = default_registry().evaluate(
        agg.metrics, require_data=args.require_data)
    board["sources"] = {"spools": agg.stats()}
    if args.json:
        print(json.dumps(board, indent=2, sort_keys=True))
    else:
        print(render_scoreboard(board))
    return 0 if board["ok"] else 1
