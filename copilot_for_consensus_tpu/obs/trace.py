"""Pipeline-wide distributed tracing: trace-context propagation over the
bus, per-stage spans, and a bounded trace collector.

PR 5's flight recorder stopped at the engine boundary: ``RequestTrace``
spans cover submit→retire inside a serving engine, but a message's
journey across the host pipeline (archive → parse → chunk → embed →
summarize → report) was invisible — ``correlation_id`` was never
carried in bus envelopes, so per-stage latency attribution had to be
re-derived from ad-hoc bench timers. This module is the
Dapper/OpenTelemetry-shaped answer, sized to this codebase:

* **Trace context over the bus.** Every published envelope carries a
  ``trace`` header block (``trace_id`` / ``span_id`` /
  ``parent_span_id`` / ``published_at``), injected once at first
  publish (``inject``) and preserved verbatim across redelivery,
  outbox replay, and requeue — at-least-once delivery yields annotated
  retries (``attempt``), never orphan traces. The publish itself is
  recorded as a zero-ish-duration ``publish`` span whose id IS the
  envelope's ``span_id``, so the consumer's stage span has a recorded
  parent and the DAG stays connected.
* **Stage spans.** ``BaseService.handle_envelope`` opens one ``stage``
  span per dispatch (``stage_span``), recording queue wait (publish →
  consume gap off ``published_at``), handler service time, redelivery
  attempt, and status; store writes / vector upserts / engine submits
  open ``child_span``s under it (the ``TracingDocumentStore`` /
  ``TracingVectorStore`` wrappers + explicit spans at the engine
  submit sites). The engine's own ``RequestTrace`` joins by the shared
  ``correlation_id`` attribute.
* **Bounded collector.** ``TraceCollector`` is a lock-cheap ring (one
  GIL-atomic deque append per span, the ``FlightRecorder``
  discipline) with Perfetto (Chrome trace event) and OTLP-JSON export
  and auto-dump on dispatch failure — the host-pipeline flight
  recorder. ``tools/tracepath.py`` reconstructs the per-thread stage
  DAG from it and names the bottleneck stage.

Everything here is host-side dict work — no device ops, no extra
syncs, no env reads (the test harness plumbs the CI dump dir through
``set_default_dump_dir``, same contract as ``engine/telemetry.py``).
"""

from __future__ import annotations

import collections
import contextlib
import json
import pathlib
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Any, Iterable, Mapping

from copilot_for_consensus_tpu.obs.metrics import check_registry_labels

#: envelope key carrying the trace context block
TRACE_KEY = "trace"

#: span kinds the pipeline emits (doc + test anchor). ``stage`` spans
#: are the only ones that land in the stage latency histograms.
SPAN_KINDS = ("publish", "stage", "store_write", "vector_upsert",
              "retrieval", "engine_submit", "engine_replay")

# ---------------------------------------------------------------------------
# Metric registry — what the tracing layer emits, in the
# engine/telemetry.py:METRICS style: the observability-pack contract
# test checks infra/grafana + infra/prometheus references against it,
# and a registry⇄emission test keeps it honest both ways. Histograms
# are emitted by services/base.py per dispatch; the span counters are
# refreshed from the collector ledger at scrape time
# (services/bootstrap.py:_BusGaugeMetrics, via set_counter).
# ---------------------------------------------------------------------------

#: metric name (sans namespace) → (type, label names, help)
PIPELINE_METRICS: dict[str, tuple[str, tuple[str, ...], str]] = {
    "pipeline_stage_duration_seconds": (
        "histogram", ("stage",),
        "Handler service time per pipeline stage span."),
    "pipeline_stage_queue_wait_seconds": (
        "histogram", ("stage",),
        "Publish → consume gap per stage span (includes redelivery "
        "latency on retries)."),
    "pipeline_spans_open_total": (
        "counter", (),
        "Spans opened by the pipeline tracer (all kinds)."),
    "pipeline_spans_dropped_total": (
        "counter", (),
        "Spans evicted from the bounded trace ring (size the "
        "collector up if this moves during an investigation)."),
}

# proc/role are stamped by the cross-process aggregator (obs/ship.py);
# declaring them here must fail at import, not at scrape time.
check_registry_labels(PIPELINE_METRICS, owner="PIPELINE_METRICS")


def prometheus_series(namespace: str = "copilot") -> dict[str, str]:
    """Full series name → type, for contract tests and docs."""
    return {f"{namespace}_{name}": typ
            for name, (typ, _labels, _help) in PIPELINE_METRICS.items()}


def _new_trace_id() -> str:
    return uuid.uuid4().hex                  # 16 bytes hex (OTLP shape)


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]             # 8 bytes hex (OTLP shape)


@dataclass
class Span:
    """One finished pipeline span. ``start_wall`` anchors to wall clock
    (cross-process join + Perfetto ts); durations are measured with
    ``time.monotonic()`` around the work."""

    trace_id: str
    span_id: str
    parent_span_id: str
    name: str                  # stage/service name or routing key
    kind: str                  # one of SPAN_KINDS
    service: str = ""
    start_wall: float = 0.0
    duration_s: float = 0.0
    status: str = "ok"         # ok | error
    error: str = ""
    correlation_id: str = ""
    event_type: str = ""
    routing_key: str = ""
    queue_wait_s: float = 0.0
    attempt: int = 0
    attrs: dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return asdict(self)


class TraceCollector:
    """Bounded ring of finished :class:`Span`s. Append is one deque op
    under the GIL (the maxlen does the eviction) plus one short lock
    for the opened-counter — cheap enough to stay on by default in
    every service's dispatch loop."""

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._ring: "collections.deque[Span]" = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self._opened = 0
        self._dump_seq = 0

    def record(self, span: Span) -> Span:
        self._ring.append(span)
        with self._lock:
            self._opened += 1
        return span

    def spans(self) -> list[Span]:
        return list(self._ring)

    def as_dicts(self) -> list[dict]:
        return [s.as_dict() for s in self.spans()]

    def stats(self) -> dict[str, int]:
        with self._lock:
            opened = self._opened
        retained = len(self._ring)
        return {"opened": opened, "retained": retained,
                "dropped": max(0, opened - retained),
                "capacity": self.capacity}

    def reset(self, capacity: int | None = None) -> None:
        """Clear the ring (benches reset between arms so per-arm orphan
        audits don't see the previous arm's evictions)."""
        if capacity is not None:
            self.capacity = capacity
        self._ring = collections.deque(maxlen=self.capacity)
        with self._lock:
            self._opened = 0

    # -- export ---------------------------------------------------------

    def export_perfetto(self, spans: Iterable[Span] | None = None) -> dict:
        """Chrome trace event format (Perfetto/chrome://tracing): one
        complete ("X") event per span, pid = service, tid = trace id —
        loadable next to the engines' device traces so a device step
        and the pipeline stage that submitted it sit in one timeline."""
        events = []
        for s in (self.spans() if spans is None else spans):
            events.append({
                "name": f"{s.kind}:{s.name}",
                "ph": "X",
                "ts": s.start_wall * 1e6,
                "dur": max(s.duration_s, 1e-6) * 1e6,
                "pid": s.service or "pipeline",
                "tid": s.trace_id[:8],
                "args": {
                    "trace_id": s.trace_id, "span_id": s.span_id,
                    "parent_span_id": s.parent_span_id,
                    "correlation_id": s.correlation_id,
                    "event_type": s.event_type,
                    "routing_key": s.routing_key,
                    "queue_wait_s": round(s.queue_wait_s, 6),
                    "attempt": s.attempt, "status": s.status,
                    **s.attrs,
                },
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_otlp(self, spans: Iterable[Span] | None = None) -> dict:
        """OTLP-JSON shape (``ExportTraceServiceRequest``): what an
        OpenTelemetry collector's HTTP receiver accepts, so the ring
        can be forwarded to any OTLP backend without a vendored SDK."""
        by_service: dict[str, list[dict]] = {}
        for s in (self.spans() if spans is None else spans):
            start_ns = int(s.start_wall * 1e9)
            attrs = [{"key": k, "value": {"stringValue": str(v)}}
                     for k, v in (
                         ("correlation_id", s.correlation_id),
                         ("event_type", s.event_type),
                         ("routing_key", s.routing_key),
                         ("queue_wait_s", round(s.queue_wait_s, 6)),
                         ("attempt", s.attempt),
                         ("kind", s.kind),
                         *sorted(s.attrs.items())) if v not in ("", None)]
            by_service.setdefault(s.service or "pipeline", []).append({
                "traceId": s.trace_id,
                "spanId": s.span_id,
                "parentSpanId": s.parent_span_id,
                "name": s.name,
                "startTimeUnixNano": str(start_ns),
                "endTimeUnixNano": str(
                    start_ns + int(s.duration_s * 1e9)),
                "status": {"code": 2 if s.status == "error" else 1,
                           **({"message": s.error} if s.error else {})},
                "attributes": attrs,
            })
        return {"resourceSpans": [{
            "resource": {"attributes": [{
                "key": "service.name",
                "value": {"stringValue": svc}}]},
            "scopeSpans": [{
                "scope": {"name": "copilot_for_consensus_tpu.obs.trace"},
                "spans": spans_}],
        } for svc, spans_ in sorted(by_service.items())]}

    def dump(self, *, error: BaseException | None = None,
             extra: dict | None = None) -> dict:
        out = {
            "dumped_wall": time.time(),
            "stats": self.stats(),
            "spans": self.as_dicts(),
        }
        if error is not None:
            out["error"] = {"type": type(error).__name__,
                            "message": str(error)}
        if extra:
            out.update(extra)
        return out

    def dump_to_file(self, directory: str | None = None,
                     tag: str = "pipeline-trace",
                     error: BaseException | None = None,
                     fmt: str = "raw") -> str:
        """Write the ring as JSON: ``fmt`` raw (span dicts, what
        tools/tracepath reads) | perfetto | otlp."""
        directory = directory or _default_dump_dir
        if not directory:
            raise ValueError("no pipeline-trace dump directory configured")
        path = pathlib.Path(directory)
        path.mkdir(parents=True, exist_ok=True)
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        if fmt == "perfetto":
            data: dict = self.export_perfetto()
        elif fmt == "otlp":
            data = self.export_otlp()
        else:
            data = self.dump(error=error)
        target = path / f"{tag}-{int(time.time())}-{seq}.json"
        target.write_text(json.dumps(data, indent=2, default=str))
        return str(target)


# ---------------------------------------------------------------------------
# process-global collector + ambient span context
# ---------------------------------------------------------------------------

_collector = TraceCollector()
_default_dump_dir: str | None = None
_tls = threading.local()        # per-thread ambient (trace_id, span_id)


def get_collector() -> TraceCollector:
    return _collector


def configure(capacity: int | None = None) -> TraceCollector:
    """Resize + clear the global ring (benches size it to their span
    volume so orphan audits never chase ring evictions)."""
    _collector.reset(capacity=capacity)
    return _collector


def set_default_dump_dir(path: str | None) -> None:
    global _default_dump_dir
    _default_dump_dir = path


def get_default_dump_dir() -> str | None:
    return _default_dump_dir


def dump_all(directory: str | None = None, tag: str = "pipeline-trace"
             ) -> list[str]:
    """Dump the global collector when it holds spans; never raises —
    this runs from test-failure hooks where a second error would mask
    the first. Returns written paths."""
    directory = directory or _default_dump_dir
    if not directory or not len(_collector._ring):
        return []
    try:
        return [_collector.dump_to_file(directory=directory, tag=tag)]
    except Exception:
        return []


def dump_on_failure(error: BaseException | None = None,
                    tag: str = "dispatch-failure") -> str | None:
    """Auto-dump hook for dispatch failures (the flight-recorder
    ``record_error`` contract): writes only when a dump dir is
    configured, never raises."""
    if not _default_dump_dir:
        return None
    try:
        return _collector.dump_to_file(directory=_default_dump_dir,
                                       tag=tag, error=error)
    except Exception:
        return None


def current_ids() -> tuple[str, str] | None:
    """Ambient (trace_id, span_id) on this thread, or None."""
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def use_context(trace_id: str, span_id: str, service: str = ""):
    """Re-establish an ambient span captured on another thread (the
    pipelined-summarization harvester, the engine replay path) so
    spans and publishes made here stay in the originating trace.
    Pass ``service`` so child/publish spans opened here attribute to
    the originating service instead of the fake-service fallbacks
    (store-method names, "publisher")."""
    prev = getattr(_tls, "ctx", None)
    prev_span = getattr(_tls, "span", None)
    _tls.ctx = (trace_id, span_id)
    # A service-only carrier (never recorded): span()/inject() read
    # only .service off the ambient span for attribution.
    _tls.span = Span(trace_id=trace_id, span_id=span_id,
                     parent_span_id="", name=service or "context",
                     kind="context",
                     service=service) if service else None
    try:
        yield
    finally:
        _tls.ctx = prev
        _tls.span = prev_span


def set_worker_label(label: str) -> None:
    """Thread-ambient worker identity (``<service>-w<i>``), set by a
    :class:`~copilot_for_consensus_tpu.services.pool.StageWorkerPool`
    worker thread at start so every stage span it dispatches carries
    which pool member did the work. Empty string clears it."""
    _tls.worker = label


def worker_label() -> str:
    return getattr(_tls, "worker", "") or ""


@contextlib.contextmanager
def span(name: str, kind: str = "stage", *, service: str = "",
         correlation_id: str = "", event_type: str = "",
         routing_key: str = "", queue_wait_s: float = 0.0,
         attempt: int = 0, parent: tuple[str, str] | None = None,
         collector: TraceCollector | None = None,
         extra_duration_s: float = 0.0, **attrs):
    """Open a span: parented under ``parent`` (or the thread's ambient
    span), made ambient for its body, recorded on exit. An exception
    marks status=error and propagates. ``extra_duration_s`` is added
    to the measured body time — batched stage dispatch attributes each
    envelope its amortized share of the wave's shared work, which the
    span body itself never executes."""
    amb = parent if parent is not None else getattr(_tls, "ctx", None)
    if amb is not None:
        trace_id, parent_span_id = amb
    else:
        trace_id, parent_span_id = _new_trace_id(), ""
    if not service:
        # child spans inherit the owning service from the ambient span
        # (a store write under the parsing stage belongs to "parsing",
        # not to a fake service named after the store method)
        amb_span = getattr(_tls, "span", None)
        service = amb_span.service if amb_span is not None else name
    s = Span(trace_id=trace_id, span_id=_new_span_id(),
             parent_span_id=parent_span_id, name=name, kind=kind,
             service=service, start_wall=time.time(),
             correlation_id=correlation_id, event_type=event_type,
             routing_key=routing_key, queue_wait_s=queue_wait_s,
             attempt=attempt, attrs=dict(attrs))
    prev = getattr(_tls, "ctx", None)
    prev_span = getattr(_tls, "span", None)
    _tls.ctx = (s.trace_id, s.span_id)
    _tls.span = s
    t0 = time.monotonic()
    try:
        yield s
    except BaseException as exc:
        s.status = "error"
        s.error = f"{type(exc).__name__}: {exc}"
        raise
    finally:
        s.duration_s = time.monotonic() - t0 + extra_duration_s
        _tls.ctx = prev
        _tls.span = prev_span
        (collector or _collector).record(s)


def child_span(kind: str, name: str = "", *, service: str = "",
               correlation_id: str = "", **attrs):
    """A child operation under the ambient stage span (store writes,
    vector upserts, engine submits). Same contract as :func:`span`,
    just named for call-site readability."""
    return span(name or kind, kind=kind, service=service,
                correlation_id=correlation_id, **attrs)


# ---------------------------------------------------------------------------
# envelope propagation
# ---------------------------------------------------------------------------


def inject(envelope: Mapping[str, Any], routing_key: str = "",
           service: str = "",
           collector: TraceCollector | None = None) -> dict[str, Any]:
    """Stamp a trace context onto an envelope at publish time.

    First publish: allocates the message's ``span_id``, records the
    ``publish`` span (parent = the publishing handler's ambient stage
    span; a publish with no ambient span roots a new trace — the
    ingestion trigger), and returns a COPY of the envelope carrying the
    ``trace`` block. Re-publish of an envelope that already carries a
    ``trace_id`` (outbox replay, redelivery requeue, DLQ requeue,
    startup requeue of a foreign envelope) returns it unchanged — the
    context, and therefore the DAG, survives at-least-once delivery."""
    existing = envelope.get(TRACE_KEY)
    if isinstance(existing, Mapping) and existing.get("trace_id"):
        return dict(envelope) if not isinstance(envelope, dict) \
            else envelope
    amb = getattr(_tls, "ctx", None)
    if amb is not None:
        trace_id, parent_span_id = amb
    else:
        trace_id, parent_span_id = _new_trace_id(), ""
    if not service:
        # attribute the publish to the service whose stage span is
        # ambient (falls back for root publishes / foreign threads)
        amb_span = getattr(_tls, "span", None)
        service = amb_span.service if amb_span is not None \
            else "publisher"
    span_id = _new_span_id()
    now = time.time()
    corr = ""
    data = envelope.get("data")
    if isinstance(data, Mapping):
        corr = str(data.get("correlation_id", "") or "")
    (collector or _collector).record(Span(
        trace_id=trace_id, span_id=span_id,
        parent_span_id=parent_span_id,
        name=routing_key or envelope.get("event_type", "publish"),
        kind="publish", service=service,
        start_wall=now, duration_s=0.0, correlation_id=corr,
        event_type=str(envelope.get("event_type", "")),
        routing_key=routing_key))
    env = dict(envelope)
    env[TRACE_KEY] = {"trace_id": trace_id, "span_id": span_id,
                      "parent_span_id": parent_span_id,
                      "published_at": now}
    return env


def extract(envelope: Mapping[str, Any]) -> dict[str, Any] | None:
    """The envelope's trace block, or None (foreign/pre-trace
    envelopes)."""
    ctx = envelope.get(TRACE_KEY)
    if isinstance(ctx, Mapping) and ctx.get("trace_id"):
        return dict(ctx)
    return None


def annotate_delivery(envelope: Mapping[str, Any], attempt: int) -> None:
    """Subscriber-side: stamp the redelivery attempt onto the envelope's
    trace block before dispatch, so the stage span is annotated (a
    retry is a new span with the SAME parent — never an orphan).
    REPLACES the trace dict instead of mutating it: the in-proc broker
    fan-out shallow-copies envelopes per consumer group, so an in-place
    write would bleed one group's attempt count into another group's
    pristine delivery."""
    if attempt <= 0 or not isinstance(envelope, dict):
        return
    ctx = envelope.get(TRACE_KEY)
    if isinstance(ctx, Mapping):
        envelope[TRACE_KEY] = {**ctx, "attempt": int(attempt)}


@contextlib.contextmanager
def stage_span(service: str, envelope: Mapping[str, Any], *,
               extra_duration_s: float = 0.0, wave: int = 0):
    """The per-dispatch stage span ``BaseService.handle_envelope``
    opens: parented on the envelope's publish span, queue wait from
    the publish stamp, attempt from the redelivery annotation. Yields
    the live :class:`Span` so the service can emit its stage metrics
    off the measured fields after the body runs.

    Batched dispatch (``BaseService.handle_envelopes``) opens one span
    per envelope with ``extra_duration_s`` = the wave's shared service
    time / wave size (honest amortized per-message residence — the
    quantity tracepath's bottleneck attribution is declared over) and
    ``wave`` = the wave size. The pool worker label, when a
    StageWorkerPool thread set one, rides every stage span."""
    ctx = extract(envelope)
    parent: tuple[str, str] | None = None
    queue_wait = 0.0
    attempt = 0
    if ctx is not None:
        parent = (str(ctx["trace_id"]), str(ctx.get("span_id", "")))
        published_at = float(ctx.get("published_at", 0.0) or 0.0)
        if published_at:
            queue_wait = max(0.0, time.time() - published_at)
        attempt = int(ctx.get("attempt", 0) or 0)
    corr = ""
    data = envelope.get("data")
    if isinstance(data, Mapping):
        corr = str(data.get("correlation_id", "") or "")
    attrs: dict[str, Any] = {}
    w = worker_label()
    if w:
        attrs["worker"] = w
    if wave:
        attrs["wave"] = int(wave)
    with span(service, kind="stage", service=service,
              correlation_id=corr,
              event_type=str(envelope.get("event_type", "")),
              queue_wait_s=queue_wait, attempt=attempt,
              parent=parent, extra_duration_s=extra_duration_s,
              **attrs) as s:
        yield s


# ---------------------------------------------------------------------------
# audits
# ---------------------------------------------------------------------------


def orphan_spans(spans: Iterable[Span | Mapping[str, Any]]
                 ) -> list[dict[str, Any]]:
    """Spans claiming a parent that is not in the set (same trace).
    Root spans (empty parent) are never orphans. Zero is the chaos
    gate's invariant: at-least-once delivery must yield annotated
    retries, not disconnected trace fragments."""
    dicts = [s.as_dict() if isinstance(s, Span) else dict(s)
             for s in spans]
    by_trace: dict[str, set[str]] = {}
    for d in dicts:
        by_trace.setdefault(d["trace_id"], set()).add(d["span_id"])
    return [d for d in dicts
            if d.get("parent_span_id")
            and d["parent_span_id"] not in by_trace.get(d["trace_id"],
                                                        set())]


# ---------------------------------------------------------------------------
# store wrappers — the child-span choke points build_pipeline wires in
# (the bus/faults.py _Wrapper delegation pattern; reads pass through)
# ---------------------------------------------------------------------------


class _TracingWrapper:
    def __init__(self, inner):
        self.inner = inner

    def __getattr__(self, name):
        return getattr(self.inner, name)


class TracingDocumentStore(_TracingWrapper):
    """Child ``store_write`` spans around document-store mutations
    (reads pass through — the interesting attribution is write
    latency under the stage span)."""

    def _traced(self, method: str, collection: str, *args, **kwargs):
        if getattr(_tls, "ctx", None) is None:     # no ambient trace
            return getattr(self.inner, method)(collection, *args,
                                               **kwargs)
        with child_span("store_write", method, collection=collection):
            return getattr(self.inner, method)(collection, *args,
                                               **kwargs)

    def upsert_document(self, collection, doc):
        return self._traced("upsert_document", collection, doc)

    def insert_document(self, collection, doc):
        return self._traced("insert_document", collection, doc)

    def insert_or_ignore(self, collection, doc):
        return self._traced("insert_or_ignore", collection, doc)

    def insert_many(self, collection, docs, ignore_duplicates=False):
        return self._traced("insert_many", collection, docs,
                            ignore_duplicates)

    def update_document(self, collection, doc_id, fields):
        return self._traced("update_document", collection, doc_id,
                            fields)

    def update_documents(self, collection, doc_ids, fields):
        return self._traced("update_documents", collection, doc_ids,
                            fields)

    def delete_document(self, collection, doc_id):
        return self._traced("delete_document", collection, doc_id)

    def delete_documents(self, collection, flt):
        return self._traced("delete_documents", collection, flt)


class TracingVectorStore(_TracingWrapper):
    """Child ``vector_upsert`` spans around ingest-path vector
    mutations (the FaultingVectorStore boundary set)."""

    def _traced(self, method: str, *args):
        if getattr(_tls, "ctx", None) is None:
            return getattr(self.inner, method)(*args)
        with child_span("vector_upsert", method):
            return getattr(self.inner, method)(*args)

    def add_embeddings(self, items):
        return self._traced("add_embeddings", items)

    def delete(self, ids):
        return self._traced("delete", ids)

    def delete_by_filter(self, flt):
        return self._traced("delete_by_filter", flt)
