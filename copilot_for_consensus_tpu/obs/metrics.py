"""Metrics collection.

Parity with ``copilot_metrics`` (ABC increment/observe/gauge/safe_push +
Prometheus/Pushgateway/Noop drivers). The Prometheus driver here keeps
counters/histograms/gauges in-process and renders the standard text
exposition format, served by the health server (obs/health.py) — no client
library dependency.
"""

from __future__ import annotations

import abc
import threading
from typing import Any, Iterable

# Top bound must exceed every latency SLO threshold the alert pack uses
# (histogram_quantile caps at the largest finite bucket, so a threshold
# at/above it could never fire).
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0, 60.0, 120.0, 300.0, 600.0)

# Labels the cross-process aggregator (obs/ship.py) stamps onto every
# merged series. A registry that declares them for its own use would be
# silently shadowed at merge time, so registration rejects them up front.
RESERVED_LABELS = ("proc", "role")


def check_registry_labels(registry: dict, owner: str = "") -> dict:
    """Validate a metric registry's declared labels at registration time.

    ``registry`` maps series name -> (type, labels-tuple, help). Raises
    ``ValueError`` if any series declares a label in ``RESERVED_LABELS``
    — the collision must be loud at import, not at scrape time when the
    aggregator stamps ``proc``/``role`` over it. Returns the registry so
    declarations can be wrapped in-place.
    """
    for name, (_typ, labels, _help) in registry.items():
        clash = [lb for lb in labels if lb in RESERVED_LABELS]
        if clash:
            raise ValueError(
                f"metric registry {owner or '<anonymous>'!s} declares "
                f"reserved label(s) {clash} on series {name!r}; "
                f"{RESERVED_LABELS} are stamped by the telemetry "
                f"aggregator and may not be declared by a registry")
    return registry


def _label_key(labels: dict[str, str] | None) -> tuple:
    return tuple(sorted((labels or {}).items()))


class MetricsCollector(abc.ABC):
    @abc.abstractmethod
    def increment(self, name: str, value: float = 1.0,
                  labels: dict[str, str] | None = None) -> None: ...

    @abc.abstractmethod
    def observe(self, name: str, value: float,
                labels: dict[str, str] | None = None) -> None: ...

    @abc.abstractmethod
    def gauge(self, name: str, value: float,
              labels: dict[str, str] | None = None) -> None: ...

    def safe_push(self) -> None:
        """Push to a gateway if this driver pushes; never raises."""


class NoopMetrics(MetricsCollector):
    def increment(self, name, value=1.0, labels=None): ...
    def observe(self, name, value, labels=None): ...
    def gauge(self, name, value, labels=None): ...


class InMemoryMetrics(MetricsCollector):
    """Thread-safe in-process metrics; also the Prometheus renderer."""

    def __init__(self, namespace: str = "copilot"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self.counters: dict[str, dict[tuple, float]] = {}
        self.gauges: dict[str, dict[tuple, float]] = {}
        self.histograms: dict[str, dict[tuple, list]] = {}
        self.buckets = DEFAULT_BUCKETS

    def increment(self, name, value=1.0, labels=None):
        with self._lock:
            series = self.counters.setdefault(name, {})
            key = _label_key(labels)
            series[key] = series.get(key, 0.0) + value

    def gauge(self, name, value, labels=None):
        with self._lock:
            self.gauges.setdefault(name, {})[_label_key(labels)] = value

    def set_counter(self, name, value, labels=None):
        """Set a counter to an absolute value — for scrape-time totals
        read from an external monotonic source (e.g. /proc cpu
        seconds), which must render with counter TYPE metadata so
        ``rate()`` consumers and OpenMetrics linters see a counter."""
        with self._lock:
            self.counters.setdefault(name, {})[_label_key(labels)] = value

    def observe(self, name, value, labels=None):
        with self._lock:
            series = self.histograms.setdefault(name, {})
            key = _label_key(labels)
            if key not in series:
                series[key] = [0.0, 0, [0] * len(self.buckets)]  # sum, count, buckets
            entry = series[key]
            entry[0] += value
            entry[1] += 1
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    entry[2][i] += 1

    def merge_histogram(self, name, labels, dsum, dcount, dbuckets):
        """Merge a pre-bucketed histogram delta into this collector.

        Used by the cross-process aggregator: a shipped spool row carries
        ``(sum, count, cumulative-bucket-counts)`` deltas that must add
        element-wise rather than re-observe (the raw samples are gone).
        ``dbuckets`` must be cumulative counts over ``self.buckets``.
        """
        if len(dbuckets) != len(self.buckets):
            raise ValueError(
                f"histogram {name!r}: bucket layout mismatch "
                f"({len(dbuckets)} vs {len(self.buckets)} bounds)")
        with self._lock:
            series = self.histograms.setdefault(name, {})
            key = _label_key(labels)
            if key not in series:
                series[key] = [0.0, 0, [0] * len(self.buckets)]
            entry = series[key]
            entry[0] += dsum
            entry[1] += dcount
            for i, dc in enumerate(dbuckets):
                entry[2][i] += dc

    # -- accessors ---------------------------------------------------------

    def snapshot(self) -> dict:
        """Deep-copied, lock-consistent view of all series.

        The shipper diffs successive snapshots to build delta rows, so
        the copy must not alias live bucket lists.
        """
        with self._lock:
            return {
                "counters": {n: dict(s) for n, s in self.counters.items()},
                "gauges": {n: dict(s) for n, s in self.gauges.items()},
                "histograms": {
                    n: {k: [e[0], e[1], list(e[2])] for k, e in s.items()}
                    for n, s in self.histograms.items()
                },
            }

    def counter_value(self, name: str, labels: dict[str, str] | None = None) -> float:
        with self._lock:
            return self.counters.get(name, {}).get(_label_key(labels), 0.0)

    def gauge_value(self, name: str, labels: dict[str, str] | None = None) -> float:
        with self._lock:
            return self.gauges.get(name, {}).get(_label_key(labels), 0.0)

    def histogram_stats(self, name: str, labels: dict[str, str] | None = None):
        with self._lock:
            entry = self.histograms.get(name, {}).get(_label_key(labels))
            if entry is None:
                return None
            return {"sum": entry[0], "count": entry[1]}

    # -- Prometheus text exposition ---------------------------------------

    @staticmethod
    def _escape(value: Any) -> str:
        # Label-value escaping per the text exposition format: backslash
        # FIRST (or the escapes it introduces get double-escaped), then
        # quote and newline.
        return (str(value).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    @staticmethod
    def _fmt_value(value: float) -> str:
        """Sample values per the text format: non-finite floats render
        as ``+Inf``/``-Inf``/``NaN`` — Python's ``str(float('inf'))``
        is ``inf``, which Prometheus rejects as unparsable and drops
        the whole scrape."""
        if value != value:                       # NaN
            return "NaN"
        if value == float("inf"):
            return "+Inf"
        if value == float("-inf"):
            return "-Inf"
        return str(value)

    def _fmt_labels(self, key: tuple, extra: Iterable[tuple] = ()) -> str:
        items = list(key) + list(extra)
        if not items:
            return ""
        body = ",".join(f'{k}="{self._escape(v)}"' for k, v in items)
        return "{" + body + "}"

    def render_prometheus(self) -> str:
        lines: list[str] = []
        ns = self.namespace
        with self._lock:
            for name, series in sorted(self.counters.items()):
                lines.append(f"# TYPE {ns}_{name} counter")
                for key, value in series.items():
                    lines.append(f"{ns}_{name}{self._fmt_labels(key)} "
                                 f"{self._fmt_value(value)}")
            for name, series in sorted(self.gauges.items()):
                lines.append(f"# TYPE {ns}_{name} gauge")
                for key, value in series.items():
                    lines.append(f"{ns}_{name}{self._fmt_labels(key)} "
                                 f"{self._fmt_value(value)}")
            for name, series in sorted(self.histograms.items()):
                lines.append(f"# TYPE {ns}_{name} histogram")
                for key, (total, count, buckets) in series.items():
                    # observe() increments every bucket with bound >= value,
                    # so the stored counts are already cumulative; the +Inf
                    # bucket must equal _count exactly.
                    for bound, bcount in zip(self.buckets, buckets):
                        lines.append(
                            f'{ns}_{name}_bucket{self._fmt_labels(key, [("le", bound)])} {bcount}'
                        )
                    lines.append(
                        f'{ns}_{name}_bucket{self._fmt_labels(key, [("le", "+Inf")])} {count}'
                    )
                    lines.append(f"{ns}_{name}_sum{self._fmt_labels(key)} "
                                 f"{self._fmt_value(total)}")
                    lines.append(f"{ns}_{name}_count{self._fmt_labels(key)} {count}")
        return "\n".join(lines) + "\n"


class PushgatewayMetrics(InMemoryMetrics):
    """In-memory metrics pushed to a Prometheus Pushgateway on safe_push().

    Pipeline services push after each event batch, mirroring the reference
    (``embedding/app/service.py:325-329``). Network errors are swallowed —
    metrics must never take the pipeline down.
    """

    def __init__(self, gateway_url: str, job: str, namespace: str = "copilot"):
        super().__init__(namespace=namespace)
        self.gateway_url = gateway_url.rstrip("/")
        self.job = job

    def safe_push(self) -> None:
        try:
            import urllib.request

            body = self.render_prometheus().encode()
            req = urllib.request.Request(
                f"{self.gateway_url}/metrics/job/{self.job}",
                data=body, method="PUT",
                headers={"Content-Type": "text/plain"},
            )
            urllib.request.urlopen(req, timeout=5).read()
        except Exception:
            pass


def create_metrics_collector(config: Any = None) -> MetricsCollector:
    cfg = dict(config or {})
    driver = cfg.get("driver", "noop")
    if driver == "noop":
        return NoopMetrics()
    if driver in ("inmemory", "prometheus"):
        return InMemoryMetrics(namespace=cfg.get("namespace", "copilot"))
    if driver == "pushgateway":
        return PushgatewayMetrics(
            gateway_url=cfg.get("gateway_url", "http://localhost:9091"),
            job=cfg.get("job", "copilot"),
            namespace=cfg.get("namespace", "copilot"),
        )
    if driver == "azure_monitor":
        from copilot_for_consensus_tpu.obs.azure_monitor import (
            AzureMonitorMetrics,
        )

        return AzureMonitorMetrics(
            cfg.get("connection_string", ""),
            namespace=cfg.get("namespace", "copilot"),
            export_interval_s=float(cfg.get("export_interval_s", 60.0)),
            raise_on_error=bool(cfg.get("raise_on_error", False)),
        )
    raise ValueError(f"unknown metrics driver {driver!r}")
