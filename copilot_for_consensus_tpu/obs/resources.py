"""Process/host resource gauges for the alert pack's resource_limits
group.

The reference watches container resources through cAdvisor +
node-exporter series (``infra/prometheus/alerts/resource_limits.yml``);
this framework's services are first-party processes, so the equivalent
gauges are read straight from ``/proc``, the cgroup-v2 files, and
``statvfs`` — no sidecar exporters. Every service's ``/metrics``
exposition stamps them (``services/bootstrap._BusGaugeMetrics``), and
the standalone stats exporter (``tools/exporters.py``) does too.

Series emitted (all prefixed by the metrics namespace, default
``copilot``):

- ``process_resident_bytes``       — VmRSS
- ``process_memory_limit_bytes``   — cgroup memory.max, else host
  MemTotal (so the ratio alert is meaningful under compose/k8s limits
  AND bare processes)
- ``process_cpu_seconds_total``    — utime+stime (counter)
- ``process_open_fds``
- ``process_start_time_seconds``   — wall-clock at module import;
  ``changes()`` over it is the restart-rate alert
- ``disk_free_bytes`` / ``disk_total_bytes`` with a ``path`` label
"""

from __future__ import annotations

import os
import time

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_START_TIME = time.time()

#: paths whose free space matters operationally: the working dir (sqlite
#: stores, archives, logstore files live under it) and the root fs
_DISK_PATHS: tuple[str, ...] = (".", "/")


def _read_first(path: str) -> str | None:
    try:
        with open(path) as fh:
            return fh.read()
    except OSError:
        return None


def _rss_bytes() -> float:
    text = _read_first("/proc/self/status") or ""
    for line in text.splitlines():
        if line.startswith("VmRSS:"):
            return float(line.split()[1]) * 1024.0
    return 0.0


def _cpu_seconds() -> float:
    text = _read_first("/proc/self/stat") or ""
    # fields 14/15 (1-based) are utime/stime in clock ticks; the comm
    # field can contain spaces, so split after the closing paren
    try:
        rest = text.rsplit(")", 1)[1].split()
        return (int(rest[11]) + int(rest[12])) / float(_CLK_TCK)
    except (IndexError, ValueError):
        return 0.0


def _memory_limit_bytes() -> float:
    # cgroup v2 (compose/k8s memory limits land here); "max" = unlimited
    for path in ("/sys/fs/cgroup/memory.max",
                 "/sys/fs/cgroup/memory/memory.limit_in_bytes"):
        text = (_read_first(path) or "").strip()
        if text and text != "max":
            try:
                v = float(text)
            except ValueError:
                continue
            # some v1 kernels report "no limit" as a huge sentinel
            if v < 1 << 60:
                return v
    text = _read_first("/proc/meminfo") or ""
    for line in text.splitlines():
        if line.startswith("MemTotal:"):
            return float(line.split()[1]) * 1024.0
    return 0.0


def _open_fds() -> float:
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return 0.0


def resource_gauges(metrics, disk_paths: tuple[str, ...] = _DISK_PATHS,
                    ) -> None:
    """Stamp the resource series into ``metrics`` (an object with
    ``gauge(name, value, labels=...)``). Never raises: a missing /proc
    entry (non-Linux dev box) just leaves gauges at 0, which the alert
    ratios treat as absent-not-firing."""
    metrics.gauge("process_resident_bytes", _rss_bytes())
    metrics.gauge("process_memory_limit_bytes", _memory_limit_bytes())
    # a _total series is a COUNTER; render it with counter metadata
    # where the collector supports absolute counter sets
    set_counter = getattr(metrics, "set_counter", metrics.gauge)
    set_counter("process_cpu_seconds_total", _cpu_seconds())
    metrics.gauge("process_open_fds", _open_fds())
    metrics.gauge("process_start_time_seconds", _START_TIME)
    for path in disk_paths:
        try:
            st = os.statvfs(path)
        except OSError:
            continue
        label = {"path": os.path.abspath(path)}
        metrics.gauge("disk_free_bytes",
                      float(st.f_bavail * st.f_frsize), labels=label)
        metrics.gauge("disk_total_bytes",
                      float(st.f_blocks * st.f_frsize), labels=label)
