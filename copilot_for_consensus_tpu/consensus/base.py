"""Consensus detection over discussion threads.

Reference surface: ``copilot_consensus/consensus.py`` —
ConsensusLevel/Signal (``:33,45``), detector ABC (``:68``),
HeuristicConsensusDetector with agreement/disagreement regex patterns and
thresholds (``:90,126,167``), Mock (``:290``), ML stub (``:351``),
factory (``:399``). Here the ML detector is TPU-real: it scores
agreement via the first-party embedding encoder (cosine similarity to
anchor statements) instead of an unimplemented stub.
"""

from __future__ import annotations

import abc
import enum
import re
from dataclasses import dataclass, field
from typing import Any, Sequence


class ConsensusLevel(enum.Enum):
    STRONG_CONSENSUS = "strong_consensus"
    ROUGH_CONSENSUS = "rough_consensus"
    CONTESTED = "contested"
    NO_SIGNAL = "no_signal"


@dataclass
class ConsensusSignal:
    level: ConsensusLevel
    score: float                       # [-1, 1]: -1 contested, +1 agreement
    agree_count: int = 0
    disagree_count: int = 0
    evidence: list[str] = field(default_factory=list)


class ConsensusDetector(abc.ABC):
    @abc.abstractmethod
    def detect(self, messages: Sequence[dict[str, Any]]) -> ConsensusSignal:
        """messages: dicts with at least ``body`` (and optionally
        ``from_addr``)."""


_AGREE_PATTERNS = [
    r"(?:^|[\s(])\+1\b", r"\bagree[sd]?\b", r"\bsounds good\b", r"\blgtm\b",
    r"\bsupport (?:this|the) (?:proposal|draft|change)\b",
    r"\bno objection[s]?\b", r"\bworks for me\b", r"\bin favou?r\b",
    r"\bship it\b", r"\bconsensus\b",
]
_DISAGREE_PATTERNS = [
    r"(?:^|[\s(])-1\b", r"\bdisagree[sd]?\b", r"\bobject(?:ion[s]?|s|ed)?\b",
    r"\boppose[sd]?\b", r"\bconcern(?:s|ed)?\b", r"\bproblematic\b",
    r"\bblock(?:ing|er)?\b", r"\bstrongly against\b", r"\bbroken\b",
]


def _signal_from_counts(agree: int, disagree: int, evidence: list[str],
                        strong: float, rough: float,
                        min_signals: int) -> ConsensusSignal:
    total = agree + disagree
    if total < min_signals:
        return ConsensusSignal(ConsensusLevel.NO_SIGNAL, 0.0, agree,
                               disagree, evidence)
    ratio = agree / total
    score = 2.0 * ratio - 1.0
    level = (ConsensusLevel.STRONG_CONSENSUS if ratio >= strong
             else ConsensusLevel.ROUGH_CONSENSUS if ratio >= rough
             else ConsensusLevel.CONTESTED)
    return ConsensusSignal(level, score, agree, disagree, evidence)


class HeuristicConsensusDetector(ConsensusDetector):
    """Regex vote counting with thresholds (reference ``:90-167``)."""

    def __init__(self, strong_threshold: float = 0.8,
                 rough_threshold: float = 0.55, min_signals: int = 2):
        self.strong_threshold = strong_threshold
        self.rough_threshold = rough_threshold
        self.min_signals = min_signals
        self._agree = [re.compile(p, re.I) for p in _AGREE_PATTERNS]
        self._disagree = [re.compile(p, re.I) for p in _DISAGREE_PATTERNS]

    def detect(self, messages: Sequence[dict[str, Any]]) -> ConsensusSignal:
        agree, disagree, evidence = 0, 0, []
        for msg in messages:
            body = (msg.get("body") or "")
            a = sum(1 for p in self._agree if p.search(body))
            d = sum(1 for p in self._disagree if p.search(body))
            if a > d:
                agree += 1
                evidence.append(f"agree: {body.strip()[:80]}")
            elif d > a:
                disagree += 1
                evidence.append(f"disagree: {body.strip()[:80]}")
        return _signal_from_counts(agree, disagree, evidence,
                                   self.strong_threshold,
                                   self.rough_threshold, self.min_signals)


class MockConsensusDetector(ConsensusDetector):
    def __init__(self, level: ConsensusLevel = ConsensusLevel.NO_SIGNAL,
                 score: float = 0.0):
        self.level = level
        self.score = score

    def detect(self, messages):
        return ConsensusSignal(self.level, self.score)


class EmbeddingConsensusDetector(ConsensusDetector):
    """TPU-ML detector: scores each message by cosine similarity of its
    embedding to agreement/disagreement anchor sentences, then applies the
    heuristic thresholds. Where the reference's MLConsensusDetector is an
    unimplemented stub (``consensus.py:351``), this one runs."""

    _AGREE_ANCHOR = "I agree, this sounds good, +1, support the proposal"
    _DISAGREE_ANCHOR = ("I disagree, objection, this is problematic, "
                        "concerns, -1")

    def __init__(self, embedding_provider, strong_threshold: float = 0.8,
                 rough_threshold: float = 0.55, min_signals: int = 2,
                 margin: float = 0.05):
        self.provider = embedding_provider
        self.margin = margin
        self._thresholds = (strong_threshold, rough_threshold, min_signals)
        anchors = self.provider.embed_batch(
            [self._AGREE_ANCHOR, self._DISAGREE_ANCHOR])
        self._agree_vec, self._disagree_vec = anchors

    @staticmethod
    def _dot(a, b) -> float:
        return float(sum(x * y for x, y in zip(a, b)))

    def detect(self, messages: Sequence[dict[str, Any]]) -> ConsensusSignal:
        strong, rough, min_signals = self._thresholds
        agree, disagree, evidence = 0, 0, []
        bodies = [(msg.get("body") or "") for msg in messages]
        vecs = self.provider.embed_batch(bodies) if bodies else []
        for body, vec in zip(bodies, vecs):
            sa = self._dot(vec, self._agree_vec)
            sd = self._dot(vec, self._disagree_vec)
            if sa - sd > self.margin:
                agree += 1
                evidence.append(f"agree({sa - sd:.2f}): {body[:60]}")
            elif sd - sa > self.margin:
                disagree += 1
                evidence.append(f"disagree({sd - sa:.2f}): {body[:60]}")
        return _signal_from_counts(agree, disagree, evidence, strong,
                                   rough, min_signals)


def create_consensus_detector(config: Any = None, **kwargs: Any
                              ) -> ConsensusDetector:
    driver = "heuristic"
    if config is not None:
        driver = (config.get("driver", "heuristic")
                  if isinstance(config, dict)
                  else getattr(config, "driver", "heuristic"))
    if driver == "heuristic":
        return HeuristicConsensusDetector()
    if driver == "mock":
        return MockConsensusDetector()
    if driver == "embedding":
        provider = kwargs.get("embedding_provider")
        if provider is None:
            raise ValueError("embedding driver needs embedding_provider=")
        return EmbeddingConsensusDetector(provider)
    raise ValueError(f"unknown consensus_detector driver {driver!r}")


from copilot_for_consensus_tpu.core.factory import register_driver  # noqa: E402

for _name in ("heuristic", "mock", "embedding"):
    register_driver("consensus_detector", _name, create_consensus_detector)
