"""Consensus detection (reference: ``adapters/copilot_consensus``)."""

from copilot_for_consensus_tpu.consensus.base import (
    ConsensusDetector,
    ConsensusLevel,
    ConsensusSignal,
    HeuristicConsensusDetector,
    MockConsensusDetector,
    create_consensus_detector,
)

__all__ = [
    "ConsensusDetector",
    "ConsensusLevel",
    "ConsensusSignal",
    "HeuristicConsensusDetector",
    "MockConsensusDetector",
    "create_consensus_detector",
]
