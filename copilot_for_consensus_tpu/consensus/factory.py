"""Driver registration shim (registration lives in base.py)."""

from copilot_for_consensus_tpu.consensus.base import (  # noqa: F401
    create_consensus_detector,
)
