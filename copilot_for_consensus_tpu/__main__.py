"""Operator CLI: the deployment entry points, one per process role.

The reference deploys via docker-compose with one container per service
(``docker-compose.services.yml``); here the roles are subcommands of one
package CLI (used by ``deploy/docker-compose.yml``):

    python -m copilot_for_consensus_tpu serve        # pipeline + gateway
    python -m copilot_for_consensus_tpu broker       # durable bus broker
    python -m copilot_for_consensus_tpu retry-job    # stuck-doc requeue
    python -m copilot_for_consensus_tpu failed-queues list ...
"""

from __future__ import annotations

import argparse
import json
import pathlib
import signal
import sys
import threading


def _load_config(path: str | None) -> dict:
    if not path:
        return {}
    text = pathlib.Path(path).read_text()
    if path.endswith((".yml", ".yaml")):
        import yaml

        return yaml.safe_load(text) or {}
    return json.loads(text)


def _cmd_serve(args: argparse.Namespace) -> int:
    from copilot_for_consensus_tpu.services.bootstrap import serve_pipeline

    cfg = _load_config(args.config)
    # An EMPTY multihost section (or `true`) means TPU-pod
    # auto-discovery, so plain truthiness is the wrong gate; `false` /
    # `null` explicitly disable.
    mh = cfg.get("multihost")
    if mh is not None and mh is not False:
        # Must join the distributed runtime BEFORE any engine triggers a
        # device query — jax.devices() then spans the whole slice/pod.
        from copilot_for_consensus_tpu.parallel.multihost import (
            initialize_multihost,
        )
        initialize_multihost(mh)
    server = serve_pipeline(cfg, host=args.host, port=args.port)
    server.start()
    print(json.dumps({"event": "serving", "host": args.host,
                      "port": server.port}), flush=True)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    # Graceful drain (services/lifecycle.py; docs/runbooks/
    # rolling-restart.md): readiness flips 503 FIRST, pools stop
    # consuming (nothing nacked — the broker redelivers nothing after
    # a clean drain), the engine finishes active slots up to the
    # drain deadline then evacuates-and-journals the rest, the publish
    # outbox flushes, and only then does the process exit. A second
    # signal during the drain is absorbed (the stop event is already
    # set); SIGKILL remains the hard path the engine journal exists
    # for.
    report = server.drain()
    print(json.dumps({"event": "drained", **report}), flush=True)
    return 0


def _cmd_retry_job(args: argparse.Namespace) -> int:
    from copilot_for_consensus_tpu.bus.factory import create_publisher
    from copilot_for_consensus_tpu.storage.factory import (
        create_document_store,
    )
    from copilot_for_consensus_tpu.tools.retry_job import (
        RetryStuckDocumentsJob,
    )

    cfg = _load_config(args.config)
    store = create_document_store(cfg.get("document_store",
                                          {"driver": "sqlite"}))
    store.connect()
    pub = create_publisher(cfg.get("bus", {"driver": "broker"}))
    pub.connect()
    from copilot_for_consensus_tpu.obs.metrics import (
        create_metrics_collector,
    )
    job = RetryStuckDocumentsJob(
        store, pub,
        metrics=create_metrics_collector(cfg.get("metrics")))
    if args.once:
        print(json.dumps({"event": "retry_sweep", **job.run_once()}),
              flush=True)
        return 0
    job.run_loop(interval_seconds=args.interval)
    return 0


def _cmd_migrate(args: argparse.Namespace) -> int:
    from copilot_for_consensus_tpu.storage.factory import (
        create_document_store,
    )
    from copilot_for_consensus_tpu.tools.data_migration import (
        export_data,
        import_data,
    )
    from copilot_for_consensus_tpu.vectorstore.factory import (
        create_vector_store,
    )

    cfg = _load_config(args.config)
    store = create_document_store(cfg.get("document_store",
                                          {"driver": "sqlite"}))
    store.connect()
    # The vector leg only makes sense against a durable index: exporting
    # a freshly-constructed empty store would clobber a previous dump
    # while printing success, and an import that never save()s is lost
    # at process exit — so both ends key off persist_path.
    vs_cfg = dict(cfg.get("vector_store") or {})
    persist = vs_cfg.get("persist_path")
    vs = None
    if vs_cfg and persist:
        vs = create_vector_store(vs_cfg)
        if args.cmd == "export-data":
            if pathlib.Path(persist).exists():
                vs.load(persist)
            else:
                print(json.dumps({"event": "vector_export_skipped",
                                  "reason": f"no index at {persist}"}),
                      flush=True)
                vs = None
    elif vs_cfg:
        print(json.dumps({"event": "vector_leg_skipped",
                          "reason": "vector_store.persist_path not set"}),
              flush=True)
    fn = export_data if args.cmd == "export-data" else import_data
    counts = fn(store, args.dir, vector_store=vs)
    if vs is not None and args.cmd == "import-data":
        vs.save(persist)
    print(json.dumps({"event": args.cmd.replace("-", "_"), **counts}),
          flush=True)
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    ap = argparse.ArgumentParser(prog="copilot_for_consensus_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    serve = sub.add_parser("serve", help="pipeline + unified gateway")
    serve.add_argument("--config", default=None,
                       help="JSON/YAML pipeline config")
    serve.add_argument("--host", default="0.0.0.0")
    serve.add_argument("--port", type=int, default=8080)

    sub.add_parser("broker", help="durable bus broker",
                   add_help=False)

    retry = sub.add_parser("retry-job", help="stuck-document requeue")
    retry.add_argument("--config", default=None)
    retry.add_argument("--interval", type=float, default=300.0)
    retry.add_argument("--once", action="store_true")

    sub.add_parser("failed-queues", help="failed-queue operator CLI",
                   add_help=False)

    sub.add_parser("logmine", help="mine templates from JSON logs",
                   add_help=False)

    sub.add_parser("logstore", help="log aggregation sink + query API "
                   "(Loki/Promtail role)", add_help=False)

    sub.add_parser("exporters", help="store/vector stats exporter",
                   add_help=False)

    sub.add_parser("tracepath", help="pipeline trace critical-path "
                   "analyzer (bottleneck stage)", add_help=False)

    sub.add_parser("slo", help="SLO scoreboard over telemetry spools "
                   "(merged registries, error-budget burn)",
                   add_help=False)

    for name, hlp in (("export-data", "dump all collections to JSONL"),
                      ("import-data", "load a JSONL dump")):
        mig = sub.add_parser(name, help=hlp)
        mig.add_argument("--config", default=None)
        mig.add_argument("--dir", required=True,
                         help="dump directory (out for export, src for "
                              "import)")

    # Delegating subcommands keep their own argparsers: split argv at the
    # subcommand and hand the rest through untouched.
    if argv and argv[0] == "broker":
        from copilot_for_consensus_tpu.bus.broker import main as broker_main

        return broker_main(argv[1:])
    if argv and argv[0] == "failed-queues":
        from copilot_for_consensus_tpu.tools.failed_queues import (
            main as fq_main,
        )

        return fq_main(argv[1:])
    if argv and argv[0] == "logmine":
        from copilot_for_consensus_tpu.tools.logmine import main as lm_main

        return lm_main(argv[1:])
    if argv and argv[0] == "logstore":
        from copilot_for_consensus_tpu.tools.logstore import (
            main as ls_main,
        )

        return ls_main(argv[1:])
    if argv and argv[0] == "exporters":
        from copilot_for_consensus_tpu.tools.exporters import main as ex_main

        return ex_main(argv[1:])
    if argv and argv[0] == "tracepath":
        from copilot_for_consensus_tpu.tools.tracepath import (
            main as tp_main,
        )

        return tp_main(argv[1:])
    if argv and argv[0] == "slo":
        from copilot_for_consensus_tpu.obs.slo import main as slo_main

        return slo_main(argv[1:])

    args = ap.parse_args(argv)
    if args.cmd == "serve":
        return _cmd_serve(args)
    if args.cmd == "retry-job":
        return _cmd_retry_job(args)
    if args.cmd in ("export-data", "import-data"):
        return _cmd_migrate(args)
    raise AssertionError(args.cmd)


if __name__ == "__main__":
    raise SystemExit(main())
