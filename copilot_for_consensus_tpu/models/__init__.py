"""TPU-native model zoo.

First-party JAX replacements for the inference engines the reference
delegates to (SURVEY.md §0): the generative LLM role played by
Ollama/llama.cpp (``adapters/copilot_summarization/.../factory.py:89-94``)
and the embedding-encoder role played by sentence-transformers
(``adapters/copilot_embedding/.../sentence_transformer_provider.py:19``).

Pure functional style: parameters are pytrees of ``jnp`` arrays, every
forward pass is a jit-able function of ``(params, inputs)``, layers are
stacked on a leading axis and driven by ``lax.scan`` so compile time stays
flat in depth and pjit shards one stacked tensor per weight.
"""

from copilot_for_consensus_tpu.models.configs import (
    DecoderConfig,
    EncoderConfig,
    DECODER_CONFIGS,
    ENCODER_CONFIGS,
    decoder_config,
    encoder_config,
)

__all__ = [
    "DecoderConfig",
    "EncoderConfig",
    "DECODER_CONFIGS",
    "ENCODER_CONFIGS",
    "decoder_config",
    "encoder_config",
]
