"""Model configuration registry.

Serving targets mirror BASELINE.json's five configs: MiniLM-class encoder
(embedding service), Mistral-7B-class and Llama-3-8B-class dense decoders
(summarization / RAG Q&A), Mixtral-8x7B-class MoE decoder (long-context
consensus). `tiny_*` variants keep the full code path but run in tests.
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class DecoderConfig:
    name: str = "decoder"
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 10000.0
    max_seq_len: int = 32768
    sliding_window: int = 0          # 0 = full causal attention
    norm_eps: float = 1e-5
    # MoE (0 experts = dense FFN)
    n_experts: int = 0
    experts_per_token: int = 2
    expert_capacity_factor: float = 1.25
    tie_embeddings: bool = False
    #: explicit per-head width; 0 derives d_model // n_heads. Needed by
    #: tensor-parallel stage-local views, where n_heads is divided by tp
    #: but each head keeps its full width.
    head_dim_override: int = 0

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.d_model // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0


@dataclass(frozen=True)
class EncoderConfig:
    name: str = "encoder"
    vocab_size: int = 30522
    d_model: int = 384
    n_layers: int = 6
    n_heads: int = 12
    d_ff: int = 1536
    max_positions: int = 512
    norm_eps: float = 1e-12

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


DECODER_CONFIGS: dict[str, DecoderConfig] = {
    # Mistral-7B class (BASELINE config 2): GQA 32/8, SWA 4096.
    "mistral-7b": DecoderConfig(
        name="mistral-7b", vocab_size=32000, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, d_ff=14336, rope_theta=1e6,
        max_seq_len=32768, sliding_window=4096,
    ),
    # Llama-3-8B class (BASELINE config 3): bigger vocab, theta 5e5.
    "llama-3-8b": DecoderConfig(
        name="llama-3-8b", vocab_size=128256, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, d_ff=14336, rope_theta=5e5,
        max_seq_len=8192,
    ),
    # Mixtral-8x7B class (BASELINE config 5): 8 experts, top-2.
    "mixtral-8x7b": DecoderConfig(
        name="mixtral-8x7b", vocab_size=32000, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, d_ff=14336, rope_theta=1e6,
        max_seq_len=32768, n_experts=8, experts_per_token=2,
    ),
    # Test-scale models: same code path, minutes-not-hours compile.
    "tiny": DecoderConfig(
        name="tiny", vocab_size=512, d_model=128, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=256, max_seq_len=512, sliding_window=0,
    ),
    "tiny-swa": DecoderConfig(
        name="tiny-swa", vocab_size=512, d_model=128, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=256, max_seq_len=512, sliding_window=64,
    ),
    "tiny-moe": DecoderConfig(
        name="tiny-moe", vocab_size=512, d_model=128, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=256, max_seq_len=512, n_experts=4,
        experts_per_token=2,
    ),
}

ENCODER_CONFIGS: dict[str, EncoderConfig] = {
    # all-MiniLM-L6-v2 class — the reference's default embedder
    # (sentence_transformer_provider.py:19), dim 384.
    "minilm-l6": EncoderConfig(
        name="minilm-l6", vocab_size=30522, d_model=384, n_layers=6,
        n_heads=12, d_ff=1536, max_positions=512,
    ),
    "tiny": EncoderConfig(
        name="tiny", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
        d_ff=128, max_positions=128,
    ),
}


def decoder_config(name: str, **overrides) -> DecoderConfig:
    cfg = DECODER_CONFIGS[name]
    return replace(cfg, **overrides) if overrides else cfg


def encoder_config(name: str, **overrides) -> EncoderConfig:
    cfg = ENCODER_CONFIGS[name]
    return replace(cfg, **overrides) if overrides else cfg
