"""Shared transformer building blocks (functional, pytree params).

Conventions:
* params are plain dicts of ``jnp`` arrays; a parallel tree of logical-axis
  tuples (see ``parallel/sharding.py``) describes how each leaf shards.
* activations flow in the compute dtype (bf16 by default); norms and
  softmax statistics accumulate in fp32 — the standard TPU recipe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from copilot_for_consensus_tpu.models.configs import DecoderConfig
from copilot_for_consensus_tpu.ops.attention import attention, decode_attention

# ---------------------------------------------------------------------------
# Matmul with transparent int8 weight dequantization
# ---------------------------------------------------------------------------


def qmatmul(x: jax.Array, w) -> jax.Array:
    """``x @ w`` where ``w`` is a plain array or a quantized leaf
    (``models.quant``: int8 per-channel or packed-int4 group-wise).
    On TPU the quantized paths run the fused Pallas kernels
    (``ops/quant_matmul.py``) so the bf16 dequantized weight never
    touches HBM — decode streams the int8/int4 bytes, once."""
    from copilot_for_consensus_tpu.models.quant import (
        act_quant_mode,
        pallas_qmatmul_enabled,
        quant_kind,
    )

    kind = quant_kind(w)
    on_tpu = jax.default_backend() == "tpu"
    # Activation quantization pays only where the matmul is MXU-bound:
    # the int8×int8 MXU path doubles the FLOPs rate, so a batched
    # prefill wave (m ≥ 1024 rows) halves its dominant cost. At decode
    # widths (m = slots) the step is weight-bandwidth-bound and the
    # dequant-fused XLA expression wins — measured 3225 vs 2662 tok/s
    # with a8 forced on decode.
    m = 1
    for s in x.shape[:-1]:
        m *= s
    a8 = act_quant_mode() == "a8" and on_tpu
    if kind == "int4":
        from copilot_for_consensus_tpu.ops.quant_matmul import (
            int4_matmul,
            int4_matmul_xla,
            w4a8_matmul,
        )
        if w["q4"].ndim == 2 and pallas_qmatmul_enabled() and on_tpu:
            # int4 in a8 mode takes the int8-MXU kernel at EVERY width:
            # the bf16 group dots of the weight-only kernel lose to it
            # at decode shapes too (harness: 31.2 vs 33.7 ms/pass).
            if a8:
                return w4a8_matmul(x, w["q4"], w["scale"])
            return int4_matmul(x, w["q4"], w["scale"])
        return int4_matmul_xla(x, w["q4"], w["scale"])
    if kind == "int8":
        # int8 a8 pays only where the matmul is MXU-bound (m ≥ 1024,
        # prefill waves); at decode widths the dequant-fused XLA
        # expression wins (3225 vs 2662 tok/s forced).
        if (a8 and m >= 1024 and w["q"].ndim == 2
                and pallas_qmatmul_enabled()):
            from copilot_for_consensus_tpu.ops.quant_matmul import (
                w8a8_matmul,
            )
            return w8a8_matmul(x, w["q"], w["scale"])
        # Measured on v5e: XLA's own dequant-fused matmul streams int8
        # weights faster than the Pallas kernel at serving shapes
        # (engine decode 2778 vs 2146 tok/s), and it partitions under
        # GSPMD — so the XLA expression is the weight-only int8 path.
        # The Pallas int8 kernel stays for reference/experiments
        # (ops/quant_matmul.int8_matmul).
        return (x @ w["q"].astype(x.dtype)) * w["scale"].astype(x.dtype)
    return x @ w


# ---------------------------------------------------------------------------
# Norms
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)


def layer_norm(x: jax.Array, weight: jax.Array, bias: jax.Array,
               eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) * jax.lax.rsqrt(var + eps)
    out = normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# Rotary position embedding (GPT-NeoX rotate-half convention, as used by
# Llama / Mistral / Mixtral)
# ---------------------------------------------------------------------------


def rope_frequencies(head_dim: int, theta: float) -> jax.Array:
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponents)                     # [head_dim/2]


def apply_rope(x: jax.Array, positions: jax.Array,
               inv_freq: jax.Array) -> jax.Array:
    """x: [B, H, S, D]; positions: [B, S] (int) → same shape, rotated."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B,S,D/2]
    cos = jnp.cos(angles)[:, None, :, :]                  # [B,1,S,D/2]
    sin = jnp.sin(angles)[:, None, :, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# Attention block (GQA) — prefill and decode variants share projections
# ---------------------------------------------------------------------------


def _project_qkv(x: jax.Array, layer: dict, cfg: DecoderConfig,
                 positions: jax.Array):
    b, s, _ = x.shape
    dh = cfg.head_dim
    if "wqkv" in layer:
        # Fused int4 projection (quant.fuse_int4_projections): one
        # kernel call; split the product by column.
        nq, nkv = cfg.n_heads * dh, cfg.n_kv_heads * dh
        qkv = qmatmul(x, layer["wqkv"])
        q, k, v = (qkv[..., :nq], qkv[..., nq:nq + nkv],
                   qkv[..., nq + nkv:])
    else:
        q = qmatmul(x, layer["wq"])
        k = qmatmul(x, layer["wk"])
        v = qmatmul(x, layer["wv"])
    q = q.reshape(b, s, cfg.n_heads, dh).transpose(0, 2, 1, 3)
    k = k.reshape(b, s, cfg.n_kv_heads, dh).transpose(0, 2, 1, 3)
    v = v.reshape(b, s, cfg.n_kv_heads, dh).transpose(0, 2, 1, 3)
    inv_freq = rope_frequencies(dh, cfg.rope_theta)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    return q, k, v


def attn_prefill(x: jax.Array, layer: dict, cfg: DecoderConfig,
                 lengths: jax.Array | None = None, impl: str = "auto"):
    """Full-sequence causal attention. Returns (out [B,S,D_model], k, v)
    with k/v in [B, Hkv, S, Dh] for cache insertion."""
    b, s, _ = x.shape
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    q, k, v = _project_qkv(x, layer, cfg, positions)
    o = attention(q, k, v, causal=True, window=cfg.sliding_window,
                  kv_lengths=lengths, impl=impl)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * cfg.head_dim)
    return qmatmul(o, layer["wo"]), k, v


def attn_prefill_seeded(x: jax.Array, layer: dict, cfg: DecoderConfig,
                        k_pref: jax.Array, v_pref: jax.Array,
                        prefix_lens: jax.Array,
                        lengths: jax.Array | None = None):
    """Suffix-prefill attention against a seeded prefix (prefix KV
    cache admission). Row b's tokens sit at absolute positions
    ``prefix_lens[b] + i`` — RoPE rotates with that offset — and attend
    (reused prefix KV ++ fresh causal suffix) in one joint softmax
    (``ops.attention.prefill_attention_seeded``). k_pref/v_pref:
    [B, Hkv, P, Dh]; rows with prefix_lens 0 reduce exactly to
    ``attn_prefill``. Returns (out [B,S,D_model], k, v) with fresh
    SUFFIX k/v in [B, Hkv, S, Dh] for cache insertion at the offset.

    Sliding-window models are routed away by the engine (a reused
    prefix inside the window would need window masking against the
    absolute timeline, which this path doesn't implement)."""
    from copilot_for_consensus_tpu.ops.attention import (
        prefill_attention_seeded,
    )

    b, s, _ = x.shape
    positions = prefix_lens[:, None] + jnp.arange(s)[None, :]
    q, k, v = _project_qkv(x, layer, cfg, positions)
    o = prefill_attention_seeded(q, k, v, k_pref, v_pref,
                                 prefix_lens, kv_lengths=lengths)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * cfg.head_dim)
    return qmatmul(o, layer["wo"]), k, v


def attn_decode_stacked(x: jax.Array, layer: dict, cfg: DecoderConfig,
                        positions: jax.Array, k_cache: jax.Array,
                        v_cache: jax.Array, li: jax.Array,
                        kv_len: int | None = None):
    """Decode attention against the FULL stacked cache [L,B,Hkv,S,Dh].

    Writes one kv column per slot into layer ``li`` via scatter (touches
    only B columns, not a whole layer slice) and reads only the
    ``kv_len`` prefix. This lets the layer loop carry the stacked cache
    — the alternative (cache as scan xs/ys) re-materializes every layer's
    full cache slice per token step, which at serving shapes costs more
    HBM traffic than the weights themselves."""
    b = x.shape[0]
    q, k, v = _project_qkv(x, layer, cfg, positions[:, None])
    bidx = jnp.arange(b)
    k_cache = k_cache.at[li, bidx, :, positions, :].set(
        k[:, :, 0, :].astype(k_cache.dtype), mode="drop")
    v_cache = v_cache.at[li, bidx, :, positions, :].set(
        v[:, :, 0, :].astype(v_cache.dtype), mode="drop")
    k_l = jax.lax.dynamic_index_in_dim(k_cache, li, 0, keepdims=False)
    v_l = jax.lax.dynamic_index_in_dim(v_cache, li, 0, keepdims=False)
    o = decode_attention(q[:, :, 0, :], k_l, v_l,
                         lengths=positions + 1,
                         window=cfg.sliding_window,
                         kv_len=kv_len)                   # [B, Hq, Dh]
    o = o.reshape(b, 1, cfg.n_heads * cfg.head_dim)
    return qmatmul(o, layer["wo"]), k_cache, v_cache


def attn_decode_windowed(x: jax.Array, layer: dict, cfg: DecoderConfig,
                         positions0: jax.Array, w: jax.Array,
                         k_pref_l: jax.Array, v_pref_l: jax.Array,
                         k_win_l: jax.Array, v_win_l: jax.Array,
                         kv_len: int | None = None,
                         k_done_l: jax.Array | None = None,
                         v_done_l: jax.Array | None = None):
    """Decode attention for one layer against (read-only prefix cache,
    completed-window buffers, current window buffer, self). Returns
    (out, k_cur, v_cur) — the caller stacks the per-layer k/v columns
    into the window buffer; nothing here writes the big cache, which is
    what keeps it out of the decode scan carry (see
    ``decoder.decode_step_windowed``).

    positions0: [B] DISPATCH-start positions; ``w``: traced step index
    within the current window; ``k_done_l`` [B, Hkv, Wd, Dh] holds the
    dispatch's already-completed windows (absolute position =
    positions0 + Wd + w, used for RoPE and sliding-window masking).
    """
    from copilot_for_consensus_tpu.ops.attention import (
        decode_attention_prefix_window,
    )

    b = x.shape[0]
    n_done = 0 if k_done_l is None else k_done_l.shape[2]
    pos = (positions0 + n_done + w)[:, None]
    q, k, v = _project_qkv(x, layer, cfg, pos)
    k_cur = k[:, :, 0, :]
    v_cur = v[:, :, 0, :]
    o = decode_attention_prefix_window(
        q[:, :, 0, :], k_pref_l, v_pref_l, k_win_l, v_win_l,
        k_cur, v_cur, prefix_lengths=positions0, w=w,
        window=cfg.sliding_window, kv_len=kv_len,
        k_done=k_done_l, v_done=v_done_l)                   # [B, Hq, Dh]
    o = o.reshape(b, 1, cfg.n_heads * cfg.head_dim)
    return qmatmul(o, layer["wo"]), k_cur, v_cur


def attn_decode_windowed_paged(x: jax.Array, layer: dict,
                               cfg: DecoderConfig,
                               positions0: jax.Array, w: jax.Array,
                               partial_fn, k_win_l: jax.Array,
                               v_win_l: jax.Array,
                               k_done_l: jax.Array | None = None,
                               v_done_l: jax.Array | None = None):
    """Kernel-route twin of :func:`attn_decode_windowed`: the big
    prefix piece never materializes — ``partial_fn(qg, lengths,
    q_pos)`` returns its flash (acc, m, l) straight off the paged
    block pool (the Pallas kernel reading blocks by pointer), and the
    dispatch-local pieces (done windows, current window, self) fold in
    through one ``combine_partials`` — the same joint softmax the
    reference computes over its gathered view. Projections, RoPE and
    the output matmul are shared with the reference twin byte for
    byte."""
    from copilot_for_consensus_tpu.ops.attention import (
        combine_partials,
        decode_window_partial,
    )

    b = x.shape[0]
    hkv, dh = cfg.n_kv_heads, cfg.head_dim
    n_done = 0 if k_done_l is None else k_done_l.shape[2]
    q_pos = positions0 + n_done + w
    q, k, v = _project_qkv(x, layer, cfg, q_pos[:, None])
    k_cur = k[:, :, 0, :]
    v_cur = v[:, :, 0, :]
    qg = q[:, :, 0, :].reshape(b, hkv, cfg.n_heads // hkv, dh)
    pool_part = partial_fn(qg, positions0, q_pos)
    local_part = decode_window_partial(
        qg, k_win_l, v_win_l, k_cur, v_cur, positions0, w,
        window=cfg.sliding_window, k_done=k_done_l, v_done=v_done_l)
    o = combine_partials([pool_part, local_part], x.dtype)
    o = o.reshape(b, 1, cfg.n_heads * dh)
    return qmatmul(o, layer["wo"]), k_cur, v_cur


def attn_prefill_seeded_paged(x: jax.Array, layer: dict,
                              cfg: DecoderConfig, partial_fn,
                              prefix_lens: jax.Array,
                              lengths: jax.Array | None = None):
    """Kernel-route twin of :func:`attn_prefill_seeded`: the seeded
    prefix KV is scored in place in the paged block pool —
    ``partial_fn`` runs the Pallas partial kernel over R = G·S query
    rows (rows (g, s) flattened row-major) — and the fresh causal
    suffix joins through ``combine_partials``. Sliding-window models
    are routed away by the engine exactly as on the reference seeded
    path. Returns (out [B,S,D_model], k, v) with fresh SUFFIX k/v in
    [B, Hkv, S, Dh] for the pool scatter at the per-row offset."""
    from copilot_for_consensus_tpu.ops.attention import (
        causal_suffix_partial,
        combine_partials,
    )

    b, s, _ = x.shape
    hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    positions = prefix_lens[:, None] + jnp.arange(s)[None, :]
    q, k, v = _project_qkv(x, layer, cfg, positions)
    q_rows = q.reshape(b, hkv, hq // hkv, s, dh).reshape(
        b, hkv, (hq // hkv) * s, dh)
    pool_part = partial_fn(q_rows, prefix_lens, prefix_lens)
    suffix_part = causal_suffix_partial(q, k, v, kv_lengths=lengths)
    o = combine_partials([pool_part, suffix_part], x.dtype)
    o = o.reshape(b, hq, s, dh).transpose(0, 2, 1, 3).reshape(
        b, s, hq * dh)
    return qmatmul(o, layer["wo"]), k, v


# ---------------------------------------------------------------------------
# Feed-forward
# ---------------------------------------------------------------------------


def swiglu(x: jax.Array, layer: dict) -> jax.Array:
    """SwiGLU MLP: silu(x·Wg) ⊙ (x·Wu) · Wd — Llama/Mistral family FFN."""
    if "w_gu" in layer:
        # Fused int4 gate+up (quant.fuse_int4_projections): one kernel
        # call, split by column.
        gu = qmatmul(x, layer["w_gu"]).astype(jnp.float32)
        f = gu.shape[-1] // 2
        gate, up = jax.nn.silu(gu[..., :f]), gu[..., f:]
    else:
        gate = jax.nn.silu(qmatmul(x, layer["w_gate"]).astype(jnp.float32))
        up = qmatmul(x, layer["w_up"]).astype(jnp.float32)
    return qmatmul((gate * up).astype(x.dtype), layer["w_down"])


def gelu_mlp(x: jax.Array, layer: dict) -> jax.Array:
    """BERT-style 2-layer GELU MLP (encoder FFN). Exact (erf) GELU —
    the BERT family's ``hidden_act="gelu"``; tanh-approximate would
    break checkpoint parity."""
    h = jax.nn.gelu((x @ layer["w_in"] + layer["b_in"]).astype(jnp.float32),
                    approximate=False)
    return h.astype(x.dtype) @ layer["w_out"] + layer["b_out"]
