"""Decoder-only LLM (Mistral / Llama-3 / Mixtral class).

Pre-norm transformer with RoPE, GQA, SwiGLU (or MoE) FFN, RMSNorm.
Layers are stacked on a leading axis and driven by ``lax.scan``:
compile time is O(1) in depth and every weight is one pjit-shardable
tensor. Three entry points:

* ``forward``      — [B, S] → logits [B, S, V] (scoring / training)
* ``prefill``      — builds the KV cache, returns last-position logits
* ``decode_step``  — one token per active slot against the cache

This model fills the generative-engine role the reference delegates to
Ollama / llama.cpp (``adapters/copilot_summarization/.../factory.py:89-94``,
``local_llm_summarizer.py:106-115``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from copilot_for_consensus_tpu.models.configs import DecoderConfig
from copilot_for_consensus_tpu.models import layers as L
from copilot_for_consensus_tpu.models.moe import moe_ffn

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Init + sharding metadata
# ---------------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: DecoderConfig,
                dtype=jnp.bfloat16) -> Params:
    """Truncated-normal init, scaled 1/sqrt(fan_in) for projections."""
    n, d, dh = cfg.n_layers, cfg.d_model, cfg.head_dim
    hq, hkv, f, v = cfg.n_heads, cfg.n_kv_heads, cfg.d_ff, cfg.vocab_size
    keys = iter(jax.random.split(rng, 16))

    def dense(key, shape, fan_in):
        return (jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
                * fan_in ** -0.5).astype(dtype)

    layer: Params = {
        "attn_norm": jnp.ones((n, d), dtype),
        "wq": dense(next(keys), (n, d, hq * dh), d),
        "wk": dense(next(keys), (n, d, hkv * dh), d),
        "wv": dense(next(keys), (n, d, hkv * dh), d),
        "wo": dense(next(keys), (n, hq * dh, d), hq * dh),
        "ffn_norm": jnp.ones((n, d), dtype),
    }
    if cfg.is_moe:
        e = cfg.n_experts
        layer.update({
            "router": dense(next(keys), (n, d, e), d),
            "w_gate": dense(next(keys), (n, e, d, f), d),
            "w_up": dense(next(keys), (n, e, d, f), d),
            "w_down": dense(next(keys), (n, e, f, d), f),
        })
    else:
        layer.update({
            "w_gate": dense(next(keys), (n, d, f), d),
            "w_up": dense(next(keys), (n, d, f), d),
            "w_down": dense(next(keys), (n, f, d), f),
        })
    params: Params = {
        "tok_emb": dense(next(keys), (v, d), d),
        "layers": layer,
        "final_norm": jnp.ones((d,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(keys), (d, v), d)
    return params


def logical_axes(cfg: DecoderConfig) -> Params:
    """Same structure as params; leaves are logical-axis tuples."""
    layer = {
        "attn_norm": (None, "norm"),
        "wq": (None, "embed", "heads"),
        "wk": (None, "embed", "kv_heads"),
        "wv": (None, "embed", "kv_heads"),
        "wo": (None, "heads", "embed"),
        "ffn_norm": (None, "norm"),
    }
    if cfg.is_moe:
        layer.update({
            "router": (None, "embed", None),
            "w_gate": (None, "experts", "embed", "expert_ffn"),
            "w_up": (None, "experts", "embed", "expert_ffn"),
            "w_down": (None, "experts", "expert_ffn", "embed"),
        })
    else:
        layer.update({
            "w_gate": (None, "embed", "ffn"),
            "w_up": (None, "embed", "ffn"),
            "w_down": (None, "ffn", "embed"),
        })
    axes: Params = {
        "tok_emb": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("norm",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def param_count(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------


def _ffn(x: jax.Array, layer: Params, cfg: DecoderConfig) -> jax.Array:
    return moe_ffn(x, layer, cfg) if cfg.is_moe else L.swiglu(x, layer)


def _unembed(x: jax.Array, params: Params, cfg: DecoderConfig) -> jax.Array:
    x = L.rms_norm(x, params["final_norm"], cfg.norm_eps)
    if cfg.tie_embeddings:
        return (x @ params["tok_emb"].T).astype(jnp.float32)
    return L.qmatmul(x, params["lm_head"]).astype(jnp.float32)


def block(x: jax.Array, layer: Params, cfg: DecoderConfig,
          lengths: jax.Array | None = None,
          attn_impl: str = "auto", reduce=None) -> jax.Array:
    """One transformer block: [B, S, D] → [B, S, D]. The single source of
    the block body — forward and the pp pipeline both run this, so model
    changes cannot drift between them. ``reduce`` (default identity)
    completes partial products when the layer's head/ffn width is
    tensor-parallel sharded — the pp×tp path passes a psum."""
    if reduce is None:
        reduce = lambda t: t  # noqa: E731
    h, _, _ = L.attn_prefill(
        L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
        layer, cfg, lengths=lengths, impl=attn_impl)
    x = x + reduce(h)
    return x + reduce(_ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                           layer, cfg))


def forward(params: Params, tokens: jax.Array, cfg: DecoderConfig,
            lengths: jax.Array | None = None,
            attn_impl: str = "auto") -> jax.Array:
    """Scoring/training pass: [B, S] int tokens → [B, S, V] fp32 logits."""
    x = params["tok_emb"][tokens]

    def body(x, layer):
        return block(x, layer, cfg, lengths, attn_impl), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return _unembed(x, params, cfg)


def init_cache(cfg: DecoderConfig, batch: int, max_len: int,
               dtype=jnp.bfloat16) -> Params:
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes() -> Params:
    return {"k": (None, "batch", "kv_heads", None, None),
            "v": (None, "batch", "kv_heads", None, None)}


def prefill(params: Params, tokens: jax.Array, lengths: jax.Array,
            cfg: DecoderConfig, cache: Params,
            attn_impl: str = "auto") -> tuple[jax.Array, Params]:
    """Prompt pass. tokens: [B, S] right-padded; lengths: [B]. Writes kv for
    positions [0, S) into the cache and returns (last-valid-position logits
    [B, V] fp32, cache)."""
    b, s = tokens.shape
    x = params["tok_emb"][tokens]

    def body(x, scanned):
        layer, k_cache, v_cache = scanned
        h, k, v = L.attn_prefill(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, lengths=lengths, impl=attn_impl)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        k_cache = jax.lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), 0, axis=2)
        v_cache = jax.lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), 0, axis=2)
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    # Select each row's last valid hidden state BEFORE the lm_head:
    # unembedding all S positions materializes [B, S, V] fp32 logits
    # (1 GB at 128×128×32k — the admission-path OOM driver) and burns
    # S× the lm_head FLOPs for rows where only the last token samples.
    x_last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)
    return _unembed(x_last, params, cfg)[:, 0], {"k": k_new, "v": v_new}


def decode_step(params: Params, tokens: jax.Array, positions: jax.Array,
                cfg: DecoderConfig, cache: Params,
                kv_len: int | None = None
                ) -> tuple[jax.Array, Params]:
    """One decode step. tokens: [B] int — the tokens to feed; positions:
    [B] — the cache index each token occupies; ``kv_len`` (static) bounds
    the cache prefix attention reads. Returns ([B, V] fp32 logits,
    updated cache)."""
    x = params["tok_emb"][tokens][:, None, :]               # [B, 1, D]

    # The stacked cache rides the scan CARRY with per-column scatter
    # writes (attn_decode_stacked): as scan xs/ys it would be fully
    # re-materialized (read + write) every token step — more HBM traffic
    # than the weights at serving shapes.
    def body(carry, scanned):
        x, k_cache, v_cache = carry
        layer, li = scanned
        h, k_cache, v_cache = L.attn_decode_stacked(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, positions, k_cache, v_cache, li, kv_len=kv_len)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        return (x, k_cache, v_cache), None

    (x, k_new, v_new), _ = jax.lax.scan(
        body, (x, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(cfg.n_layers)))
    return _unembed(x, params, cfg)[:, 0], {"k": k_new, "v": v_new}


def decode_step_windowed(params: Params, tokens: jax.Array,
                         positions0: jax.Array, w: jax.Array,
                         cfg: DecoderConfig, cache: Params,
                         k_win: jax.Array, v_win: jax.Array,
                         kv_len: int | None = None,
                         k_done: jax.Array | None = None,
                         v_done: jax.Array | None = None
                         ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step that never writes the big cache.

    The stacked cache in a decode-window scan carry is re-materialized
    (read + copied) once per token step — measured at ~2× the cache
    bytes, which dominated the step once weights went int8. Here the
    cache is a read-only loop invariant; fresh KV goes into the small
    per-window buffers ``k_win``/``v_win`` [L, B, Hkv, W, Dh] carried by
    the engine's window scan, and is merged into the cache ONCE per
    DISPATCH. A multi-window dispatch passes the completed windows as
    ``k_done``/``v_done`` [L, B, Hkv, Wd, Dh] (a fourth attention
    piece) instead of merging them — merging per window made the big
    cache a loop variable again and ping-ponged a second full cache
    allocation (the r2 OOM at kv extents > 256).

    tokens: [B]; positions0: [B] dispatch-start positions; ``w``: traced
    in-window step index. Returns ([B, V] fp32 logits, k_cols, v_cols)
    where k_cols/v_cols [L, B, Hkv, Dh] are this step's new KV columns
    for the caller to slot into the window buffers at index ``w``.
    """
    x = params["tok_emb"][tokens][:, None, :]               # [B, 1, D]
    # Static prefix slice BEFORE the layer scan, streamed per layer as
    # scan xs (read-only, never in ys): attention reads exactly the
    # occupied [0, kv_len) columns per layer and nothing writes back.
    # A dynamic per-layer index into the full-extent cache instead
    # materializes max_len-proportional layer copies (measured: going
    # max_len 256→512 with identical kv_len cost ~12 ms/step).
    k_pref, v_pref = cache["k"], cache["v"]
    if kv_len is not None and kv_len < k_pref.shape[3]:
        k_pref = k_pref[:, :, :, :kv_len]
        v_pref = v_pref[:, :, :, :kv_len]
    have_done = k_done is not None
    xs = (params["layers"], jnp.arange(cfg.n_layers), k_pref, v_pref)
    if have_done:
        xs = xs + (k_done, v_done)

    def body(x, scanned):
        layer, li, k_pref_l, v_pref_l = scanned[:4]
        k_done_l = scanned[4] if have_done else None
        v_done_l = scanned[5] if have_done else None
        # Window buffers are [L, B, H, W, D] (attention-native layout;
        # merge_window transposes once per window, not per layer/step).
        k_win_l = jax.lax.dynamic_index_in_dim(k_win, li, 0,
                                               keepdims=False)
        v_win_l = jax.lax.dynamic_index_in_dim(v_win, li, 0,
                                               keepdims=False)
        h, k_cur, v_cur = L.attn_decode_windowed(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, positions0, w, k_pref_l, v_pref_l,
            k_win_l, v_win_l, kv_len=None,
            k_done_l=k_done_l, v_done_l=v_done_l)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        return x, (k_cur, v_cur)

    x, (k_cols, v_cols) = jax.lax.scan(body, x, xs)
    return _unembed(x, params, cfg)[:, 0], k_cols, v_cols


def merge_window(cache: Params, k_win: jax.Array, v_win: jax.Array,
                 positions0: jax.Array, steps: int) -> Params:
    """Scatter a decode window's KV into the big cache, once.

    k_win/v_win: [L, B, Hkv, W, Dh]; slot b's window columns land at
    cache positions ``positions0[b] + [0, steps)``. Out-of-range columns
    drop (same semantics as the per-step scatter this replaces). One
    transpose per window puts W in front of the head axis to match the
    advanced-indexing update shape [B, W, L, H, D].
    """
    b = k_win.shape[1]
    w = k_win.shape[3]
    bidx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, w))
    pidx = positions0[:, None] + jnp.arange(w)[None, :]
    if steps < w:
        k_win = k_win[:, :, :, :steps]
        v_win = v_win[:, :, :, :steps]
        bidx, pidx = bidx[:, :steps], pidx[:, :steps]
    k_upd = k_win.transpose(1, 3, 0, 2, 4)     # [B, W, L, H, D]
    v_upd = v_win.transpose(1, 3, 0, 2, 4)
    # cache axes [L, B, H, S, D]; advanced indices on axes 1 and 3 put
    # the [B, W] index shape in front: update shape [B, W, L, H, D].
    k = cache["k"].at[:, bidx, :, pidx, :].set(
        k_upd.astype(cache["k"].dtype), mode="drop")
    v = cache["v"].at[:, bidx, :, pidx, :].set(
        v_upd.astype(cache["v"].dtype), mode="drop")
    return {"k": k, "v": v}
