"""Decoder-only LLM (Mistral / Llama-3 / Mixtral class).

Pre-norm transformer with RoPE, GQA, SwiGLU (or MoE) FFN, RMSNorm.
Layers are stacked on a leading axis and driven by ``lax.scan``:
compile time is O(1) in depth and every weight is one pjit-shardable
tensor. Three entry points:

* ``forward``      — [B, S] → logits [B, S, V] (scoring / training)
* ``prefill``      — builds the KV cache, returns last-position logits
* ``decode_step``  — one token per active slot against the cache

This model fills the generative-engine role the reference delegates to
Ollama / llama.cpp (``adapters/copilot_summarization/.../factory.py:89-94``,
``local_llm_summarizer.py:106-115``).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from copilot_for_consensus_tpu.models.configs import DecoderConfig
from copilot_for_consensus_tpu.models import layers as L
from copilot_for_consensus_tpu.models.moe import moe_ffn

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Init + sharding metadata
# ---------------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: DecoderConfig,
                dtype=jnp.bfloat16) -> Params:
    """Truncated-normal init, scaled 1/sqrt(fan_in) for projections."""
    n, d, dh = cfg.n_layers, cfg.d_model, cfg.head_dim
    hq, hkv, f, v = cfg.n_heads, cfg.n_kv_heads, cfg.d_ff, cfg.vocab_size
    keys = iter(jax.random.split(rng, 16))

    def dense(key, shape, fan_in):
        return (jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
                * fan_in ** -0.5).astype(dtype)

    layer: Params = {
        "attn_norm": jnp.ones((n, d), dtype),
        "wq": dense(next(keys), (n, d, hq * dh), d),
        "wk": dense(next(keys), (n, d, hkv * dh), d),
        "wv": dense(next(keys), (n, d, hkv * dh), d),
        "wo": dense(next(keys), (n, hq * dh, d), hq * dh),
        "ffn_norm": jnp.ones((n, d), dtype),
    }
    if cfg.is_moe:
        e = cfg.n_experts
        layer.update({
            "router": dense(next(keys), (n, d, e), d),
            "w_gate": dense(next(keys), (n, e, d, f), d),
            "w_up": dense(next(keys), (n, e, d, f), d),
            "w_down": dense(next(keys), (n, e, f, d), f),
        })
    else:
        layer.update({
            "w_gate": dense(next(keys), (n, d, f), d),
            "w_up": dense(next(keys), (n, d, f), d),
            "w_down": dense(next(keys), (n, f, d), f),
        })
    params: Params = {
        "tok_emb": dense(next(keys), (v, d), d),
        "layers": layer,
        "final_norm": jnp.ones((d,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(keys), (d, v), d)
    return params


def logical_axes(cfg: DecoderConfig) -> Params:
    """Same structure as params; leaves are logical-axis tuples."""
    layer = {
        "attn_norm": (None, "norm"),
        "wq": (None, "embed", "heads"),
        "wk": (None, "embed", "kv_heads"),
        "wv": (None, "embed", "kv_heads"),
        "wo": (None, "heads", "embed"),
        "ffn_norm": (None, "norm"),
    }
    if cfg.is_moe:
        layer.update({
            "router": (None, "embed", None),
            "w_gate": (None, "experts", "embed", "expert_ffn"),
            "w_up": (None, "experts", "embed", "expert_ffn"),
            "w_down": (None, "experts", "expert_ffn", "embed"),
        })
    else:
        layer.update({
            "w_gate": (None, "embed", "ffn"),
            "w_up": (None, "embed", "ffn"),
            "w_down": (None, "ffn", "embed"),
        })
    axes: Params = {
        "tok_emb": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("norm",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def param_count(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------


def _ffn(x: jax.Array, layer: Params, cfg: DecoderConfig) -> jax.Array:
    return moe_ffn(x, layer, cfg) if cfg.is_moe else L.swiglu(x, layer)


def _unembed(x: jax.Array, params: Params, cfg: DecoderConfig) -> jax.Array:
    x = L.rms_norm(x, params["final_norm"], cfg.norm_eps)
    if cfg.tie_embeddings:
        return (x @ params["tok_emb"].T).astype(jnp.float32)
    return L.qmatmul(x, params["lm_head"]).astype(jnp.float32)


def block(x: jax.Array, layer: Params, cfg: DecoderConfig,
          lengths: jax.Array | None = None,
          attn_impl: str = "auto", reduce=None) -> jax.Array:
    """One transformer block: [B, S, D] → [B, S, D]. The single source of
    the block body — forward and the pp pipeline both run this, so model
    changes cannot drift between them. ``reduce`` (default identity)
    completes partial products when the layer's head/ffn width is
    tensor-parallel sharded — the pp×tp path passes a psum."""
    if reduce is None:
        reduce = lambda t: t  # noqa: E731
    h, _, _ = L.attn_prefill(
        L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
        layer, cfg, lengths=lengths, impl=attn_impl)
    x = x + reduce(h)
    return x + reduce(_ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                           layer, cfg))


def forward(params: Params, tokens: jax.Array, cfg: DecoderConfig,
            lengths: jax.Array | None = None,
            attn_impl: str = "auto") -> jax.Array:
    """Scoring/training pass: [B, S] int tokens → [B, S, V] fp32 logits."""
    x = params["tok_emb"][tokens]

    def body(x, layer):
        return block(x, layer, cfg, lengths, attn_impl), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return _unembed(x, params, cfg)


def init_cache(cfg: DecoderConfig, batch: int, max_len: int,
               dtype=jnp.bfloat16) -> Params:
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes() -> Params:
    return {"k": (None, "batch", "kv_heads", None, None),
            "v": (None, "batch", "kv_heads", None, None)}


def prefill(params: Params, tokens: jax.Array, lengths: jax.Array,
            cfg: DecoderConfig, cache: Params,
            attn_impl: str = "auto") -> tuple[jax.Array, Params]:
    """Prompt pass. tokens: [B, S] right-padded; lengths: [B]. Writes kv for
    positions [0, S) into the cache and returns (last-valid-position logits
    [B, V] fp32, cache)."""
    b, s = tokens.shape
    x = params["tok_emb"][tokens]

    def body(x, scanned):
        layer, k_cache, v_cache = scanned
        h, k, v = L.attn_prefill(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, lengths=lengths, impl=attn_impl)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        k_cache = jax.lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), 0, axis=2)
        v_cache = jax.lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), 0, axis=2)
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    # Select each row's last valid hidden state BEFORE the lm_head:
    # unembedding all S positions materializes [B, S, V] fp32 logits
    # (1 GB at 128×128×32k — the admission-path OOM driver) and burns
    # S× the lm_head FLOPs for rows where only the last token samples.
    x_last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)
    return _unembed(x_last, params, cfg)[:, 0], {"k": k_new, "v": v_new}


def prefill_seeded(params: Params, tokens: jax.Array, lengths: jax.Array,
                   k_pref: jax.Array, v_pref: jax.Array,
                   prefix_lens: jax.Array, cfg: DecoderConfig,
                   cache: Params) -> tuple[jax.Array, Params]:
    """Suffix prompt pass against seeded prefix KV (prefix cache hits).

    tokens: [B, S] right-padded SUFFIX tokens — row b's token i sits at
    absolute position ``prefix_lens[b] + i``; lengths: [B] suffix
    lengths (>= 1: the first generated token samples from the last
    suffix position). k_pref/v_pref: [L, B, Hkv, P, Dh] reused prefix
    KV gathered from the block pool (zero-padded past prefix_lens —
    masked in attention). Writes SUFFIX kv into scratch positions
    [0, S) (the engine scatters them into the slot cache at the
    per-row offset) and returns (last-valid-position logits [B, V]
    fp32, scratch). Rows with prefix_lens 0 compute exactly what
    ``prefill`` computes — mixed hit/miss admission waves run as one
    program."""
    x = params["tok_emb"][tokens]

    def body(x, scanned):
        layer, k_pref_l, v_pref_l, k_cache, v_cache = scanned
        h, k, v = L.attn_prefill_seeded(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, k_pref_l, v_pref_l, prefix_lens,
            lengths=lengths)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        k_cache = jax.lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), 0, axis=2)
        v_cache = jax.lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), 0, axis=2)
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], k_pref, v_pref,
                  cache["k"], cache["v"]))
    x_last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)
    return _unembed(x_last, params, cfg)[:, 0], {"k": k_new, "v": v_new}


def verify_seeded(params: Params, tokens: jax.Array, lengths: jax.Array,
                  prefix_lens: jax.Array, cfg: DecoderConfig,
                  cache: Params, kv_len: int | None = None
                  ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Multi-token verification pass for speculative decoding.

    A short seeded prefill over DECODE SLOTS: row b's S = k+1 tokens
    (the committed next token plus its k drafted continuations) sit at
    absolute positions ``prefix_lens[b] + i`` and attend (slot cache
    prefix ++ fresh causal suffix) through the same
    ``attn_prefill_seeded`` machinery the prefix-cache admission wave
    uses — one weight pass scores all k+1 positions of every slot,
    which is the entire point (decode is pinned at the HBM weight-read
    wall; see docs/SPEC_DECODE.md).

    Differences from :func:`prefill_seeded`: the seeded prefix is the
    engine's own slot cache ``[L, B, Hkv, S_max, Dh]`` read in place
    (sliced to the static ``kv_len`` bucket, streamed per layer as
    read-only scan xs — never in the carry, the same discipline as
    ``decode_step_windowed``), and logits come back for EVERY position
    (acceptance needs all k+1 distributions, not just the last).
    Positions at or past ``prefix_lens[b]`` are masked, so KV left over
    from a previous dispatch's rejected drafts is dead by construction.

    tokens: [B, S] right-padded; lengths: [B] valid tokens per row
    (>= 1); prefix_lens: [B] committed cache prefix per slot (free
    slots park out of range and produce garbage that the engine's
    scatter drops). Returns (logits [B, S, V] fp32, k_new, v_new
    [L, B, Hkv, S, Dh] — ``merge_window`` layout, for the engine's
    single end-of-dispatch scatter at the per-row offset)."""
    x = params["tok_emb"][tokens]
    k_pref, v_pref = cache["k"], cache["v"]
    if kv_len is not None and kv_len < k_pref.shape[3]:
        k_pref = k_pref[:, :, :, :kv_len]
        v_pref = v_pref[:, :, :, :kv_len]

    def body(x, scanned):
        layer, k_pref_l, v_pref_l = scanned
        h, k, v = L.attn_prefill_seeded(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, k_pref_l, v_pref_l, prefix_lens,
            lengths=lengths)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        return x, (k, v)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], k_pref, v_pref))
    return _unembed(x, params, cfg), k_new, v_new


def decode_step(params: Params, tokens: jax.Array, positions: jax.Array,
                cfg: DecoderConfig, cache: Params,
                kv_len: int | None = None
                ) -> tuple[jax.Array, Params]:
    """One decode step. tokens: [B] int — the tokens to feed; positions:
    [B] — the cache index each token occupies; ``kv_len`` (static) bounds
    the cache prefix attention reads. Returns ([B, V] fp32 logits,
    updated cache)."""
    x = params["tok_emb"][tokens][:, None, :]               # [B, 1, D]

    # The stacked cache rides the scan CARRY with per-column scatter
    # writes (attn_decode_stacked): as scan xs/ys it would be fully
    # re-materialized (read + write) every token step — more HBM traffic
    # than the weights at serving shapes.
    def body(carry, scanned):
        x, k_cache, v_cache = carry
        layer, li = scanned
        h, k_cache, v_cache = L.attn_decode_stacked(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, positions, k_cache, v_cache, li, kv_len=kv_len)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        return (x, k_cache, v_cache), None

    (x, k_new, v_new), _ = jax.lax.scan(
        body, (x, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(cfg.n_layers)))
    return _unembed(x, params, cfg)[:, 0], {"k": k_new, "v": v_new}


def decode_step_windowed(params: Params, tokens: jax.Array,
                         positions0: jax.Array, w: jax.Array,
                         cfg: DecoderConfig, cache: Params,
                         k_win: jax.Array, v_win: jax.Array,
                         kv_len: int | None = None,
                         k_done: jax.Array | None = None,
                         v_done: jax.Array | None = None
                         ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step that never writes the big cache.

    The stacked cache in a decode-window scan carry is re-materialized
    (read + copied) once per token step — measured at ~2× the cache
    bytes, which dominated the step once weights went int8. Here the
    cache is a read-only loop invariant; fresh KV goes into the small
    per-window buffers ``k_win``/``v_win`` [L, B, Hkv, W, Dh] carried by
    the engine's window scan, and is merged into the cache ONCE per
    DISPATCH. A multi-window dispatch passes the completed windows as
    ``k_done``/``v_done`` [L, B, Hkv, Wd, Dh] (a fourth attention
    piece) instead of merging them — merging per window made the big
    cache a loop variable again and ping-ponged a second full cache
    allocation (the r2 OOM at kv extents > 256).

    tokens: [B]; positions0: [B] dispatch-start positions; ``w``: traced
    in-window step index. Returns ([B, V] fp32 logits, k_cols, v_cols)
    where k_cols/v_cols [L, B, Hkv, Dh] are this step's new KV columns
    for the caller to slot into the window buffers at index ``w``.
    """
    x = params["tok_emb"][tokens][:, None, :]               # [B, 1, D]
    # Static prefix slice BEFORE the layer scan, streamed per layer as
    # scan xs (read-only, never in ys): attention reads exactly the
    # occupied [0, kv_len) columns per layer and nothing writes back.
    # A dynamic per-layer index into the full-extent cache instead
    # materializes max_len-proportional layer copies (measured: going
    # max_len 256→512 with identical kv_len cost ~12 ms/step).
    k_pref, v_pref = cache["k"], cache["v"]
    if kv_len is not None and kv_len < k_pref.shape[3]:
        k_pref = k_pref[:, :, :, :kv_len]
        v_pref = v_pref[:, :, :, :kv_len]
    have_done = k_done is not None
    xs = (params["layers"], jnp.arange(cfg.n_layers), k_pref, v_pref)
    if have_done:
        xs = xs + (k_done, v_done)

    def body(x, scanned):
        layer, li, k_pref_l, v_pref_l = scanned[:4]
        k_done_l = scanned[4] if have_done else None
        v_done_l = scanned[5] if have_done else None
        # Window buffers are [L, B, H, W, D] (attention-native layout;
        # merge_window transposes once per window, not per layer/step).
        k_win_l = jax.lax.dynamic_index_in_dim(k_win, li, 0,
                                               keepdims=False)
        v_win_l = jax.lax.dynamic_index_in_dim(v_win, li, 0,
                                               keepdims=False)
        h, k_cur, v_cur = L.attn_decode_windowed(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, positions0, w, k_pref_l, v_pref_l,
            k_win_l, v_win_l, kv_len=None,
            k_done_l=k_done_l, v_done_l=v_done_l)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        return x, (k_cur, v_cur)

    x, (k_cols, v_cols) = jax.lax.scan(body, x, xs)
    return _unembed(x, params, cfg)[:, 0], k_cols, v_cols


def decode_step_windowed_paged(params: Params, tokens: jax.Array,
                               positions0: jax.Array, w: jax.Array,
                               cfg: DecoderConfig, partial_fn,
                               k_win: jax.Array, v_win: jax.Array,
                               k_done: jax.Array | None = None,
                               v_done: jax.Array | None = None
                               ) -> tuple[jax.Array, jax.Array,
                                          jax.Array]:
    """Kernel-route twin of :func:`decode_step_windowed`: the big
    cache piece never appears as an array at all. ``partial_fn(li,
    qg, lengths, q_pos)`` scores the slot's committed pool blocks in
    place (the Pallas paged kernel, layer selected by the traced
    ``li`` on the scalar-prefetch lane — no per-layer pool slice
    materializes either), and the fresh KV discipline is identical:
    window buffers in the engine's scan carry, completed windows as a
    ``k_done`` piece, one pool scatter per dispatch by the caller.

    tokens: [B]; positions0: [B] dispatch-start positions; ``w``:
    traced in-window step index. Returns ([B, V] fp32 logits, k_cols,
    v_cols [L, B, Hkv, Dh]) exactly like the reference twin."""
    x = params["tok_emb"][tokens][:, None, :]               # [B, 1, D]
    have_done = k_done is not None
    xs = (params["layers"], jnp.arange(cfg.n_layers))
    if have_done:
        xs = xs + (k_done, v_done)

    def body(x, scanned):
        layer, li = scanned[:2]
        k_done_l = scanned[2] if have_done else None
        v_done_l = scanned[3] if have_done else None
        k_win_l = jax.lax.dynamic_index_in_dim(k_win, li, 0,
                                               keepdims=False)
        v_win_l = jax.lax.dynamic_index_in_dim(v_win, li, 0,
                                               keepdims=False)
        h, k_cur, v_cur = L.attn_decode_windowed_paged(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, positions0, w,
            functools.partial(partial_fn, li), k_win_l, v_win_l,
            k_done_l=k_done_l, v_done_l=v_done_l)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        return x, (k_cur, v_cur)

    x, (k_cols, v_cols) = jax.lax.scan(body, x, xs)
    return _unembed(x, params, cfg)[:, 0], k_cols, v_cols


def prefill_seeded_paged(params: Params, tokens: jax.Array,
                         lengths: jax.Array, prefix_lens: jax.Array,
                         cfg: DecoderConfig, partial_fn, *,
                         all_logits: bool
                         ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Kernel-route seeded suffix pass: one program standing in for
    :func:`prefill_seeded` (``all_logits=False`` — admission) and
    :func:`verify_seeded` (``all_logits=True`` — spec-decode verify
    and chunked prefill), with the seeded prefix scored straight off
    the paged block pool by ``partial_fn(li, q_rows, lengths,
    q_pos)`` instead of a gathered ``k_pref`` view. Masking semantics
    are the reference twins' exactly: prefix columns at or past
    ``prefix_lens[b]`` are structurally unreadable, suffix attention
    is causal below ``lengths[b]``.

    tokens: [B, S] right-padded suffix tokens at absolute positions
    ``prefix_lens[b] + i``. Returns (logits, k_new, v_new
    [L, B, Hkv, S, Dh] in compute dtype — ``merge_window`` layout for
    the engine's single pool scatter): logits are [B, S, V] fp32 when
    ``all_logits`` else the last-valid-position [B, V] (selected
    BEFORE the lm_head — the same admission OOM guard as
    ``prefill``)."""
    x = params["tok_emb"][tokens]

    def body(x, scanned):
        layer, li = scanned
        h, k, v = L.attn_prefill_seeded_paged(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, functools.partial(partial_fn, li),
            prefix_lens, lengths=lengths)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        return x, (k, v)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], jnp.arange(cfg.n_layers)))
    if all_logits:
        return _unembed(x, params, cfg), k_new, v_new
    x_last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)
    return _unembed(x_last, params, cfg)[:, 0], k_new, v_new


def decode_step_piggyback(params: Params, tokens: jax.Array,
                          positions0: jax.Array, w: jax.Array,
                          cfg: DecoderConfig, cache: Params,
                          k_win: jax.Array, v_win: jax.Array,
                          pre_tok: jax.Array, pre_rope_base: jax.Array,
                          pre_kv_begin: jax.Array,
                          pre_kv_len: jax.Array,
                          pre_sel_rel: jax.Array,
                          pre_kbuf: jax.Array, pre_vbuf: jax.Array,
                          kv_len: int | None = None):
    """One decode step that ALSO advances P prefill lanes by a C-token
    chunk — chunked-prefill piggybacking with lane packing.

    Decode at serving widths is weight-bandwidth-bound: every step
    streams the full weights to advance `slots` rows while the MXU sits
    mostly idle. A monolithic admission wave is the opposite — pure MXU
    work that stalls decode for seconds at RAG prompt lengths (the r3
    verdict's 2k-token 2.1x finding). Here each decode step's
    projections/FFN matmuls take the decode rows AND ``P*C`` prompt
    tokens as ONE row-concatenated matmul, so the prefill FLOPs ride
    the weight stream decode was already paying for (measured: one
    piggybacked dispatch carries 8192 prompt tokens for +0.18 s over a
    plain decode dispatch, vs 0.77 s as a standalone wave). The
    replaced role: the reference's blocking prompt pass inside
    ``local_llm_summarizer.py:106-115``.

    The engine PACKS whole prompts into the ``W x P`` chunk grid
    host-side (``GenerationEngine._pack_prefill``): lane p's dispatch
    buffer holds consecutive rows' chunks back to back, so one
    dispatch can admit many short prompts per lane as well as one
    2048-token prompt. All per-step per-lane metadata arrives as
    arrays; nothing about the packing is traced:

    * pre_tok [P, C]       — this step's chunk token ids per lane;
    * pre_rope_base [P]    — chunk-start position WITHIN its row (RoPE);
    * pre_kv_begin [P]     — buffer column where the row's kv starts
      (earlier columns belong to other rows — masked in-kernel);
    * pre_kv_len [P]       — valid buffer columns through this step
      (masks the final partial chunk and idle lanes, which carry 0);
    * pre_sel_rel [P]      — in-chunk index of the row's LAST prompt
      token when this chunk completes the row (arbitrary otherwise);
      the returned ``h_step`` is the hidden state at that index, from
      which the engine samples the row's first generated token;
    * pre_kbuf/pre_vbuf [L, P, Hkv, BUF, Dh] — the dispatch's chunk
      buffers (carried by the engine scan like the decode window
      buffers; scattered into the cache once per dispatch by
      ``merge_prefill`` under host-built slot/position maps).

    The chunk's attention is ONE flash call per layer over the buffer
    (chunk kv written in first) with a dynamic query offset of ``w*C``
    and the begin/length bounds above — a naive piecewise attention
    materializes a [P, Hq, C, BUF] fp32 score tensor per layer per
    step (~76 ms/step at rag2k shapes, measured), which is the exact
    failure mode flash tiling exists to avoid.

    Returns (logits [B, V], k_cols, v_cols, pre_k [L, P, Hkv, C, Dh],
    pre_v, h_step [P, D]).
    """
    assert not cfg.is_moe, "piggyback prefill: dense FFN only"
    from copilot_for_consensus_tpu.ops.attention import (
        decode_attention_prefix_window,
    )
    from copilot_for_consensus_tpu.ops.flash_attention import (
        flash_attention,
    )

    b = tokens.shape[0]
    p, c = pre_tok.shape
    dh, hq, hkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    x_dec = params["tok_emb"][tokens]                      # [B, D]
    x_pre = params["tok_emb"][pre_tok]                     # [P, C, D]
    d_model = x_dec.shape[-1]
    x = jnp.concatenate([x_dec, x_pre.reshape(p * c, d_model)], axis=0)

    pos_dec = (positions0 + w)[:, None]                    # [B, 1]
    pos_pre = pre_rope_base[:, None] + jnp.arange(c)[None, :]  # [P, C]

    k_pref, v_pref = cache["k"], cache["v"]
    if kv_len is not None and kv_len < k_pref.shape[3]:
        k_pref = k_pref[:, :, :, :kv_len]
        v_pref = v_pref[:, :, :, :kv_len]
    inv_freq = L.rope_frequencies(dh, cfg.rope_theta)
    xs = (params["layers"], jnp.arange(cfg.n_layers), k_pref, v_pref)

    def body(x, scanned):
        layer, li, k_pref_l, v_pref_l = scanned
        xa = L.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        # ONE projection matmul over decode+prefill rows: the weight
        # stream is shared — this is the piggyback.
        if "wqkv" in layer:
            nq, nkv = hq * dh, hkv * dh
            qkv = L.qmatmul(xa, layer["wqkv"])
            q_all, k_all, v_all = (qkv[..., :nq], qkv[..., nq:nq + nkv],
                                   qkv[..., nq + nkv:])
        else:
            q_all = L.qmatmul(xa, layer["wq"])
            k_all = L.qmatmul(xa, layer["wk"])
            v_all = L.qmatmul(xa, layer["wv"])

        def split_heads(z, n_heads):
            zd = z[:b].reshape(b, 1, n_heads, dh).transpose(0, 2, 1, 3)
            zp = z[b:].reshape(p, c, n_heads, dh).transpose(0, 2, 1, 3)
            return zd, zp

        qd, qp = split_heads(q_all, hq)
        kd, kp = split_heads(k_all, hkv)
        vd, vp = split_heads(v_all, hkv)
        qd = L.apply_rope(qd, pos_dec, inv_freq)
        kd = L.apply_rope(kd, pos_dec, inv_freq)
        qp = L.apply_rope(qp, pos_pre, inv_freq)
        kp = L.apply_rope(kp, pos_pre, inv_freq)

        # decode population: prefix + current-window pieces
        k_win_l = jax.lax.dynamic_index_in_dim(k_win, li, 0,
                                               keepdims=False)
        v_win_l = jax.lax.dynamic_index_in_dim(v_win, li, 0,
                                               keepdims=False)
        o_dec = decode_attention_prefix_window(
            qd[:, :, 0, :], k_pref_l, v_pref_l, k_win_l, v_win_l,
            kd[:, :, 0, :], vd[:, :, 0, :], prefix_lengths=positions0,
            w=w, window=cfg.sliding_window, kv_len=None)   # [B, Hq, Dh]

        # prefill population: chunk kv joins the buffer, then ONE flash
        # call over it with the query block offset at w*C; the
        # begin/length bounds keep each row inside its own span.
        kbuf_l = jax.lax.dynamic_index_in_dim(pre_kbuf, li, 0,
                                              keepdims=False)
        vbuf_l = jax.lax.dynamic_index_in_dim(pre_vbuf, li, 0,
                                              keepdims=False)
        kbuf_cur = jax.lax.dynamic_update_slice_in_dim(
            kbuf_l, kp.astype(kbuf_l.dtype), w * c, axis=2)
        vbuf_cur = jax.lax.dynamic_update_slice_in_dim(
            vbuf_l, vp.astype(vbuf_l.dtype), w * c, axis=2)
        o_pre = flash_attention(
            qp, kbuf_cur.astype(qp.dtype), vbuf_cur.astype(qp.dtype),
            causal=True, kv_lengths=pre_kv_len,
            q_offsets=jnp.broadcast_to(w * c, (p,)),
            kv_begins=pre_kv_begin)                 # [P, Hq, C, Dh]

        o = jnp.concatenate([
            o_dec.reshape(b, hq * dh),
            o_pre.transpose(0, 2, 1, 3).reshape(p * c, hq * dh),
        ], axis=0)
        x = x + L.qmatmul(o, layer["wo"])           # one wo matmul
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)                    # one FFN pass
        return x, (kd[:, :, 0, :], vd[:, :, 0, :], kp, vp)

    x, (k_cols, v_cols, pre_k, pre_v) = jax.lax.scan(body, x, xs)
    logits = _unembed(x[:b][:, None, :], params, cfg)[:, 0]
    x_pre_out = x[b:].reshape(p, c, d_model)
    h_step = jnp.take_along_axis(
        x_pre_out, jnp.clip(pre_sel_rel, 0, c - 1)[:, None, None],
        axis=1)[:, 0]                                      # [P, D]
    return logits, k_cols, v_cols, pre_k, pre_v, h_step


def merge_prefill(cache: Params, k_buf: jax.Array, v_buf: jax.Array,
                  sidx: jax.Array, pidx: jax.Array) -> Params:
    """Scatter a dispatch's prefill-chunk buffers into the cache.

    k_buf/v_buf: [L, P, Hkv, BUF, Dh]; the host-built maps say where
    every buffer column goes: column j of lane i lands at cache
    position ``pidx[i, j]`` of slot ``sidx[i, j]``. Padding/garbage
    columns carry out-of-range indices and drop — nothing may write
    into a live slot's timeline.
    """
    k = cache["k"].at[:, sidx, :, pidx, :].set(
        k_buf.transpose(1, 3, 0, 2, 4).astype(cache["k"].dtype),
        mode="drop")
    v = cache["v"].at[:, sidx, :, pidx, :].set(
        v_buf.transpose(1, 3, 0, 2, 4).astype(cache["v"].dtype),
        mode="drop")
    return {"k": k, "v": v}


def merge_window(cache: Params, k_win: jax.Array, v_win: jax.Array,
                 positions0: jax.Array, steps: int) -> Params:
    """Scatter a decode window's KV into the big cache, once.

    k_win/v_win: [L, B, Hkv, W, Dh]; slot b's window columns land at
    cache positions ``positions0[b] + [0, steps)``. Out-of-range columns
    drop (same semantics as the per-step scatter this replaces). One
    transpose per window puts W in front of the head axis to match the
    advanced-indexing update shape [B, W, L, H, D].
    """
    b = k_win.shape[1]
    w = k_win.shape[3]
    bidx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, w))
    pidx = positions0[:, None] + jnp.arange(w)[None, :]
    if steps < w:
        k_win = k_win[:, :, :, :steps]
        v_win = v_win[:, :, :, :steps]
        bidx, pidx = bidx[:, :steps], pidx[:, :steps]
    k_upd = k_win.transpose(1, 3, 0, 2, 4)     # [B, W, L, H, D]
    v_upd = v_win.transpose(1, 3, 0, 2, 4)
    # cache axes [L, B, H, S, D]; advanced indices on axes 1 and 3 put
    # the [B, W] index shape in front: update shape [B, W, L, H, D].
    k = cache["k"].at[:, bidx, :, pidx, :].set(
        k_upd.astype(cache["k"].dtype), mode="drop")
    v = cache["v"].at[:, bidx, :, pidx, :].set(
        v_upd.astype(cache["v"].dtype), mode="drop")
    return {"k": k, "v": v}
