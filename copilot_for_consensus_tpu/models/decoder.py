"""Decoder-only LLM (Mistral / Llama-3 / Mixtral class).

Pre-norm transformer with RoPE, GQA, SwiGLU (or MoE) FFN, RMSNorm.
Layers are stacked on a leading axis and driven by ``lax.scan``:
compile time is O(1) in depth and every weight is one pjit-shardable
tensor. Three entry points:

* ``forward``      — [B, S] → logits [B, S, V] (scoring / training)
* ``prefill``      — builds the KV cache, returns last-position logits
* ``decode_step``  — one token per active slot against the cache

This model fills the generative-engine role the reference delegates to
Ollama / llama.cpp (``adapters/copilot_summarization/.../factory.py:89-94``,
``local_llm_summarizer.py:106-115``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from copilot_for_consensus_tpu.models.configs import DecoderConfig
from copilot_for_consensus_tpu.models import layers as L
from copilot_for_consensus_tpu.models.moe import moe_ffn

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Init + sharding metadata
# ---------------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: DecoderConfig,
                dtype=jnp.bfloat16) -> Params:
    """Truncated-normal init, scaled 1/sqrt(fan_in) for projections."""
    n, d, dh = cfg.n_layers, cfg.d_model, cfg.head_dim
    hq, hkv, f, v = cfg.n_heads, cfg.n_kv_heads, cfg.d_ff, cfg.vocab_size
    keys = iter(jax.random.split(rng, 16))

    def dense(key, shape, fan_in):
        return (jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
                * fan_in ** -0.5).astype(dtype)

    layer: Params = {
        "attn_norm": jnp.ones((n, d), dtype),
        "wq": dense(next(keys), (n, d, hq * dh), d),
        "wk": dense(next(keys), (n, d, hkv * dh), d),
        "wv": dense(next(keys), (n, d, hkv * dh), d),
        "wo": dense(next(keys), (n, hq * dh, d), hq * dh),
        "ffn_norm": jnp.ones((n, d), dtype),
    }
    if cfg.is_moe:
        e = cfg.n_experts
        layer.update({
            "router": dense(next(keys), (n, d, e), d),
            "w_gate": dense(next(keys), (n, e, d, f), d),
            "w_up": dense(next(keys), (n, e, d, f), d),
            "w_down": dense(next(keys), (n, e, f, d), f),
        })
    else:
        layer.update({
            "w_gate": dense(next(keys), (n, d, f), d),
            "w_up": dense(next(keys), (n, d, f), d),
            "w_down": dense(next(keys), (n, f, d), f),
        })
    params: Params = {
        "tok_emb": dense(next(keys), (v, d), d),
        "layers": layer,
        "final_norm": jnp.ones((d,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(keys), (d, v), d)
    return params


def logical_axes(cfg: DecoderConfig) -> Params:
    """Same structure as params; leaves are logical-axis tuples."""
    layer = {
        "attn_norm": (None, "norm"),
        "wq": (None, "embed", "heads"),
        "wk": (None, "embed", "kv_heads"),
        "wv": (None, "embed", "kv_heads"),
        "wo": (None, "heads", "embed"),
        "ffn_norm": (None, "norm"),
    }
    if cfg.is_moe:
        layer.update({
            "router": (None, "embed", None),
            "w_gate": (None, "experts", "embed", "expert_ffn"),
            "w_up": (None, "experts", "embed", "expert_ffn"),
            "w_down": (None, "experts", "expert_ffn", "embed"),
        })
    else:
        layer.update({
            "w_gate": (None, "embed", "ffn"),
            "w_up": (None, "embed", "ffn"),
            "w_down": (None, "ffn", "embed"),
        })
    axes: Params = {
        "tok_emb": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("norm",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def param_count(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------


def _ffn(x: jax.Array, layer: Params, cfg: DecoderConfig) -> jax.Array:
    return moe_ffn(x, layer, cfg) if cfg.is_moe else L.swiglu(x, layer)


def _unembed(x: jax.Array, params: Params, cfg: DecoderConfig) -> jax.Array:
    x = L.rms_norm(x, params["final_norm"], cfg.norm_eps)
    if cfg.tie_embeddings:
        return (x @ params["tok_emb"].T).astype(jnp.float32)
    return L.qmatmul(x, params["lm_head"]).astype(jnp.float32)


def block(x: jax.Array, layer: Params, cfg: DecoderConfig,
          lengths: jax.Array | None = None,
          attn_impl: str = "auto") -> jax.Array:
    """One transformer block: [B, S, D] → [B, S, D]. The single source of
    the block body — forward and the pp pipeline both run this, so model
    changes cannot drift between them."""
    h, _, _ = L.attn_prefill(
        L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
        layer, cfg, lengths=lengths, impl=attn_impl)
    x = x + h
    return x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                    layer, cfg)


def forward(params: Params, tokens: jax.Array, cfg: DecoderConfig,
            lengths: jax.Array | None = None,
            attn_impl: str = "auto") -> jax.Array:
    """Scoring/training pass: [B, S] int tokens → [B, S, V] fp32 logits."""
    x = params["tok_emb"][tokens]

    def body(x, layer):
        return block(x, layer, cfg, lengths, attn_impl), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return _unembed(x, params, cfg)


def init_cache(cfg: DecoderConfig, batch: int, max_len: int,
               dtype=jnp.bfloat16) -> Params:
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes() -> Params:
    return {"k": (None, "batch", "kv_heads", None, None),
            "v": (None, "batch", "kv_heads", None, None)}


def prefill(params: Params, tokens: jax.Array, lengths: jax.Array,
            cfg: DecoderConfig, cache: Params,
            attn_impl: str = "auto") -> tuple[jax.Array, Params]:
    """Prompt pass. tokens: [B, S] right-padded; lengths: [B]. Writes kv for
    positions [0, S) into the cache and returns (last-valid-position logits
    [B, V] fp32, cache)."""
    b, s = tokens.shape
    x = params["tok_emb"][tokens]

    def body(x, scanned):
        layer, k_cache, v_cache = scanned
        h, k, v = L.attn_prefill(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, lengths=lengths, impl=attn_impl)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        k_cache = jax.lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), 0, axis=2)
        v_cache = jax.lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), 0, axis=2)
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    logits = _unembed(x, params, cfg)                       # [B, S, V]
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    return last, {"k": k_new, "v": v_new}


def decode_step(params: Params, tokens: jax.Array, positions: jax.Array,
                cfg: DecoderConfig, cache: Params,
                kv_len: int | None = None
                ) -> tuple[jax.Array, Params]:
    """One decode step. tokens: [B] int — the tokens to feed; positions:
    [B] — the cache index each token occupies; ``kv_len`` (static) bounds
    the cache prefix attention reads. Returns ([B, V] fp32 logits,
    updated cache)."""
    x = params["tok_emb"][tokens][:, None, :]               # [B, 1, D]

    # The stacked cache rides the scan CARRY with per-column scatter
    # writes (attn_decode_stacked): as scan xs/ys it would be fully
    # re-materialized (read + write) every token step — more HBM traffic
    # than the weights at serving shapes.
    def body(carry, scanned):
        x, k_cache, v_cache = carry
        layer, li = scanned
        h, k_cache, v_cache = L.attn_decode_stacked(
            L.rms_norm(x, layer["attn_norm"], cfg.norm_eps),
            layer, cfg, positions, k_cache, v_cache, li, kv_len=kv_len)
        x = x + h
        x = x + _ffn(L.rms_norm(x, layer["ffn_norm"], cfg.norm_eps),
                     layer, cfg)
        return (x, k_cache, v_cache), None

    (x, k_new, v_new), _ = jax.lax.scan(
        body, (x, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(cfg.n_layers)))
    return _unembed(x, params, cfg)[:, 0], {"k": k_new, "v": v_new}
