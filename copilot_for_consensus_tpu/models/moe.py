"""Mixture-of-experts FFN (Mixtral-class: softmax top-2 routing).

GShard/Switch-style capacity-based dispatch: tokens are routed to experts
through dense one-hot dispatch/combine einsums, which XLA turns into MXU
matmuls and — when the expert axis is sharded over the ``ep`` mesh axis —
into all-to-all collectives over ICI. No data-dependent shapes, so the
whole layer stays jit-compatible (static capacity; overflow tokens drop,
standard for capacity-factor routing).

The reference has no MoE anywhere (SURVEY.md §2.3 — Mixtral-8x7B appears
only as a BASELINE.json target config); this is new TPU-first capability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from copilot_for_consensus_tpu.models.configs import DecoderConfig


def _q_einsum(spec: str, x: jax.Array, w, prefer_f32: bool = False
              ) -> jax.Array:
    """Expert einsum with transparent weight dequantization. int8 scales
    are per output channel, so they apply after the contraction; int4's
    group-wise scales do not commute with an einsum contraction, so the
    int4 path materializes the dequantized expert weight (experts are
    small relative to the dense stack). ``prefer_f32`` keeps fp32
    accumulation on the full-precision path."""
    from copilot_for_consensus_tpu.models.quant import (
        dequant_int4,
        quant_kind,
    )

    kind = quant_kind(w)
    if kind == "int4":
        return jnp.einsum(spec, x, dequant_int4(w, x.dtype))
    if kind == "int8":
        return (jnp.einsum(spec, x, w["q"].astype(x.dtype))
                * w["scale"].astype(x.dtype))
    if prefer_f32:
        return jnp.einsum(spec, x, w, preferred_element_type=jnp.float32)
    return jnp.einsum(spec, x, w)


def moe_capacity(n_tokens: int, cfg: DecoderConfig) -> int:
    cap = int(cfg.expert_capacity_factor * n_tokens
              * cfg.experts_per_token / cfg.n_experts)
    return max(8, -(-cap // 8) * 8)         # round up to sublane multiple


def moe_ffn(x: jax.Array, layer: dict, cfg: DecoderConfig) -> jax.Array:
    """x: [B, S, D] → [B, S, D].

    layer: router [D, E]; w_gate/w_up [E, D, F]; w_down [E, F, D].
    """
    b, s, d = x.shape
    t = b * s
    e, k = cfg.n_experts, cfg.experts_per_token
    cap = moe_capacity(t, cfg)
    xt = x.reshape(t, d)

    router_logits = (xt @ layer["router"]).astype(jnp.float32)   # [T, E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)                # [T, k]
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # Position of each (token, choice) inside its expert's capacity buffer.
    # Flatten choices in priority order (choice 0 of all tokens first) so
    # top-1 assignments win capacity over top-2 spillover.
    flat_idx = gate_idx.T.reshape(-1)                            # [k*T]
    onehot = jax.nn.one_hot(flat_idx, e, dtype=jnp.int32)        # [k*T, E]
    pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot - onehot  # 0-based
    pos = jnp.sum(pos_in_expert, axis=-1)                        # [k*T]
    keep = pos < cap

    # dispatch/combine: [T, E, C]
    disp_flat = (
        jax.nn.one_hot(flat_idx, e, dtype=x.dtype)[:, :, None]
        * jax.nn.one_hot(pos, cap, dtype=x.dtype)[:, None, :]
        * keep[:, None, None]
    )                                                            # [k*T, E, C]
    disp = disp_flat.reshape(k, t, e, cap)
    dispatch = jnp.sum(disp, axis=0)                             # [T, E, C]
    combine = jnp.einsum("ktec,kt->tec", disp, gate_vals.T.astype(x.dtype))

    expert_in = jnp.einsum("tec,td->ecd", dispatch, xt)          # [E, C, D]
    gate = jax.nn.silu(
        _q_einsum("ecd,edf->ecf", expert_in, layer["w_gate"],
                  prefer_f32=True).astype(jnp.float32))
    up = _q_einsum("ecd,edf->ecf", expert_in, layer["w_up"],
                   prefer_f32=True).astype(jnp.float32)
    h = (gate * up).astype(x.dtype)
    expert_out = _q_einsum("ecf,efd->ecd", h, layer["w_down"])   # [E, C, D]
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out.reshape(b, s, d)


def moe_load_balancing_loss(router_logits: jax.Array, gate_idx: jax.Array,
                            n_experts: int) -> jax.Array:
    """Switch-style auxiliary loss: mean fraction routed × mean prob."""
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    frac_tokens = jnp.mean(
        jax.nn.one_hot(gate_idx[..., 0], n_experts), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(frac_tokens * frac_probs)
