"""Bidirectional sentence encoder (MiniLM/BERT class) → text embeddings.

Post-norm transformer with learned positions, GELU MLP, masked mean
pooling and L2 normalization — the architecture class of
all-MiniLM-L6-v2, the reference's default embedder
(``adapters/copilot_embedding/.../sentence_transformer_provider.py:19-51``).
Unlike the reference's per-text ``embed()`` loop
(``embedding/app/service.py:393``), this forward is built for real
cross-text batching: [B, S] in, [B, dim] out, one MXU pass.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from copilot_for_consensus_tpu.models.configs import EncoderConfig
from copilot_for_consensus_tpu.models import layers as L
from copilot_for_consensus_tpu.ops.attention import attention

Params = dict[str, Any]


def init_params(rng: jax.Array, cfg: EncoderConfig,
                dtype=jnp.bfloat16) -> Params:
    n, d, f, v = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab_size
    keys = iter(jax.random.split(rng, 12))

    def dense(key, shape, fan_in):
        return (jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
                * fan_in ** -0.5).astype(dtype)

    return {
        "tok_emb": dense(next(keys), (v, d), d),
        "pos_emb": dense(next(keys), (cfg.max_positions, d), d),
        "emb_norm_w": jnp.ones((d,), dtype),
        "emb_norm_b": jnp.zeros((d,), dtype),
        "layers": {
            "wq": dense(next(keys), (n, d, d), d),
            "wk": dense(next(keys), (n, d, d), d),
            "wv": dense(next(keys), (n, d, d), d),
            "wo": dense(next(keys), (n, d, d), d),
            # BERT-family projections carry biases; zero at init, real
            # values under checkpoint load (checkpoint/hf.py).
            "wq_b": jnp.zeros((n, d), dtype),
            "wk_b": jnp.zeros((n, d), dtype),
            "wv_b": jnp.zeros((n, d), dtype),
            "wo_b": jnp.zeros((n, d), dtype),
            "attn_norm_w": jnp.ones((n, d), dtype),
            "attn_norm_b": jnp.zeros((n, d), dtype),
            "w_in": dense(next(keys), (n, d, f), d),
            "b_in": jnp.zeros((n, f), dtype),
            "w_out": dense(next(keys), (n, f, d), f),
            "b_out": jnp.zeros((n, d), dtype),
            "ffn_norm_w": jnp.ones((n, d), dtype),
            "ffn_norm_b": jnp.zeros((n, d), dtype),
        },
    }


def logical_axes(cfg: EncoderConfig) -> Params:
    return {
        "tok_emb": ("vocab", "embed"),
        "pos_emb": (None, "embed"),
        "emb_norm_w": ("norm",),
        "emb_norm_b": ("norm",),
        "layers": {
            "wq": (None, "embed", "heads"),
            "wk": (None, "embed", "heads"),
            "wv": (None, "embed", "heads"),
            "wo": (None, "heads", "embed"),
            "wq_b": (None, "heads"),
            "wk_b": (None, "heads"),
            "wv_b": (None, "heads"),
            "wo_b": (None, "norm"),
            "attn_norm_w": (None, "norm"),
            "attn_norm_b": (None, "norm"),
            "w_in": (None, "embed", "ffn"),
            "b_in": (None, "ffn"),
            "w_out": (None, "ffn", "embed"),
            "b_out": (None, "norm"),
            "ffn_norm_w": (None, "norm"),
            "ffn_norm_b": (None, "norm"),
        },
    }


def encode(params: Params, tokens: jax.Array, lengths: jax.Array,
           cfg: EncoderConfig, attn_impl: str = "auto") -> jax.Array:
    """tokens: [B, S] right-padded; lengths: [B] → [B, d_model] fp32,
    L2-normalized (cosine-ready, matching sentence-transformers)."""
    b, s = tokens.shape
    if s > cfg.max_positions:
        raise ValueError(
            f"sequence length {s} exceeds max_positions "
            f"{cfg.max_positions}; the caller must truncate or window"
        )
    dh = cfg.head_dim
    positions = jnp.arange(s)
    x = params["tok_emb"][tokens] + params["pos_emb"][positions][None]
    x = L.layer_norm(x, params["emb_norm_w"], params["emb_norm_b"],
                     cfg.norm_eps)

    def body(x, layer):
        q = (x @ layer["wq"] + layer["wq_b"]).reshape(
            b, s, cfg.n_heads, dh).transpose(0, 2, 1, 3)
        k = (x @ layer["wk"] + layer["wk_b"]).reshape(
            b, s, cfg.n_heads, dh).transpose(0, 2, 1, 3)
        v = (x @ layer["wv"] + layer["wv_b"]).reshape(
            b, s, cfg.n_heads, dh).transpose(0, 2, 1, 3)
        o = attention(q, k, v, causal=False, kv_lengths=lengths,
                      impl=attn_impl)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.d_model)
        x = L.layer_norm(x + o @ layer["wo"] + layer["wo_b"],
                         layer["attn_norm_w"],
                         layer["attn_norm_b"], cfg.norm_eps)
        h = L.gelu_mlp(x, layer)
        x = L.layer_norm(x + h, layer["ffn_norm_w"], layer["ffn_norm_b"],
                         cfg.norm_eps)
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])

    # Masked mean pooling over valid positions, then L2 normalize.
    mask = (jnp.arange(s)[None, :] < lengths[:, None])
    xf = x.astype(jnp.float32) * mask[..., None]
    pooled = jnp.sum(xf, axis=1) / jnp.maximum(
        lengths[:, None].astype(jnp.float32), 1.0)
    norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
    return pooled / jnp.maximum(norm, 1e-9)
