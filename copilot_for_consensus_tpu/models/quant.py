"""Weight-only int8 quantization for serving.

Per-output-channel symmetric scales over the contraction axis (axis -2 of
every ``x @ W`` weight), so dequantization commutes with the matmul:
``(x @ q) * scale == x @ (q * scale)`` exactly. Weights live in HBM as
int8 (half the bytes of bf16 — decode is HBM-bandwidth-bound, so this is
both the memory fix that fits Mistral-7B-class models on a single 16GB
v5e chip and a ~2× decode-throughput lever). The cast to compute dtype
happens per scan-sliced layer, so the transient is one layer, never the
stacked tensor.

A quantized leaf is the dict ``{"q": int8, "scale": f32}`` (pytree-
transparent); ``layers.qmatmul`` dispatches on it, plain arrays pass
through unchanged.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any

import jax
import jax.numpy as jnp

# Decoder leaves quantized by default: every matmul weight. Embedding
# gather and norms stay bf16 (tiny); the MoE router stays full precision
# (routing decisions are precision-sensitive and the weight is small).
DECODER_QUANT_LEAVES = (
    ("layers", "wq"), ("layers", "wk"), ("layers", "wv"), ("layers", "wo"),
    ("layers", "w_gate"), ("layers", "w_up"), ("layers", "w_down"),
    ("lm_head",),
)


def is_quantized(leaf: Any) -> bool:
    return (isinstance(leaf, dict) and "scale" in leaf
            and ("q" in leaf or "q4" in leaf))


def quant_kind(leaf: Any) -> str | None:
    """None for plain arrays, else "int8" / "int4"."""
    if not isinstance(leaf, dict) or "scale" not in leaf:
        return None
    if "q4" in leaf:
        return "int4"
    if "q" in leaf:
        return "int8"
    return None


# Process-wide switch for the fused Pallas int8 matmul. Sharded engines
# disable it (the kernel is not GSPMD-partitionable; the XLA dequant
# expression partitions naturally over tp). Process-global because model
# forwards are traced lazily from engine internals.
_PALLAS_QMATMUL = True

# Activation quantization mode for the decode matmuls. "weight_only"
# keeps activations bf16 (dequant-style matmuls); "a8" dynamically
# quantizes activations to int8 per row and uses the MXU's native
# int8×int8 path (W8A8/W4A8 kernels in ops/quant_matmul.py) — the
# weight bytes then go HBM → VMEM → MXU without a VPU widening pass.
_ACT_QUANT = "weight_only"


def set_pallas_qmatmul(enabled: bool) -> None:
    global _PALLAS_QMATMUL
    _PALLAS_QMATMUL = enabled


# Thread-local override so ONE engine can re-route ONE of its programs
# (e.g. long-extent int4 decode → XLA dequant) without flipping the
# process-wide flag under other engines: the flag is read at TRACE
# time, so holding the override around a jitted call bakes the route
# into that program only.
_PALLAS_TLS = threading.local()


@contextlib.contextmanager
def pallas_qmatmul_override(enabled: bool | None):
    """Force (or, with None, don't touch) the Pallas-qmatmul route for
    model code traced on this thread inside the block."""
    if enabled is None:
        yield
        return
    prev = getattr(_PALLAS_TLS, "value", None)
    _PALLAS_TLS.value = enabled
    try:
        yield
    finally:
        _PALLAS_TLS.value = prev


def pallas_qmatmul_enabled() -> bool:
    override = getattr(_PALLAS_TLS, "value", None)
    return _PALLAS_QMATMUL if override is None else override


def set_act_quant(mode: str) -> None:
    """"weight_only" (default) or "a8" (dynamic per-row int8
    activations into native int8 MXU dots — W8A8-class accuracy)."""
    if mode not in ("weight_only", "a8"):
        raise ValueError(f"unknown act-quant mode {mode!r}")
    global _ACT_QUANT
    _ACT_QUANT = mode


def act_quant_mode() -> str:
    return _ACT_QUANT


def quantize_tensor(w: jax.Array) -> dict[str, jax.Array]:
    """Symmetric int8 over axis -2 (the contraction axis of ``x @ W``)."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale}


INT4_GROUP = 256   # rows per scale group; multiple of 256 (TPU lane tiling)


def dequant_int4(leaf: dict, dtype) -> jax.Array:
    """Materialize an int4 leaf back to ``dtype`` — the XLA fallback and
    einsum (MoE) path. Handles any leading batch/layer/expert dims:
    the contraction axis is -2 of the unpacked tensor and the group
    axis is -2 of the scale."""
    from copilot_for_consensus_tpu.ops.quant_matmul import unpack_int4

    q = unpack_int4(leaf["q4"])                     # [..., D, F]
    scale = leaf["scale"]                           # [..., G, F]
    d, g = q.shape[-2], scale.shape[-2]
    s = jnp.repeat(scale, d // g, axis=-2)
    return q.astype(dtype) * s.astype(dtype)


def quantize_tensor_int4(w: jax.Array,
                         group: int = INT4_GROUP) -> dict[str, jax.Array]:
    """Symmetric int4 with group-wise scales over the contraction axis.

    Four bits is too coarse for one scale per output channel, so each
    ``group`` rows of the contraction axis get their own scale row —
    the standard accuracy recovery for 4-bit weight-only quantization.
    Nibbles are packed two-per-int8-byte (``ops.quant_matmul.pack_int4``)
    so the serving dtype works around this JAX build's broken int4
    arrays and halves weight HBM again over int8."""
    from copilot_for_consensus_tpu.ops.quant_matmul import pack_int4

    *lead, d, f = w.shape
    group = min(group, d)          # small models: one group spans D
    if d % group:
        raise ValueError(f"contraction dim {d} not divisible by "
                         f"group {group}")
    wf = w.astype(jnp.float32).reshape(*lead, d // group, group, f)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / 7.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -8, 7)
    q = q.reshape(*lead, d, f).astype(jnp.int8)
    return {"q4": pack_int4(q),
            "scale": scale.reshape(*lead, d // group, f)}


def fuse_int4_projections(params: dict) -> dict:
    """Fuse the int4 qkv and gate/up leaves into single wide leaves.

    Decode through the Pallas int4 kernels pays ~65 µs per kernel call
    (measured r3); 7 calls/layer lose the format's halved-bytes
    advantage. q/k/v share the input x, as do gate/up, so their packed
    nibbles and group scales concatenate along the OUTPUT axis into one
    ``wqkv`` [L, D/2, (Hq+2Hkv)·dh] and one ``w_gu`` [L, D/2, 2F] —
    4 calls/layer. ``layers._project_qkv`` / ``layers.swiglu`` split the
    fused product by column; packing along D is untouched, so per-group
    scales stay exact. Single-device serving only (the fused leaves have
    no sharding rules); callers gate on ``mesh is None``."""
    layers_t = params.get("layers", {})
    if "wqkv" in layers_t or "wq" not in layers_t:
        return params
    if quant_kind(layers_t["wq"]) != "int4" or \
            quant_kind(layers_t.get("w_gate")) != "int4":
        raise ValueError("fuse_int4_projections needs int4 leaves")
    if layers_t["w_gate"]["q4"].ndim != 3:
        # MoE expert leaves are [L, E, D/2, F]: moe_ffn dispatches per
        # expert by name and must keep w_gate/w_up — fusing (and
        # deleting) them breaks every MoE forward.
        raise ValueError(
            "fuse_int4_projections supports dense FFN leaves only; "
            "gate fusion on cfg.is_moe at the call site")

    def cat(*leaves):
        return {"q4": jnp.concatenate([l["q4"] for l in leaves], axis=-1),
                "scale": jnp.concatenate([l["scale"] for l in leaves],
                                         axis=-1)}

    fused = dict(layers_t)
    fused["wqkv"] = cat(layers_t["wq"], layers_t["wk"], layers_t["wv"])
    fused["w_gu"] = cat(layers_t["w_gate"], layers_t["w_up"])
    for k in ("wq", "wk", "wv", "w_gate", "w_up"):
        del fused[k]
    return {**params, "layers": fused}


def _get_path(tree: dict, path: tuple[str, ...]):
    node = tree
    for p in path:
        if not isinstance(node, dict) or p not in node:
            return None
        node = node[p]
    return node


def _set_path(tree: dict, path: tuple[str, ...], value) -> None:
    node = tree
    for p in path[:-1]:
        node = node[p]
    node[path[-1]] = value


def quantize_params(params: dict,
                    leaves: tuple[tuple[str, ...], ...] = DECODER_QUANT_LEAVES,
                    mode: str = "int8",
                    group: int = INT4_GROUP) -> dict:
    """Returns a copy of the param tree with the given leaves quantized
    (``mode``: "int8" per-channel or "int4" group-wise packed)."""
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown quantization mode {mode!r}")
    out = jax.tree.map(lambda x: x, params)  # shallow-ish structural copy
    for path in leaves:
        w = _get_path(params, path)
        if w is not None:
            _set_path(out, path,
                      quantize_tensor(w) if mode == "int8"
                      else quantize_tensor_int4(w, group))
    return out


def init_random_quantized(rng: jax.Array, cfg, dtype=jnp.bfloat16,
                          leaves: tuple[tuple[str, ...], ...] = DECODER_QUANT_LEAVES,
                          mode: str = "int8",
                          group: int = INT4_GROUP) -> dict:
    """Random decoder params with quantized leaves born int8 on-device.

    Serving benches need weights with the right shapes/dtypes, not trained
    values; materializing bf16 first and quantizing would transiently need
    2-3× the final HBM (what OOMs a 7B on a 16GB chip). Real checkpoints
    are quantized offline on the host (``quantize_params``) where RAM is
    plentiful. Shapes come from ``jax.eval_shape`` over the real init, so
    there is exactly one source of truth for the param tree.

    The whole tree is generated by ONE jitted program: per-leaf dispatch
    costs a full XLA compile each (~10s × 13 leaves was most of a 155 s
    engine build on hardware where compiles round-trip a tunnel).
    """
    from copilot_for_consensus_tpu.models import decoder

    shapes = jax.eval_shape(
        lambda k: decoder.init_params(k, cfg, dtype=dtype), rng)
    quant_set = set(leaves)
    flat: list[tuple[tuple, Any]] = jax.tree_util.tree_flatten_with_path(
        shapes)[0]

    def build(path, aval, key):
        names = tuple(p.key for p in path)
        shape = aval.shape
        if names in quant_set:
            fan_in = shape[-2]
            if mode == "int4":
                # Random packed bytes: each nibble uniform in [-8, 7],
                # std ≈ 4.61; scale to ~1/sqrt(fan_in).
                g = min(group, fan_in)
                packed_shape = shape[:-2] + (shape[-2] // 2,) + shape[-1:]
                q4 = jax.random.randint(key, packed_shape, -128, 128,
                                        dtype=jnp.int32).astype(jnp.int8)
                scale_shape = shape[:-2] + (fan_in // g,) + shape[-1:]
                scale = jnp.full(scale_shape, fan_in ** -0.5 / 4.61,
                                 jnp.float32)
                return {"q4": q4, "scale": scale}
            q = jax.random.randint(key, shape, -127, 128, dtype=jnp.int8)
            # uniform int8 has std ≈ 73.3; scale to ~1/sqrt(fan_in)
            scale_shape = shape[:-2] + (1,) + shape[-1:]
            scale = jnp.full(scale_shape, fan_in ** -0.5 / 73.3,
                             jnp.float32)
            return {"q": q, "scale": scale}
        if "norm" in names[-1]:
            return jnp.ones(shape, aval.dtype)
        if names[-1] == "tok_emb":
            fan_in = shape[-1]        # init_params scales embeds by d_model
        else:
            fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
        return (jax.random.truncated_normal(key, -2, 2, shape,
                                            jnp.float32)
                * fan_in ** -0.5).astype(aval.dtype)

    def build_all(key):
        keys = jax.random.split(key, len(flat))
        out: dict = {}
        for i, (path, aval) in enumerate(flat):
            names = tuple(p.key for p in path)
            node = out
            for n in names[:-1]:
                node = node.setdefault(n, {})
            node[names[-1]] = build(path, aval, keys[i])
        return out

    return jax.jit(build_all)(rng)


def quantize_logical_axes(axes: dict,
                          leaves: tuple[tuple[str, ...], ...] = DECODER_QUANT_LEAVES,
                          mode: str = "int8") -> dict:
    """Transform the logical-axes tree to match a quantized param tree.

    int8: the scale keeps every axis except the (size-1) contraction
    axis, which becomes None/replicated. int4: the packed q4 keeps the
    original axes (packed rows shard like the rows they encode); the
    scale's group axis is replicated — it can be size 1 (small models
    where one group spans the contraction axis) which a tp>1 mesh can't
    divide, and at ≤G×F×4 bytes the tensor is too small to matter."""
    out = {k: (dict(v) if isinstance(v, dict) else v)
           for k, v in axes.items()}
    for path in leaves:
        t = _get_path(axes, path)
        if t is not None:
            scale_axes = tuple(
                None if i == len(t) - 2 else a for i, a in enumerate(t))
            _set_path(out, path,
                      {"q4" if mode == "int4" else "q": t,
                       "scale": scale_axes})
    return out
