"""Azure Service Bus bus driver — REST API, no SDK.

Fills the role of the reference's SDK-based publisher/subscriber pair
(``copilot_message_bus/azureservicebuspublisher.py:30``,
``copilot_message_bus/azureservicebussubscriber.py:29``) with the
documented Service Bus HTTP wire protocol and stdlib HTTP only, in the
style of this repo's other Azure drivers (Blob/Key Vault/Cosmos): the
same requests work against real Azure, the emulator, or the in-process
wire-contract mock in ``tests/test_azure_servicebus.py``.

Topology (the repo's bus contract, ``bus/base.py``):

* ONE topic plays the exchange role; every envelope is sent to it with
  the routing key stamped both as the message ``Label`` (subject) and a
  ``routing_key`` custom property.
* one subscription per (group, routing key), created on demand with a
  SQL rule ``routing_key = '<rk>'`` — the server-side filtering the
  reference provisions in Bicep (rule ``EventTypeFilter``,
  ``infra/azure/modules/servicebus.bicep`` via
  ``tests/infra/azure/test_servicebus_filters.py:115``). Subscribers
  sharing a ``group`` name share the subscription and compete;
  distinct groups each see every message.
* consume is peek-lock: callback ok → DELETE (complete); callback
  raising → PUT (abandon, redelivery); the subscription's
  ``MaxDeliveryCount = max_redeliveries + 1`` makes the BROKER move
  poisoned messages to ``$DeadLetterQueue`` — the same at-least-once +
  DLQ contract as the first-party broker driver (``bus/broker.py``).
* locks expire server-side after ``lock_duration_s``; a renewal thread
  POSTs the lock URI at half-life while the callback runs (the SDK's
  ``AutoLockRenewer`` role) so slow handlers don't get redelivered.

Auth is SAS (SharedAccessSignature over the namespace URI) — the
documented HMAC-SHA256 scheme; tokens are minted per request window and
cached until near expiry.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any

_LOG = logging.getLogger(__name__)

from copilot_for_consensus_tpu.bus.base import (
    EventCallback,
    EventPublisher,
    EventSubscriber,
    PoisonEnvelope,
    PublishError,
)

API_VERSION = "2017-04"
#: transient HTTP statuses worth retrying (the reference's
#: ``_is_transient_error`` heuristic, re-expressed for REST)
TRANSIENT_STATUSES = (408, 429, 500, 502, 503, 504)


def sas_token(endpoint: str, key_name: str, key: str,
              ttl_s: int = 3600, now: float | None = None) -> str:
    """Mint a SharedAccessSignature for the namespace URI (documented
    scheme: HMAC-SHA256 over ``<url-encoded-uri>\\n<expiry>``)."""
    uri = urllib.parse.quote_plus(endpoint.lower().rstrip("/"))
    expiry = int((now if now is not None else time.time()) + ttl_s)
    to_sign = f"{uri}\n{expiry}".encode()
    sig = base64.b64encode(
        hmac.new(key.encode(), to_sign, hashlib.sha256).digest())
    return ("SharedAccessSignature "
            f"sr={uri}&sig={urllib.parse.quote_plus(sig)}"
            f"&se={expiry}&skn={key_name}")


#: routing keys ride inside SqlFilter + ATOM XML (see
#: ``ensure_subscription``) — only this safe alphabet is accepted
_SAFE_RK = re.compile(r"[A-Za-z0-9._-]+\Z")


def validate_routing_key(rk: str) -> None:
    """Reject routing keys that cannot be safely interpolated into a
    SqlFilter expression / ATOM XML rule body. Called for EVERY key
    before any subscription state is mutated, so a bad key in a batch
    cannot leave partial routes behind."""
    if not _SAFE_RK.match(rk):
        raise ValueError(
            f"routing key {rk!r} contains characters outside "
            "[A-Za-z0-9._-]; refusing to build a SqlFilter from it")


def entity_name(rk: str, group: str) -> str:
    """Subscription name for (group, routing key): a readable sanitized
    prefix + a digest of the UNsanitized pair. The digest is what makes
    the name injective — sanitization collapses characters ('a-b'.'c'
    vs 'a'-'b.c' would collide on prefix alone) and a collision would
    silently drop the second key's messages behind the first key's SQL
    rule. Service Bus limits subscription names to 50 chars."""
    digest = hashlib.sha256(
        f"{group}\x00{rk}".encode()).hexdigest()[:8]
    raw = f"{group}-{rk}" if group else rk
    safe = re.sub(r"[^A-Za-z0-9._-]", "-", raw)[:41]
    return f"{safe}-{digest}"


class _Transport:
    """Shared REST plumbing: SAS header, retries, error mapping."""

    def __init__(self, namespace: str, key_name: str, key: str, *,
                 endpoint: str = "", timeout_s: float = 30.0,
                 retry_attempts: int = 3, retry_backoff_s: float = 0.3):
        if not namespace and not endpoint:
            raise ValueError("azure_servicebus needs namespace or endpoint")
        if not key:
            raise ValueError("azure_servicebus needs key")
        self.endpoint = (endpoint.rstrip("/") or
                         f"https://{namespace}.servicebus.windows.net")
        self.key_name = key_name or "RootManageSharedAccessKey"
        self.key = key
        self.timeout_s = timeout_s
        self.retry_attempts = retry_attempts
        self.retry_backoff_s = retry_backoff_s
        self._token = ""
        self._token_exp = 0.0
        self._token_lock = threading.Lock()

    def _auth(self) -> str:
        with self._token_lock:
            if time.time() > self._token_exp - 60:
                self._token = sas_token(self.endpoint, self.key_name,
                                        self.key)
                self._token_exp = time.time() + 3600
            return self._token

    def request(self, method: str, path: str, *,
                body: bytes | None = None,
                headers: dict[str, str] | None = None,
                ok: tuple[int, ...] = (200, 201),
                content_type: str = "application/json",
                retry: bool = True) -> tuple[int, bytes, dict[str, str]]:
        """One REST call with retry-on-transient; returns
        (status, body, lowercased headers). Statuses in ``ok`` return;
        everything else raises PublishError."""
        url = f"{self.endpoint}{path}"
        attempt = 0
        while True:
            req = urllib.request.Request(url, method=method, data=body,
                                         headers={
                                             "Authorization": self._auth(),
                                             "Content-Type": content_type,
                                             **(headers or {}),
                                         })
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    return (resp.status, resp.read(),
                            {k.lower(): v for k, v in resp.headers.items()})
            except urllib.error.HTTPError as exc:
                if exc.code in ok:
                    return (exc.code, exc.read(),
                            {k.lower(): v for k, v in exc.headers.items()})
                transient = exc.code in TRANSIENT_STATUSES
                if not (retry and transient
                        and attempt < self.retry_attempts):
                    detail = exc.read()[:200].decode("utf-8", "replace")
                    raise PublishError(
                        f"servicebus {method} {path} failed: "
                        f"HTTP {exc.code} {detail}") from exc
            except (urllib.error.URLError, TimeoutError, OSError) as exc:
                if not (retry and attempt < self.retry_attempts):
                    raise PublishError(
                        f"servicebus unreachable at {self.endpoint}: {exc}"
                    ) from exc
            time.sleep(self.retry_backoff_s * (2 ** attempt))
            attempt += 1

    # -- entity management (ATOM, idempotent: 409 Conflict == exists) --

    def ensure_topic(self, topic: str) -> None:
        atom = ('<entry xmlns="http://www.w3.org/2005/Atom">'
                '<content type="application/xml">'
                '<TopicDescription xmlns="http://schemas.microsoft.com/'
                'netservices/2010/10/servicebus/connect"/>'
                "</content></entry>")
        self.request("PUT", f"/{topic}", body=atom.encode(),
                     content_type="application/atom+xml",
                     ok=(201, 409))

    def ensure_subscription(self, topic: str, sub: str, rk: str, *,
                            lock_duration_s: int,
                            max_delivery_count: int) -> None:
        """Create subscription + replace the match-all $Default rule
        with the routing-key SQL filter (the reference's Bicep
        ``EventTypeFilter`` rule).

        ``rk`` is interpolated into both a SqlFilter expression and an
        ATOM XML body, so it is restricted to ``[A-Za-z0-9._-]``
        (``validate_routing_key``) — a quote or XML metacharacter would
        break or ALTER the subscription rule."""
        validate_routing_key(rk)
        atom = ('<entry xmlns="http://www.w3.org/2005/Atom">'
                '<content type="application/xml">'
                '<SubscriptionDescription xmlns="http://schemas.'
                'microsoft.com/netservices/2010/10/servicebus/connect">'
                f"<LockDuration>PT{lock_duration_s}S</LockDuration>"
                f"<MaxDeliveryCount>{max_delivery_count}"
                "</MaxDeliveryCount>"
                "<DeadLetteringOnMessageExpiration>true"
                "</DeadLetteringOnMessageExpiration>"
                "</SubscriptionDescription></content></entry>")
        self.request(
            "PUT", f"/{topic}/subscriptions/{sub}", body=atom.encode(),
            content_type="application/atom+xml", ok=(201, 409))
        # Rules are (re-)asserted even when the subscription already
        # existed (409): a crash between subscription-create and
        # rule-create would otherwise leave a permanent match-all
        # $Default rule feeding every routing key to this callback.
        # Both calls are idempotent (409/404 tolerated).
        rule = ('<entry xmlns="http://www.w3.org/2005/Atom">'
                '<content type="application/xml">'
                '<RuleDescription xmlns="http://schemas.microsoft.com/'
                'netservices/2010/10/servicebus/connect">'
                '<Filter i:type="SqlFilter" xmlns:i="http://www.w3.org/'
                '2001/XMLSchema-instance">'
                f"<SqlExpression>routing_key = '{rk}'</SqlExpression>"
                "</Filter></RuleDescription></content></entry>")
        self.request("PUT",
                     f"/{topic}/subscriptions/{sub}/rules/RoutingKeyFilter",
                     body=rule.encode(),
                     content_type="application/atom+xml", ok=(201, 409))
        self.request("DELETE",
                     f"/{topic}/subscriptions/{sub}/rules/%24Default",
                     ok=(200, 204, 404))


class AzureServiceBusPublisher(EventPublisher):
    """Topic publisher (reference
    ``azureservicebuspublisher.py:30`` role: persistent messages, retry
    with exponential backoff on transient errors, subject + custom
    properties for server-side filtering)."""

    def __init__(self, config: Any = None):
        cfg = dict(config or {})
        self.topic = cfg.get("topic") or cfg.get(
            "exchange", "copilot.events")
        self._t = _Transport(
            cfg.get("namespace", ""), cfg.get("key_name", ""),
            cfg.get("key", ""), endpoint=cfg.get("endpoint", ""),
            timeout_s=float(cfg.get("timeout_s", 30.0)),
            retry_attempts=int(cfg.get("retry_attempts", 3)),
            retry_backoff_s=float(cfg.get("retry_backoff_s", 0.3)))
        self._connected = False

    def connect(self) -> None:
        self._t.ensure_topic(self.topic)
        self._connected = True

    def publish_envelope(self, envelope, routing_key=None) -> None:
        if not self._connected:
            self.connect()
        if routing_key is None:
            from copilot_for_consensus_tpu.core.events import EVENT_TYPES

            cls = EVENT_TYPES.get(envelope.get("event_type", ""))
            routing_key = cls.routing_key if cls else "unrouted"
        from copilot_for_consensus_tpu.obs import trace

        # trace-context stamp, same contract as the broker/inproc
        # drivers: first publish injects, re-publish preserves
        envelope = trace.inject(envelope, routing_key)
        body = json.dumps(dict(envelope)).encode()
        # Label (subject) + custom property both carry the routing key:
        # rules filter on the property; operators read the subject.
        props = {"Label": routing_key,
                 "MessageId": str(envelope.get("event_id", "") or
                                  hashlib.sha256(body).hexdigest()[:32])}
        headers = {
            "BrokerProperties": json.dumps(props),
            # custom properties ride as headers with JSON-quoted values
            "routing_key": json.dumps(routing_key),
            "event_type": json.dumps(envelope.get("event_type", "")),
        }
        self._t.request("POST", f"/{self.topic}/messages", body=body,
                        headers=headers, ok=(201,))


class AzureServiceBusSubscriber(EventSubscriber):
    """Peek-lock consumer over topic subscriptions (reference
    ``azureservicebussubscriber.py:29`` role: manual complete/abandon,
    auto lock renewal, DLQ after MaxDeliveryCount)."""

    def __init__(self, config: Any = None, group: str | None = None):
        cfg = dict(config or {})
        self.topic = cfg.get("topic") or cfg.get(
            "exchange", "copilot.events")
        self.group = group or cfg.get("group") or ""
        self.lock_duration_s = int(cfg.get("lock_duration_s", 60))
        self.max_redeliveries = int(cfg.get("max_redeliveries", 3))
        self.peek_timeout_s = int(cfg.get("peek_timeout_s", 1))
        self.poll_interval_s = float(cfg.get("poll_interval_s", 0.05))
        self.auto_renew = bool(cfg.get("auto_renew", True))
        self._t = _Transport(
            cfg.get("namespace", ""), cfg.get("key_name", ""),
            cfg.get("key", ""), endpoint=cfg.get("endpoint", ""),
            timeout_s=float(cfg.get("timeout_s", 30.0)),
            retry_attempts=int(cfg.get("retry_attempts", 3)),
            retry_backoff_s=float(cfg.get("retry_backoff_s", 0.3)))
        self._routes: dict[str, EventCallback] = {}
        self._subs: dict[str, str] = {}      # rk -> subscription name
        self._stop = threading.Event()
        #: optional MetricsCollector, assigned by wiring code AFTER
        #: construction (services/runner.py) — deliberately NOT read
        #: from ``cfg``: the config mapping carries plain data, and a
        #: stray "metrics" key there must not masquerade as a collector
        self.metrics = None
        #: messages deleted by the $Default-window guard because their
        #: stamped key matched no local route (see _dispatch)
        self.misroute_dropped = 0

    # -- wiring ---------------------------------------------------------

    def subscribe(self, routing_keys, callback) -> None:
        # validate the whole batch BEFORE mutating routes: a bad key
        # mid-list must not leave earlier keys half-registered
        for rk in routing_keys:
            validate_routing_key(rk)
        self._t.ensure_topic(self.topic)
        for rk in routing_keys:
            self._routes[rk] = callback
            sub = entity_name(rk, self.group)
            self._t.ensure_subscription(
                self.topic, sub, rk,
                lock_duration_s=self.lock_duration_s,
                max_delivery_count=self.max_redeliveries + 1)
            self._subs[rk] = sub

    # -- peek-lock primitives ------------------------------------------

    def _receive(self, sub: str, timeout_s: int,
                 dlq: bool = False) -> dict | None:
        """One peek-lock receive. Returns ``{envelope?, raw, lock_path,
        props}`` or None when the subscription is empty."""
        path = (f"/{self.topic}/subscriptions/{sub}"
                f"{'/%24DeadLetterQueue' if dlq else ''}"
                f"/messages/head?timeout={timeout_s}")
        status, raw, headers = self._t.request("POST", path,
                                               ok=(201, 204))
        if status == 204:
            return None
        props = json.loads(headers.get("brokerproperties", "{}"))
        # the publisher stamps the routing key as a custom property,
        # which comes back as its own JSON-quoted header on receive
        stamped_rk = None
        if headers.get("routing_key"):
            try:
                stamped_rk = json.loads(headers["routing_key"])
            except ValueError:
                stamped_rk = headers["routing_key"]
        lock_path = urllib.parse.urlparse(
            headers.get("location", "")).path
        if not lock_path:       # per-spec fallback construction
            mid, token = props.get("MessageId"), props.get("LockToken")
            if not mid or not token:
                # can't settle a message we can't address; surface as
                # the loop's transient-error class, not a KeyError that
                # would kill the consumer thread
                raise PublishError(
                    "servicebus receive returned neither Location nor "
                    "BrokerProperties MessageId/LockToken")
            lock_path = (f"/{self.topic}/subscriptions/{sub}"
                         f"{'/%24DeadLetterQueue' if dlq else ''}"
                         f"/messages/"
                         f"{urllib.parse.quote(str(mid), safe='')}/"
                         f"{urllib.parse.quote(str(token), safe='')}")
        return {"raw": raw, "props": props, "lock_path": lock_path,
                "stamped_rk": stamped_rk}

    def _complete(self, msg: dict) -> bool:
        try:
            self._t.request("DELETE", msg["lock_path"], ok=(200,),
                            retry=False)
            return True
        except PublishError:
            # lock lost (expired / already settled): the broker will
            # redeliver — at-least-once holds, don't crash the loop
            return False

    def _abandon(self, msg: dict) -> None:
        try:
            self._t.request("PUT", msg["lock_path"], ok=(200,),
                            retry=False)
        except PublishError:
            pass                # lock expired == broker already requeued

    def _renew(self, msg: dict) -> bool:
        try:
            self._t.request("POST", msg["lock_path"], ok=(200,),
                            retry=False)
            return True
        except PublishError:
            return False

    # -- consume loop ---------------------------------------------------

    def _dispatch(self, rk: str, msg: dict) -> None:
        cb = self._routes.get(rk)
        try:
            envelope = json.loads(msg["raw"].decode("utf-8"))
            if not isinstance(envelope, dict):
                raise ValueError("envelope is not an object")
        except (ValueError, UnicodeDecodeError):
            # malformed body can never succeed: complete it so it does
            # not block the subscription (reference behavior for
            # JSONDecodeError, ``azureservicebussubscriber.py:568``)
            self._complete(msg)
            return
        if cb is None:
            self._complete(msg)
            return
        # The subscription's SQL rule is asserted idempotently, but a
        # message enqueued through the match-all $Default rule during
        # the create-subscription -> delete-$Default window carries
        # whatever routing key the publisher STAMPED (the same custom
        # property the SQL rule filters on). Route by the stamp, not
        # the subscription: when this consumer has a route for the
        # stamped key the message dispatches LOCALLY to that callback.
        # If the stamped key's own subscription already existed at
        # publish time it got its own copy and the handler runs twice —
        # an at-least-once duplicate the pipeline's idempotent-replay
        # design already absorbs; but if that subscription did NOT yet
        # exist (the same half-provisioned window, one key later in the
        # subscribe batch), this $Default copy is the ONLY delivery and
        # dropping it would LOSE the message. Loss is the failure mode
        # the guard must never convert a duplicate into. A stamped key
        # with no local route is completed (dropped) with a log line +
        # counter so the window leak is observable, never delivered to
        # the wrong callback. Unstamped messages (foreign publishers)
        # are not checkable and dispatch as before.
        stamped = msg.get("stamped_rk")
        if stamped is not None and stamped != rk:
            local = self._routes.get(stamped)
            if local is None:
                self.misroute_dropped += 1
                _LOG.warning(
                    "servicebus $Default-window guard dropped message: "
                    "stamped routing key %r arrived on subscription for "
                    "%r with no local route", stamped, rk)
                try:
                    if self.metrics is not None:
                        self.metrics.increment(
                            "bus_misroute_dropped",
                            labels={"stamped": stamped,
                                    "subscription": rk})
                except Exception:
                    pass   # metrics must never take the consumer down
                self._complete(msg)
                return
            rk, cb = stamped, local
        stop_renew = threading.Event()
        if self.auto_renew:
            interval = max(self.lock_duration_s / 2.0, 0.05)

            def renewer():
                while not stop_renew.wait(interval):
                    if not self._renew(msg):
                        return

            threading.Thread(target=renewer, daemon=True,
                             name="sb-lock-renewer").start()
        from copilot_for_consensus_tpu.obs import trace

        try:
            # DeliveryCount starts at 1; attempt counts REdeliveries
            delivery = int(msg["props"].get("DeliveryCount", 1) or 1)
        except (TypeError, ValueError):
            delivery = 1
        trace.annotate_delivery(envelope, max(0, delivery - 1))
        try:
            cb(envelope)
        except PoisonEnvelope as exc:
            # Deterministic failure: the *Failed event (published by
            # BaseService before raising) is the operator record.
            # Settle the message — abandoning would re-run the handler
            # through the whole redelivery budget and mint a duplicate
            # failure event per delivery. This transport's REST surface
            # has no dead-letter settle op, so completing is the
            # degrade path bus/base.py names for drivers without
            # quarantine support.
            _LOG.warning("poison envelope settled on %r: %s",
                         rk, exc.reason)
            stop_renew.set()
            self._complete(msg)
            return
        except Exception:
            stop_renew.set()
            self._abandon(msg)   # redelivery; broker DLQs past max
            return
        stop_renew.set()
        self._complete(msg)

    def drain(self, max_messages: int | None = None) -> int:
        """Process what's queued now; returns the number handled."""
        n = 0
        progressed = True
        while progressed and (max_messages is None or n < max_messages):
            progressed = False
            for rk, sub in self._subs.items():
                if max_messages is not None and n >= max_messages:
                    break
                msg = self._receive(sub, 0)
                if msg is None:
                    continue
                self._dispatch(rk, msg)
                progressed = True
                n += 1
        return n

    def _long_poll_once(self) -> int:
        """One ``peek_timeout_s`` server-side long-poll round-robin over
        the subscriptions; dispatches at most one message per
        subscription. Against real Azure the server holds the request
        open, so an idle consumer costs one REST call per subscription
        per ``peek_timeout_s`` instead of one per ``poll_interval_s``."""
        n = 0
        for rk, sub in self._subs.items():
            if self._stop.is_set():
                break
            msg = self._receive(sub, self.peek_timeout_s)
            if msg is not None:
                self._dispatch(rk, msg)
                n += 1
        return n

    def start_consuming(self) -> None:
        """Blocking consume until stop(); drains fast while messages
        flow, falls back to server-side long-polling when idle, and
        survives outages by backing off (reference reconnect loop,
        ``azureservicebussubscriber.py:292``)."""
        self._stop.clear()
        backoff = self.poll_interval_s
        while not self._stop.is_set():
            try:
                n = self.drain()
                if n == 0:
                    n = self._long_poll_once()
            except PublishError:
                self._stop.wait(min(backoff, 5.0))
                backoff = min(backoff * 2, 5.0)
                continue
            backoff = self.poll_interval_s
            if n == 0:
                # guards against servers that answer timeout>0 with an
                # immediate 204 (no server-side blocking)
                self._stop.wait(self.poll_interval_s)

    def stop(self) -> None:
        self._stop.set()

    # -- DLQ surface (failed-queues CLI parity) ------------------------

    def dead_letters(self, rk: str) -> list[dict]:
        """Drain-read the subscription's $DeadLetterQueue (peek-lock +
        complete, so inspection removes them like the broker CLI's
        ``purge`` after listing)."""
        sub = self._subs.get(rk) or entity_name(rk, self.group)
        out = []
        while True:
            msg = self._receive(sub, 0, dlq=True)
            if msg is None:
                return out
            try:
                out.append(json.loads(msg["raw"].decode("utf-8")))
            except ValueError:
                out.append({"_malformed": msg["raw"][:200].decode(
                    "utf-8", "replace")})
            self._complete(msg)
