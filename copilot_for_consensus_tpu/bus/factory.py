"""Bus driver registration + create_publisher/create_subscriber helpers.

Parity with ``copilot_message_bus/factory.py:94-144``: construction is
config-driven, and validation wrapping is applied here so services never
instantiate raw drivers.
"""

from __future__ import annotations

from typing import Any

from copilot_for_consensus_tpu.bus.base import NoopPublisher, NoopSubscriber
from copilot_for_consensus_tpu.bus.inproc import InProcPublisher, InProcSubscriber
from copilot_for_consensus_tpu.bus.validating import (
    ValidatingPublisher,
    ValidatingSubscriber,
)
from copilot_for_consensus_tpu.core.factory import register_driver


def create_publisher(config: Any = None, validate: bool = True,
                     faults=None):
    """``faults`` (a ``bus/faults.py`` plan or FaultBoundary) is wired
    into drivers with a fault plane (the broker tier); drivers without
    one ignore it — the chaos harness targets the deployment topology
    it actually storms."""
    cfg = dict(config or {})
    driver = cfg.get("driver", "inproc")
    if driver == "inproc":
        pub = InProcPublisher(cfg)
    elif driver in ("broker", "zmq"):   # zmq kept as a config alias
        from copilot_for_consensus_tpu.bus.broker import BrokerPublisher

        pub = BrokerPublisher(cfg, faults=faults)
    elif driver == "azure_servicebus":
        from copilot_for_consensus_tpu.bus.azure_servicebus import (
            AzureServiceBusPublisher,
        )

        pub = AzureServiceBusPublisher(cfg)
    elif driver == "noop":
        pub = NoopPublisher()
    else:
        raise ValueError(f"unknown message_bus driver {driver!r}")
    return ValidatingPublisher(pub) if validate else pub


def create_subscriber(config: Any = None, validate: bool = True,
                      on_invalid=None, faults=None):
    cfg = dict(config or {})
    driver = cfg.get("driver", "inproc")
    if driver == "inproc":
        sub = InProcSubscriber(cfg)
    elif driver in ("broker", "zmq"):
        from copilot_for_consensus_tpu.bus.broker import BrokerSubscriber

        sub = BrokerSubscriber(cfg, faults=faults)
    elif driver == "azure_servicebus":
        from copilot_for_consensus_tpu.bus.azure_servicebus import (
            AzureServiceBusSubscriber,
        )

        sub = AzureServiceBusSubscriber(cfg)
    elif driver == "noop":
        sub = NoopSubscriber()
    else:
        raise ValueError(f"unknown message_bus driver {driver!r}")
    return ValidatingSubscriber(sub, on_invalid=on_invalid) if validate else sub


for _name in ("inproc", "broker", "zmq", "noop", "azure_servicebus"):
    register_driver("message_bus", _name, create_publisher)
