"""Broker-grade inter-process bus: one ROUTER socket, durable queues.

The distributed-bus role the reference fills with RabbitMQ (publisher
confirms ``rabbitmq_publisher.py:146-149``; manual ack + nack-requeue
``rabbitmq_subscriber.py:504-560``; durable pre-declared queues
``infra/rabbitmq/definitions.json``). Design:

* **One broker socket.** All routing keys multiplex over a single ZMQ
  ROUTER endpoint — no per-key ports, no hash collisions (the round-1
  port-hash topology collided 17 keys into 64 ports). Publishers and
  consumers are DEALER clients doing strict request/reply with timeouts.
* **Durable by default.** Every published envelope lands in a sqlite
  (WAL) queue table before the publisher confirm is sent; a broker crash
  or restart loses nothing. In-flight deliveries carry a lease — if a
  consumer dies mid-message, the lease expires and the message requeues.
* **Ack / nack-requeue / DLQ.** Callback success acks; failure nacks and
  requeues with an attempt count; past ``max_redeliveries`` the message
  parks in the dead-letter state, visible to the failed-queues CLI.
* **At-least-once.** Retries on timeouts can duplicate deliveries; the
  pipeline is idempotent end-to-end (deterministic ids, upserts), same
  contract as the reference's bus.

The broker runs embedded (``Broker.start()`` thread) or standalone:
``python -m copilot_for_consensus_tpu.bus.broker --port 5700 --db q.db``.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from typing import Any

from copilot_for_consensus_tpu.bus.base import (
    EventCallback,
    EventPublisher,
    EventSubscriber,
    PublishError,
)

try:
    import zmq

    HAS_ZMQ = True
except ImportError:  # pragma: no cover - environment without pyzmq
    HAS_ZMQ = False

DEFAULT_PORT = 5700
DEFAULT_LEASE_S = 30.0
# Subscribers that don't set a group share one queue per routing key
# (competing consumers) — the reference's one-durable-queue-per-key
# topology. Distinct groups each get every message (service fan-out).
DEFAULT_GROUP = "default"


class _QueueStore:
    """sqlite-backed message queues. One table, state machine per row:
    pending → inflight → (acked | pending | dead).

    Consumer groups (the AMQP binding model, reference
    ``infra/rabbitmq/definitions.json``): a binding is (routing_key,
    group); publish inserts one row per bound group so distinct groups
    each see every message (service fan-out) while consumers sharing a
    group compete (replicas). Messages published before any binding
    exists are parked (``grp=''``) and handed to the first group that
    binds the key."""

    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._lock = threading.Lock()
        with self._lock, self._db:
            self._db.execute("""
                CREATE TABLE IF NOT EXISTS messages (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    rk TEXT NOT NULL,
                    grp TEXT NOT NULL DEFAULT '',
                    envelope TEXT NOT NULL,
                    state TEXT NOT NULL DEFAULT 'pending',
                    attempts INTEGER NOT NULL DEFAULT 0,
                    lease_expires REAL,
                    enqueued_at REAL NOT NULL
                )""")
            try:  # pre-group db files: add the column in place
                self._db.execute(
                    "ALTER TABLE messages ADD COLUMN grp TEXT "
                    "NOT NULL DEFAULT ''")
            except sqlite3.OperationalError:
                pass
            self._db.execute("""
                CREATE TABLE IF NOT EXISTS bindings (
                    rk TEXT NOT NULL,
                    grp TEXT NOT NULL,
                    UNIQUE (rk, grp)
                )""")
            self._db.execute(
                "CREATE INDEX IF NOT EXISTS idx_rk_grp_state "
                "ON messages (rk, grp, state, id)")
            # Broker (re)start: whatever was in flight requeues.
            self._db.execute(
                "UPDATE messages SET state='pending', lease_expires=NULL "
                "WHERE state='inflight'")

    def bind(self, rks: list[str], grp: str) -> None:
        with self._lock, self._db:
            for rk in rks:
                self._db.execute(
                    "INSERT OR IGNORE INTO bindings (rk, grp) VALUES (?, ?)",
                    (rk, grp))
                # Parked pre-bind messages go to the first binder.
                self._db.execute(
                    "UPDATE messages SET grp=? "
                    "WHERE rk=? AND grp='' AND state='pending'", (grp, rk))

    def enqueue(self, rk: str, envelope: str) -> int:
        now = time.time()
        with self._lock, self._db:
            groups = [g for (g,) in self._db.execute(
                "SELECT grp FROM bindings WHERE rk=?", (rk,))]
            last = 0
            for grp in (groups or [""]):
                cur = self._db.execute(
                    "INSERT INTO messages (rk, grp, envelope, enqueued_at) "
                    "VALUES (?, ?, ?, ?)", (rk, grp, envelope, now))
                last = cur.lastrowid
            return last

    def fetch(self, rks: list[str], grp: str, limit: int, lease_s: float
              ) -> list[tuple[int, str, str, int]]:
        """Atomically move up to ``limit`` pending messages (across the
        given keys, within one group) to inflight. Returns
        (id, rk, envelope, attempts)."""
        now = time.time()
        qmarks = ",".join("?" for _ in rks)
        with self._lock, self._db:
            rows = self._db.execute(
                f"SELECT id, rk, envelope, attempts FROM messages "
                f"WHERE state='pending' AND grp=? AND rk IN ({qmarks}) "
                f"ORDER BY id LIMIT ?", (grp, *rks, limit)).fetchall()
            if rows:
                ids = [r[0] for r in rows]
                self._db.execute(
                    f"UPDATE messages SET state='inflight', "
                    f"lease_expires=? WHERE id IN "
                    f"({','.join('?' for _ in ids)})",
                    (now + lease_s, *ids))
            return rows

    def ack(self, ids: list[int]) -> None:
        if not ids:
            return
        with self._lock, self._db:
            self._db.execute(
                f"DELETE FROM messages WHERE id IN "
                f"({','.join('?' for _ in ids)}) AND state='inflight'",
                ids)

    def nack(self, ids: list[int], max_redeliveries: int) -> None:
        if not ids:
            return
        qmarks = ",".join("?" for _ in ids)
        with self._lock, self._db:
            self._db.execute(
                f"UPDATE messages SET attempts=attempts+1, "
                f"lease_expires=NULL, state=CASE WHEN attempts+1 >= ? "
                f"THEN 'dead' ELSE 'pending' END "
                f"WHERE id IN ({qmarks}) AND state='inflight'",
                (max_redeliveries, *ids))

    def expire_leases(self, parked_ttl_s: float = 300.0) -> int:
        with self._lock, self._db:
            cur = self._db.execute(
                "UPDATE messages SET state='pending', lease_expires=NULL "
                "WHERE state='inflight' AND lease_expires < ?",
                (time.time(),))
            # Parked rows (published with no binding) exist only to cover
            # the startup race where a subscriber binds moments later; a
            # key nothing ever binds (e.g. a terminal event with no
            # consumer) must not grow the db forever — AMQP drops
            # unroutable messages outright, we just do it on a delay.
            self._db.execute(
                "DELETE FROM messages WHERE grp='' AND state='pending' "
                "AND enqueued_at < ?", (time.time() - parked_ttl_s,))
            return cur.rowcount

    def counts(self) -> dict[str, dict[str, int]]:
        with self._lock:
            rows = self._db.execute(
                "SELECT rk, state, COUNT(*) FROM messages "
                "GROUP BY rk, state").fetchall()
        out: dict[str, dict[str, int]] = {}
        for rk, state, n in rows:
            out.setdefault(rk, {})[state] = n
        return out

    def dead_letters(self, rk: str | None = None
                     ) -> list[tuple[int, str, str, int]]:
        q = ("SELECT id, rk, envelope, attempts FROM messages "
             "WHERE state='dead'")
        args: tuple = ()
        if rk:
            q += " AND rk=?"
            args = (rk,)
        with self._lock:
            return self._db.execute(q + " ORDER BY id", args).fetchall()

    def requeue_dead(self, rk: str | None = None) -> int:
        q = "UPDATE messages SET state='pending', attempts=0 " \
            "WHERE state='dead'"
        args: tuple = ()
        if rk:
            q += " AND rk=?"
            args = (rk,)
        with self._lock, self._db:
            return self._db.execute(q, args).rowcount

    def purge_dead(self, rk: str | None = None) -> int:
        q = "DELETE FROM messages WHERE state='dead'"
        args: tuple = ()
        if rk:
            q += " AND rk=?"
            args = (rk,)
        with self._lock, self._db:
            return self._db.execute(q, args).rowcount

    def close(self) -> None:
        with self._lock:
            self._db.close()


class Broker:
    """The broker process: ROUTER socket + durable queue store."""

    def __init__(self, port: int = DEFAULT_PORT, db_path: str = ":memory:",
                 host: str = "127.0.0.1", max_redeliveries: int = 3,
                 lease_s: float = DEFAULT_LEASE_S):
        if not HAS_ZMQ:
            raise PublishError("pyzmq is not available")
        self.host = host
        self.port = port
        self.store = _QueueStore(db_path)
        self.max_redeliveries = max_redeliveries
        self.lease_s = lease_s
        self._ctx = zmq.Context.instance()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._bound = threading.Event()

    # ---- request handling -------------------------------------------

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        if op == "pub":
            mid = self.store.enqueue(req["rk"], json.dumps(req["envelope"]))
            return {"ok": True, "id": mid}            # publisher confirm
        if op == "bind":
            self.store.bind(list(req.get("rks", [])),
                            req.get("group", DEFAULT_GROUP))
            return {"ok": True}
        if op == "fetch":
            self.store.expire_leases()
            rows = self.store.fetch(req["rks"],
                                    req.get("group", DEFAULT_GROUP),
                                    int(req.get("max", 16)), self.lease_s)
            return {"ok": True, "msgs": [
                {"id": i, "rk": rk, "envelope": json.loads(env),
                 "attempts": at} for i, rk, env, at in rows]}
        if op == "ack":
            self.store.ack(list(req.get("ids", [])))
            return {"ok": True}
        if op == "nack":
            self.store.nack(list(req.get("ids", [])), self.max_redeliveries)
            return {"ok": True}
        if op == "counts":
            return {"ok": True, "counts": self.store.counts()}
        if op == "dead":
            return {"ok": True, "msgs": [
                {"id": i, "rk": rk, "envelope": json.loads(env),
                 "attempts": at}
                for i, rk, env, at in self.store.dead_letters(
                    req.get("rk"))]}
        if op == "requeue_dead":
            return {"ok": True, "n": self.store.requeue_dead(req.get("rk"))}
        if op == "purge_dead":
            return {"ok": True, "n": self.store.purge_dead(req.get("rk"))}
        if op == "ping":
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    # ---- run loop ----------------------------------------------------

    def run(self) -> None:
        sock = self._ctx.socket(zmq.ROUTER)
        sock.setsockopt(zmq.LINGER, 0)
        if self.port == 0:
            self.port = sock.bind_to_random_port(f"tcp://{self.host}")
        else:
            # A broker restarting right after a crash can race the old
            # socket's TIME_WAIT; retry instead of dying on EADDRINUSE.
            # Deadline stays under start()'s _bound.wait(5) so a failed
            # bind surfaces there rather than binding after the caller
            # already gave up. Non-transient errnos re-raise immediately.
            deadline = time.time() + 4
            while True:
                try:
                    sock.bind(f"tcp://{self.host}:{self.port}")
                    break
                except zmq.ZMQError as exc:
                    if exc.errno != zmq.EADDRINUSE or time.time() > deadline:
                        raise
                    # stop-aware backoff: a broker stopped while waiting
                    # out TIME_WAIT must exit, not finish the bind retry
                    if self._stop.wait(0.2):
                        sock.close()
                        return
        self._bound.set()
        poller = zmq.Poller()
        poller.register(sock, zmq.POLLIN)
        try:
            while not self._stop.is_set():
                if not dict(poller.poll(timeout=100)):
                    continue
                frames = sock.recv_multipart()
                identity, payload = frames[0], frames[-1]
                try:
                    reply = self._handle(json.loads(payload))
                except Exception as exc:   # malformed request
                    reply = {"ok": False, "error": str(exc)}
                sock.send_multipart(
                    [identity, b"", json.dumps(reply).encode()])
        finally:
            sock.close()

    def start(self) -> "Broker":
        self._thread = threading.Thread(target=self.run, name="bus-broker",
                                        daemon=True)
        self._thread.start()
        if not self._bound.wait(timeout=5):
            raise PublishError("broker failed to bind")
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.store.close()

    @property
    def address(self) -> str:
        return f"tcp://{self.host}:{self.port}"


class _Client:
    """One DEALER connection doing strict request/reply with timeouts."""

    def __init__(self, address: str, timeout_ms: int = 5000,
                 retries: int = 3):
        if not HAS_ZMQ:
            raise PublishError("pyzmq is not available")
        self.address = address
        self.timeout_ms = timeout_ms
        self.retries = retries
        self._ctx = zmq.Context.instance()
        self._sock = None
        self._lock = threading.Lock()

    def _connect(self):
        if self._sock is not None:
            self._sock.close(linger=0)
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.connect(self.address)

    def request(self, req: dict) -> dict:
        """Send one request, await the reply. Times out → reconnect and
        retry (at-least-once: a retried 'pub' may duplicate; consumers
        are idempotent by pipeline contract)."""
        with self._lock:
            if self._sock is None:
                self._connect()
            payload = json.dumps(req).encode()
            last = "timeout"
            for _ in range(self.retries):
                self._sock.send_multipart([b"", payload])
                poller = zmq.Poller()
                poller.register(self._sock, zmq.POLLIN)
                if dict(poller.poll(timeout=self.timeout_ms)):
                    frames = self._sock.recv_multipart()
                    reply = json.loads(frames[-1])
                    if not reply.get("ok"):
                        raise PublishError(reply.get("error", "broker nak"))
                    return reply
                self._connect()      # stale socket: drop + reconnect
            raise PublishError(f"broker unreachable at {self.address} "
                               f"({last})")

    def close(self):
        with self._lock:
            if self._sock is not None:
                self._sock.close(linger=0)
                self._sock = None


class BrokerPublisher(EventPublisher):
    """Publishes with broker confirms (the role of RabbitMQ publisher
    confirms, ``rabbitmq_publisher.py:146-149``)."""

    def __init__(self, config: Any = None):
        cfg = dict(config or {})
        address = cfg.get("address") or (
            f"tcp://{cfg.get('host', '127.0.0.1')}:"
            f"{cfg.get('port', DEFAULT_PORT)}")
        self._client = _Client(address,
                               timeout_ms=int(cfg.get("timeout_ms", 5000)))

    def publish_envelope(self, envelope, routing_key=None):
        if routing_key is None:
            from copilot_for_consensus_tpu.core.events import EVENT_TYPES

            cls = EVENT_TYPES.get(envelope.get("event_type", ""))
            routing_key = cls.routing_key if cls else "unrouted"
        self._client.request(
            {"op": "pub", "rk": routing_key, "envelope": dict(envelope)})

    def close(self):
        self._client.close()


class BrokerSubscriber(EventSubscriber):
    """Pull-based consumer: fetch → dispatch → ack/nack per message.
    ``group`` names this consumer's queue group: subscribers sharing a
    group compete (replicas), distinct groups each see every message
    (distinct services) — same contract as ``InProcSubscriber``."""

    def __init__(self, config: Any = None, group: str | None = None):
        cfg = dict(config or {})
        address = cfg.get("address") or (
            f"tcp://{cfg.get('host', '127.0.0.1')}:"
            f"{cfg.get('port', DEFAULT_PORT)}")
        self._address = address
        self._client = _Client(address,
                               timeout_ms=int(cfg.get("timeout_ms", 5000)))
        self.poll_interval_s = float(cfg.get("poll_interval_s", 0.05))
        self.batch = int(cfg.get("batch", 16))
        self.group = group or cfg.get("group") or DEFAULT_GROUP
        self._routes: dict[str, EventCallback] = {}
        self._counts_client: _Client | None = None
        self._stop = threading.Event()

    def subscribe(self, routing_keys, callback):
        for rk in routing_keys:
            self._routes[rk] = callback
        self._client.request({"op": "bind", "rks": list(routing_keys),
                              "group": self.group})

    def counts(self, timeout_ms: int | None = None
               ) -> dict[str, dict[str, int]]:
        """Broker-side per-key state counts (pending/inflight/dead) — the
        ops introspection surface for gauges and the failed-queues CLI.
        ``timeout_ms`` uses a dedicated single-try client so metric
        scrapes fail fast during a broker outage instead of tying up the
        HTTP worker for the full retry budget."""
        if timeout_ms is None:
            return self._client.request({"op": "counts"})["counts"]
        if self._counts_client is None:
            self._counts_client = _Client(self._address,
                                          timeout_ms=timeout_ms, retries=1)
        return self._counts_client.request({"op": "counts"})["counts"]

    def _dispatch(self, msg: dict) -> None:
        cb = self._routes.get(msg["rk"])
        ok = True
        if cb is not None:
            try:
                cb(msg["envelope"])
            except Exception:
                ok = False
        try:
            self._client.request(
                {"op": "ack" if ok else "nack", "ids": [msg["id"]]})
        except PublishError:
            # Broker unreachable: the lease will expire and the message
            # redelivers — at-least-once holds without us crashing.
            pass

    def drain(self, max_messages: int | None = None) -> int:
        """Process what's queued now; returns the number handled."""
        n = 0
        while max_messages is None or n < max_messages:
            want = self.batch if max_messages is None else min(
                self.batch, max_messages - n)
            reply = self._client.request(
                {"op": "fetch", "rks": sorted(self._routes),
                 "group": self.group, "max": want})
            msgs = reply.get("msgs", [])
            if not msgs:
                break
            for m in msgs:
                self._dispatch(m)
                n += 1
        return n

    def start_consuming(self):
        """Consume until stop(); survives broker outages by backing off and
        reconnecting (the reference subscriber's reconnect loop,
        ``rabbitmq_subscriber.py``)."""
        self._stop.clear()
        backoff = self.poll_interval_s
        while not self._stop.is_set():
            try:
                n = self.drain()
            except PublishError:
                self._stop.wait(min(backoff, 5.0))
                backoff = min(backoff * 2, 5.0)
                continue
            backoff = self.poll_interval_s
            if n == 0:
                self._stop.wait(self.poll_interval_s)

    def stop(self):
        self._stop.set()

    def close(self):
        self.stop()
        self._client.close()
        if self._counts_client is not None:
            self._counts_client.close()


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="copilot bus broker")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--db", default=":memory:",
                    help="sqlite path for durable queues")
    ap.add_argument("--max-redeliveries", type=int, default=3)
    ap.add_argument("--lease-s", type=float, default=DEFAULT_LEASE_S)
    args = ap.parse_args(argv)
    broker = Broker(port=args.port, db_path=args.db, host=args.host,
                    max_redeliveries=args.max_redeliveries,
                    lease_s=args.lease_s)
    print(f"broker listening on {broker.address} (db={args.db})",
          flush=True)
    broker.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
